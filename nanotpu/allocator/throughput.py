"""Throughput-aware placement: heterogeneity + contention rater.

The binpack/spread raters treat every chip as interchangeable within a
node — on a mixed v4/v5p fleet they happily park work on the slow
generation while the fast one idles, and on contended fractional cards
they stack shares until everyone time-slices. Gavel ("Heterogeneity-Aware
Cluster Scheduling Policies", PAPERS.md) shows per-(workload x
accelerator-type) effective-throughput models recover double-digit
aggregate throughput, and BandPilot shows the contention penalty can be
*calibrated online* from observed per-card usage — exactly the signal the
metric-sync loop already writes into :mod:`nanotpu.dealer.usage`.

Two pieces:

* :class:`ThroughputModel` — the per-(pod-shape x slice-type)
  effective-throughput table (seedable per-generation defaults, YAML
  override via ``policy.yaml``'s ``throughput:`` section,
  :mod:`nanotpu.policy`) plus the contention calibrator: an EWMA over
  every per-card usage sample the dealer ingests. ``version`` bumps on
  every table reload AND every calibration update — it is the cache
  token :meth:`NodeInfo.assume <nanotpu.dealer.nodeinfo.NodeInfo.assume>`
  folds into its plan-cache key, so a score computed against pre-sync
  usage can never be served after the sync lands (the stale-cached-plan
  window this PR closes).
* :class:`Throughput` — the rater (``priority=throughput``). Its score
  decomposes into three terms the decision ledger records per candidate
  (docs/scoring.md):

  ===============  =====================================================
  base             ``BASE_BAND x (table value / table max)`` — how fast
                   this pod-shape runs on this node's slice type
  contention       ``-CONTENTION_BAND x EWMA(per-card usage)`` — steer
                   away from cards the calibrator has seen hot (falls
                   back to the instantaneous folded load before the
                   first sync)
  fragmentation    ``FRAG_BAND x (whole-free percent / free percent)``
                   — prefer nodes whose free capacity is whole chips
                   (a gang can still land there after us)
  ===============  =====================================================

Score parity contract (fixed-point, ABI 7 — docs/scoring.md): the score
arithmetic is pure INTEGER arithmetic over quantized inputs
(:func:`quantize`, Q16 fixed point), and every consumer runs the same
integer formula (:meth:`Throughput._combine`): the per-node
``NodeInfo.score`` path, the batch row hook
(:meth:`Throughput.batch_score_rows`, consumed by
``BatchScorer.run(score_hook=...)``), the decision-ledger per-term
breakdown, and — since ABI 7 — the NATIVE fused path
(``nanotpu_score_batch``/``nanotpu_score_render`` evaluate the identical
integer formula in C over the model mirror the dealer syncs into the
scoring arena). Fixed point is what makes that native evaluation
bit-deterministic across platforms AND bit-equal to this module — no
float op survives past the quantization edge, so there is no
compiler/FPU freedom left to diverge. Parity is fuzz-pinned by
tests/test_throughput.py. When the native model path is unavailable
(``NANOTPU_NATIVE_MODEL=0``, stale library), the dealer falls back to
the Python row hook and *explicitly refuses* the fused payload path
(counted as ``hook_refusals``), answering through the render-cached list
path: same wire shape, zero view/renderer rebuilds per request.

Determinism: the model draws time only through the injectable ``now``
parameter (``time.time() if now is None else now`` — the sanctioned
injection idiom; the sim passes virtual time end to end), holds one
witness-named lock, and iterates nothing hash-ordered, so the nanolint
sim-determinism pass holds this module to the same contract as the
dealer it feeds.
"""

from __future__ import annotations

import time

from nanotpu import types
from nanotpu.analysis.witness import make_lock

#: Score-band split (sums to SCORE_MAX): how fast the slice type runs
#: this shape / how hot the calibrator has seen its cards / how much of
#: its free capacity is still whole chips.
BASE_BAND = 70
CONTENTION_BAND = 20
FRAG_BAND = 10

#: EWMA smoothing for online contention calibration (BandPilot-style:
#: heavy enough to converge within a few metric-sync ticks, light enough
#: that one noisy sample cannot flip a placement).
DEFAULT_EWMA_ALPHA = 0.3

#: Fraction of a pod's modeled throughput lost per 100% of co-resident
#: share on its cards (the sim's aggregate-throughput metric and the
#: /metrics modeled-aggregate gauge both derate with this).
CONTENTION_LOSS = 0.3

#: Seedable per-generation effective-throughput defaults, normalized to
#: v5p == 1.0 (relative bf16 peak compute per chip: v4 275 TFLOPs, v5p
#: 459, v5e 197, v6e 918 capped into the band). Shape ``"*"`` is the
#: wildcard row; ``policy.yaml`` overrides add (shape, sliceType) rows.
DEFAULT_TABLE: dict[tuple[str, str], float] = {
    ("*", "v5p"): 1.0,
    ("*", "v4"): 0.6,
    ("*", "v5e"): 0.43,
    ("*", "v6e"): 1.0,
}

#: table value when neither the (shape, generation) row nor the
#: generation wildcard exists: schedule load-blind, never crash
FALLBACK_VALUE = 0.5

#: Fixed-point quantization (docs/scoring.md, ABI 7): every fractional
#: score input — the base fraction, each per-card contention EWMA, each
#: instantaneous per-card load — is quantized to Q16 (``value * 2**16``
#: rounded, clamped to [0, Q_ONE]) at the float/int edge, and ALL
#: downstream arithmetic is integer. 16 fraction bits resolve 1/65536 ≈
#: 0.0015% of a band — far below the 1-point score granularity — while
#: keeping every intermediate product (band × sum of ≤64 quantized
#: cards) comfortably inside int64 for the C evaluation.
Q_BITS = 16
Q_ONE = 1 << Q_BITS


def quantize(fraction: float) -> int:
    """Quantize a [0, 1] fraction to Q16 (out-of-range inputs clamp).
    THE float→int edge of the scoring formula: Python and C never see
    the same value disagree because past this point there are no
    floats left to round differently."""
    return min(Q_ONE, max(0, round(fraction * Q_ONE)))


def shape_of(demand) -> str:
    """Canonical pod-shape key for the throughput table: the non-zero
    per-container percents, largest first — ``"400"``, ``"100/100"``,
    ``"20"``. Stable across container ordering."""
    parts = sorted((p for p in demand.percents if p > 0), reverse=True)
    return "/".join(str(p) for p in parts) or "0"


class ThroughputModel:
    """Effective-throughput table + online contention calibrator.

    Thread-safe: ``observe`` lands from the metric-sync thread while
    verbs read; every mutation bumps ``version`` (the plan-cache token).
    """

    def __init__(self, table: dict | None = None,
                 alpha: float = DEFAULT_EWMA_ALPHA):
        self._lock = make_lock("ThroughputModel._lock")
        self.alpha = float(alpha)
        self._table: dict[tuple[str, str], float] = dict(
            table if table is not None else DEFAULT_TABLE
        )
        self._norm = max(self._table.values(), default=1.0) or 1.0
        #: node -> chip -> EWMA of observed usage (the calibration state)
        self._ewma: dict[str, dict[int, float]] = {}
        #: node -> last observe() timestamp (gauge: calibration age)
        self._updated_at: dict[str, float] = {}
        self._last_update: float | None = None
        #: bumped on EVERY state change (table reload, calibration
        #: sample): NodeInfo folds it into the plan-cache key so cached
        #: plans version out instead of serving pre-sync scores
        self.version = 0

    # -- table -------------------------------------------------------------
    def configure(self, spec) -> None:
        """Apply a :class:`nanotpu.policy.ThroughputSpec` (``policy.yaml``
        override): replaces matching (shape, sliceType) rows on top of
        the seed defaults and retunes the EWMA alpha. Idempotent; bumps
        ``version`` so every cached plan re-scores."""
        if spec is None:
            return
        with self._lock:
            if spec.alpha is not None:
                self.alpha = float(spec.alpha)
            for entry in spec.entries:
                self._table[(entry.shape, entry.slice_type)] = float(
                    entry.value
                )
            self._norm = max(self._table.values(), default=1.0) or 1.0
            self.version += 1

    def effective(self, shape: str, generation: str) -> float:
        """Raw table value for (shape, generation): exact row, then the
        generation wildcard, then the load-blind fallback."""
        table = self._table
        v = table.get((shape, generation))
        if v is None:
            v = table.get(("*", generation))
        return FALLBACK_VALUE * self._norm if v is None else v

    def base_fraction(self, shape: str, generation: str) -> float:
        """``effective / table max`` in (0, 1] — the base-term scaler."""
        return min(1.0, self.effective(shape, generation) / self._norm)

    def base_q(self, shape: str, generation: str) -> int:
        """Quantized (Q16) base fraction — the integer the score formula
        actually consumes (docs/scoring.md fixed-point contract)."""
        return quantize(self.base_fraction(shape, generation))

    def base_q_for(self, demand, generations) -> list[int]:
        """Quantized base fractions for one demand across a view's
        generation list — the per-call table resolution the native path
        needs (O(#generations) dict lookups in Python; the per-ROW
        indirection happens in C via the view's generation indices).
        Iterates the caller's list: no hash-order dependence."""
        shape = shape_of(demand)
        return [quantize(self.base_fraction(shape, g)) for g in generations]

    # -- online contention calibration ------------------------------------
    def observe(self, node: str, chip: int, load: float,
                now: float | None = None) -> None:
        """Fold one observed per-card usage sample (the same value the
        dealer writes into ``ChipResource.load``) into the card's EWMA.
        Called by ``Dealer.update_chip_usage`` on every metric-sync
        write; ``now`` is the injectable clock (virtual time in-sim)."""
        ts = time.time() if now is None else now
        load = max(0.0, min(1.0, load))
        with self._lock:
            per_node = self._ewma.setdefault(node, {})
            prev = per_node.get(chip)
            per_node[chip] = (
                load if prev is None
                else prev + self.alpha * (load - prev)
            )
            self._updated_at[node] = ts
            self._last_update = ts
            self.version += 1

    def contention(self, node: str) -> float | None:
        """Mean per-card EWMA for the node in [0, 1]; None before the
        first calibration sample. Introspection/test surface ONLY — the
        scoring paths consume :meth:`contention_q` (the quantized
        integers), never this float."""
        with self._lock:
            per_node = self._ewma.get(node)
            if not per_node:
                return None
            return sum(per_node.values()) / len(per_node)

    @staticmethod
    def _q_entry(per_node: dict[int, float]) -> tuple[int, int]:
        """``(sum of per-card Q16 EWMAs, card count)`` for one node's
        calibration dict (caller holds the lock). THE quantize-then-sum
        rule — never sum-then-quantize — in exactly one place: the
        mirror, the hook, and the per-node path all feed the fixed-point
        formula integers produced by this body, which is what keeps them
        bit-equal to each other and to the C evaluation."""
        return sum(quantize(v) for v in per_node.values()), len(per_node)

    def contention_q(self, node: str) -> tuple[int, int] | None:
        """Quantized contention state for one node (:meth:`_q_entry`) —
        the exact integers the fixed-point formula divides. None before
        the first calibration sample (callers fall back to quantized
        instantaneous load)."""
        with self._lock:
            per_node = self._ewma.get(node)
            if not per_node:
                return None
            return self._q_entry(per_node)

    def _collect_q_locked(self, nodes) -> dict[str, tuple[int, int]]:
        """:meth:`_q_entry` per calibrated node (caller holds the
        lock). Nodes without calibration are absent (callers fall back
        to quantized instantaneous load). Iterates the caller's list,
        so the result order carries no hash-order dependence."""
        out: dict[str, tuple[int, int]] = {}
        for n in nodes:
            per_node = self._ewma.get(n)
            if per_node:
                out[n] = self._q_entry(per_node)
        return out

    def contention_q_many(self, nodes) -> dict[str, tuple[int, int]]:
        """:meth:`contention_q` for many nodes under ONE lock hold —
        the batch row hook scores hundreds of candidates per verb while
        holding the view arena lock, and a per-candidate lock
        round-trip there contends with the metric-sync writer."""
        with self._lock:
            return self._collect_q_locked(nodes)

    def mirror_snapshot(
        self, nodes
    ) -> tuple[int, dict[str, tuple[int, int]]]:
        """``(version, {node: (Q16 EWMA sum, card count)})`` captured
        under ONE lock hold — the copy-on-write source for the scoring
        arena's model mirror (nanotpu.dealer.batch). Capturing the
        version INSIDE the same critical section as the state is what
        makes the mirror's version stamp honest: a concurrent
        ``observe`` either lands before the capture (and is in both) or
        after (and bumps ``version`` past the stamp, retiring the
        mirror on the next read)."""
        with self._lock:
            return self.version, self._collect_q_locked(nodes)

    def forget_node(self, node: str) -> None:
        with self._lock:
            self._ewma.pop(node, None)
            self._updated_at.pop(node, None)
            self.version += 1

    # -- gauges (nanotpu_sched_throughput_*, docs/scoring.md) --------------
    def calibration_age_s(self, now: float | None = None) -> float:
        """Seconds since the newest calibration sample; -1 before the
        first (a forever-growing age and a never-calibrated model must
        read differently on a dashboard)."""
        ts = time.time() if now is None else now
        with self._lock:
            if self._last_update is None:
                return -1.0
            return max(0.0, ts - self._last_update)

    def calibrated_nodes(self) -> int:
        with self._lock:
            return len(self._ewma)

    def gauge_values(self, now: float | None = None) -> dict[str, float]:
        """The unlabeled ``nanotpu_sched_throughput_*`` gauge values,
        keyed by metric suffix. The nanolint metrics-completeness pass
        cross-checks these keys against the exporter's declared
        ``_THROUGHPUT_GAUGES`` table BOTH directions — a suffix produced
        here but never exported (or declared there but never produced)
        is a lint finding."""
        return {
            "calibration_age_seconds": self.calibration_age_s(now),
            "calibrated_nodes": float(self.calibrated_nodes()),
            "table_rows": float(len(self._table)),
        }


class Throughput:
    """The ``priority=throughput`` rater (docs/scoring.md).

    Placement (``choose``) packs whole-chip demands like binpack
    (contiguity preserves ICI for gangs) but SPREADS fractional demands
    across cards — co-residency is exactly the contention the model
    penalizes, so stacking shares while scoring against stacking would
    fight itself. Node ranking is the three-term model score; the plan's
    score IS ``rate`` (no plan-local compactness bonus) so the per-node,
    batch-hook, and ledger-breakdown views of a score are one number.
    """

    name = types.POLICY_THROUGHPUT

    def __init__(self, model: ThroughputModel | None = None):
        self.model = model or ThroughputModel()

    # -- dealer integration hooks ------------------------------------------
    def cache_token(self) -> int:
        """Plan-cache version key (see NodeInfo.assume): any model state
        change — a calibration sample, a table reload — retires every
        plan cached under the previous token."""
        return self.model.version

    def native_model(self):
        """Duck-typed dealer hook (ABI 7, docs/scoring.md): expose the
        model so the dealer can mirror its quantized state into the
        scoring arena and evaluate the fixed-point formula inside
        ``nanotpu_score_batch``/``nanotpu_score_render`` — the same
        integer arithmetic as :meth:`_combine`, bit-equal by
        construction."""
        return self.model

    def observe_usage(self, node: str, chip: int, load: float,
                      now: float | None = None) -> None:
        """Dealer.update_chip_usage forwards every per-card usage write
        here — the online-calibration tap."""
        self.model.observe(node, chip, load, now=now)

    def forget_node(self, node: str) -> None:
        self.model.forget_node(node)

    def configure(self, spec) -> None:
        self.model.configure(spec)

    # -- the one scoring formula -------------------------------------------
    @staticmethod
    def _combine(base_q: int, cont: tuple[int, int] | None,
                 free, total, load_q) -> dict[str, int]:
        """The term arithmetic, shared verbatim by every caller — this
        single body is what makes the per-node path, the batch row
        hook, the ledger breakdown, AND the native C evaluation
        (allocator.cc ``model_score``) bit-equal. Pure integer
        arithmetic over quantized inputs (docs/scoring.md):

        * ``base_q`` — Q16 base fraction (:meth:`ThroughputModel.base_q`)
        * ``cont`` — ``(Q16 EWMA sum, card count)`` or None;
          None means uncalibrated: fall back to the node's quantized
          instantaneous per-card loads (``load_q`` — identical values
          in a ChipSet and in the batch rows copied from it)
        * ``free``/``total`` — raw integer chip percents

        Every division is floor division of non-negative integers —
        exactly C's truncating ``/`` on the same operands, which is the
        whole parity argument. Change NOTHING here without changing
        allocator.cc in lockstep (the fuzz pin in tests/test_throughput
        will catch you)."""
        if cont is None:
            cont_sum, cont_n = sum(load_q), len(load_q)
        else:
            cont_sum, cont_n = cont
        contention = (
            (CONTENTION_BAND * cont_sum) // (cont_n * Q_ONE)
            if cont_n else 0
        )
        free_pct = sum(free)
        whole_free = sum(
            f for f, t in zip(free, total) if f == t and t > 0
        )
        frag = (FRAG_BAND * whole_free) // free_pct if free_pct else 0
        base = (BASE_BAND * base_q) // Q_ONE
        total_score = max(
            types.SCORE_MIN,
            min(types.SCORE_MAX, base - contention + frag),
        )
        return {
            "base": base,
            "contention": -contention,
            "fragmentation": frag,
            "total": total_score,
        }

    def _score_terms(self, generation: str, node_key: str,
                     free, total, load, demand) -> dict[str, int]:
        """Per-term score breakdown from raw per-chip state (the
        one-candidate adapter over :meth:`_combine`; ``load`` is the
        raw float per-card loads, quantized here at the formula's
        float/int edge)."""
        model = self.model
        return self._combine(
            model.base_q(shape_of(demand), generation),
            model.contention_q(node_key),
            free, total, [quantize(v) for v in load],
        )

    def _terms_of(self, chips, demand) -> dict[str, int]:
        return self._score_terms(
            chips.torus.generation, chips.key,
            [c.percent_free for c in chips.chips],
            [c.percent_total for c in chips.chips],
            [c.load for c in chips.chips],
            demand,
        )

    # -- Rater protocol ----------------------------------------------------
    def rate(self, chips, demand) -> int:
        return self._terms_of(chips, demand)["total"]

    def rate_terms(self, chips, demand) -> dict[str, int]:
        """The per-term breakdown the decision ledger records for every
        scored candidate (docs/scoring.md: how the ledger proves WHY a
        pod moved)."""
        return self._terms_of(chips, demand)

    def choose(self, chips, demand):
        from nanotpu.allocator.rater import Plan, _choose

        has_fractional = any(
            0 < p < types.PERCENT_PER_CHIP for p in demand.percents
        )
        assignments = _choose(chips, demand, prefer_used=not has_fractional)
        if assignments is None:
            return None
        # plan.score == rate: one number across the per-node path, the
        # batch hook, and the ledger breakdown (no plan-local bonus)
        return Plan(
            demand=demand, assignments=assignments,
            score=self.rate(chips, demand),
        )

    # -- batch row hook (BatchScorer.run(score_hook=...)) ------------------
    def batch_score_rows(self, scorer, demand, feasible) -> list[int]:
        """Python-side scores over a frozen BatchScorer's row arrays:
        the same :meth:`_combine` arithmetic the per-node path runs,
        over the same free/total/load values (rows are copies of
        exactly that state). Infeasible rows score SCORE_MIN, like the
        per-node path's infeasible verdict.

        Loop-invariant work is hoisted: the shape key + per-generation
        quantized base fraction compute once per call, and every
        candidate's quantized contention state snapshots under ONE
        model-lock hold (:meth:`ThroughputModel.contention_q_many`) —
        this loop runs under the view's arena lock at fan-out sizes, and
        per-candidate lock round-trips there would contend with the
        metric-sync writer. The uncalibrated fallback reads the view's
        pre-quantized ``load_q`` rows — the SAME integers the native
        mirror path consumes, which is what keeps hook and native
        bit-equal."""
        model = self.model
        shape = shape_of(demand)
        base_by_gen: dict[str, int] = {}
        cont_map = model.contention_q_many(
            [info.name for info in scorer.infos]
        )
        c = scorer.chip_count
        out: list[int] = []
        for i, info in enumerate(scorer.infos):
            if not feasible[i]:
                out.append(types.SCORE_MIN)
                continue
            base_q = base_by_gen.get(info.generation)
            if base_q is None:
                base_q = base_by_gen[info.generation] = (
                    model.base_q(shape, info.generation)
                )
            row = i * c
            out.append(self._combine(
                base_q,
                cont_map.get(info.name),
                scorer.free[row:row + c],
                scorer.total[row:row + c],
                scorer.load_q[row:row + c],
            )["total"])
        return out


# -- modeled aggregate throughput (sim report + /metrics gauge) ------------

def pod_modeled_throughput(pod, info, model: ThroughputModel) -> float:
    """One bound pod's modeled throughput: the (shape x slice-type)
    table value derated by co-residency on its assigned cards —
    ``1 - CONTENTION_LOSS x (co-resident share / 100)`` per card,
    averaged over the pod's cards. 0.0 when the pod's chip annotations
    are missing/corrupt (unaccountable work models as nothing)."""
    from nanotpu.allocator.core import Demand
    from nanotpu.utils import pod as podutil

    assigned = podutil.get_assigned_chips(pod)
    if not assigned:
        return 0.0
    demand = Demand.from_pod(pod)
    value = model.effective(shape_of(demand), info.generation)
    by_name = dict(
        zip(demand.container_names, demand.percents)
    )
    eff_sum, n_chips = 0.0, 0
    for cname in sorted(assigned):
        chip_ids = assigned[cname]
        percent = by_name.get(cname, 0)
        if not chip_ids or percent <= 0:
            continue
        own = (
            types.PERCENT_PER_CHIP
            if percent >= types.PERCENT_PER_CHIP else percent
        )
        for chip_id in chip_ids:
            if not 0 <= chip_id < len(info.chips.chips):
                continue
            used = info.chips.chips[chip_id].percent_used
            others = max(0, used - own)
            eff_sum += 1.0 - CONTENTION_LOSS * (
                others / types.PERCENT_PER_CHIP
            )
            n_chips += 1
    if n_chips == 0:
        return 0.0
    return value * (eff_sum / n_chips)


def modeled_aggregate(node_infos: dict, pods: list,
                      model: ThroughputModel | None = None) -> dict:
    """Fleet-wide modeled throughput for a set of bound pods, plus the
    oracle bound (every pod on its best slice type, uncontended) — the
    sim report's ``throughput`` section and the certification metric
    for the het-throughput scenarios (docs/scoring.md). Deterministic:
    pods iterate in sorted-name order, floats round at the edge."""
    from nanotpu.allocator.core import Demand

    model = model or ThroughputModel()
    generations = sorted({
        info.generation for info in node_infos.values()
    })
    total = 0.0
    oracle = 0.0
    by_gen: dict[str, float] = {}
    n = 0
    for pod in sorted(pods, key=lambda p: (p.name, p.uid)):
        info = node_infos.get(pod.node_name)
        if info is None:
            continue
        tput = pod_modeled_throughput(pod, info, model)
        if tput <= 0.0:
            continue
        n += 1
        total += tput
        by_gen[info.generation] = by_gen.get(info.generation, 0.0) + tput
        shape = shape_of(Demand.from_pod(pod))
        oracle += max(
            (model.effective(shape, g) for g in generations),
            default=0.0,
        )
    loss_pct = (
        round(100.0 * (oracle - total) / oracle, 2) if oracle else 0.0
    )
    return {
        "pods": n,
        "aggregate": round(total, 4),
        "oracle": round(oracle, 4),
        "loss_vs_oracle_pct": loss_pct,
        "by_generation": {g: round(by_gen[g], 4) for g in sorted(by_gen)},
    }
