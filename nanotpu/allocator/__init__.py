from nanotpu.allocator.core import ChipResource, ChipSet, Demand, Plan
from nanotpu.allocator.rater import (
    Binpack,
    Random,
    Rater,
    Sample,
    Spread,
    clamp_score,
    make_rater,
)

__all__ = [
    "ChipResource",
    "ChipSet",
    "Demand",
    "Plan",
    "Binpack",
    "Spread",
    "Random",
    "Sample",
    "Rater",
    "clamp_score",
    "make_rater",
]
