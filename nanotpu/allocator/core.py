"""Allocation primitives: ChipResource, Demand, Plan, ChipSet.

Rebuild of ``pkg/dealer/allocate.go`` with two structural changes:

* chips live on an ICI torus (:class:`nanotpu.topology.Torus`) instead of a
  flat array (``GPUs []*GPUResource``, allocate.go:90), so multi-chip
  containers receive *contiguous sub-boxes* and plans carry a compactness
  score;
* a container may span several chips: demands > 100 percent mean whole
  chips (400 == a 2x2x1 sub-box), so Plan maps container -> chip id list
  rather than container -> single card index (allocate.go:22-27).

The rollback path in :meth:`ChipSet.allocate` restores exactly the chips it
touched — the reference restored ``plan.Demand[i]`` onto the *wrong* index
while unwinding (allocate.go:110-112), corrupting card accounting; we keep an
undo log instead.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from functools import lru_cache

from nanotpu import types
from nanotpu.topology import Torus


@lru_cache(maxsize=64)
def _torus_for(chip_count: int, topology_spec: str,
               generation: str) -> Torus:
    """Shared Torus per (count, topology, generation) — a fleet has a
    handful of shapes, and the warm-restart path resolves one per node.
    Tori are immutable, so sharing is safe (ChipSet already relies on
    that: ``for_node`` instances share cached spec parses)."""
    if topology_spec:
        torus = Torus.from_spec(topology_spec, generation)
        if torus.num_chips != chip_count:
            torus = Torus((chip_count, 1, 1), generation)
        return torus
    return Torus((chip_count, 1, 1), generation)


@dataclass
class ChipResource:
    """One TPU chip's fractional capacity (GPUResource, allocate.go:141-145).

    ``percent_free`` in [0, percent_total]; ``load`` is the live utilization
    in [0, 1] folded in from the metrics pipeline (RemainLoad analogue,
    allocate.go:173-195) — 0 when load-aware scheduling is off or stale.
    ``hbm_*_mib`` is the second scheduled dimension (north-star resource
    model); ``hbm_total_mib == 0`` means HBM is untracked on this chip and
    every HBM request is accepted unaccounted.
    """

    percent_free: int = types.PERCENT_PER_CHIP
    percent_total: int = types.PERCENT_PER_CHIP
    load: float = 0.0
    hbm_free_mib: int = 0
    hbm_total_mib: int = 0

    @property
    def percent_used(self) -> int:
        return self.percent_total - self.percent_free

    def can_allocate(self, percent: int, hbm_mib: int = 0) -> bool:
        if not 0 <= percent <= self.percent_free:
            return False
        if hbm_mib <= 0 or self.hbm_total_mib == 0:
            return True
        return hbm_mib <= self.hbm_free_mib

    def sub(self, percent: int, hbm_mib: int = 0) -> None:
        if not self.can_allocate(percent, hbm_mib):
            raise ValueError(
                f"cannot allocate {percent}% / {hbm_mib} MiB from chip with "
                f"{self.percent_free}% / {self.hbm_free_mib} MiB free"
            )
        self.percent_free -= percent
        if self.hbm_total_mib:
            self.hbm_free_mib -= max(hbm_mib, 0)

    def add(self, percent: int, hbm_mib: int = 0) -> None:
        if percent < 0 or self.percent_free + percent > self.percent_total:
            raise ValueError(
                f"cannot release {percent}% onto chip with {self.percent_free}%/"
                f"{self.percent_total}%"
            )
        if self.hbm_total_mib and (
            hbm_mib < 0 or self.hbm_free_mib + hbm_mib > self.hbm_total_mib
        ):
            raise ValueError(
                f"cannot release {hbm_mib} MiB onto chip with "
                f"{self.hbm_free_mib}/{self.hbm_total_mib} MiB"
            )
        self.percent_free += percent
        if self.hbm_total_mib:
            self.hbm_free_mib += max(hbm_mib, 0)


@dataclass(frozen=True)
class Demand:
    """Per-container chip-percent request vector (allocate.go:52-75).

    Built from container limits in pod order; zero-request containers keep a
    0 entry so Plan indexes align with containers.
    """

    percents: tuple[int, ...]
    container_names: tuple[str, ...] = ()
    #: per-container HBM MiB reserved ON EACH assigned chip; empty tuple ==
    #: no HBM requests (keeps old constructors and plan-cache hashes valid)
    hbm_mib: tuple[int, ...] = ()

    @staticmethod
    def from_pod(pod) -> "Demand":
        """Demand vector from container limits, memoized on the Pod object:
        every verb re-derives the demand, and quantity parsing across the
        containers is a measurable slice of a 256-host scheduling cycle.
        Safe because container resource limits are immutable for a pod's
        lifetime (the annotation writes at bind touch metadata only)."""
        cached = getattr(pod, "_demand_memo", None)
        if cached is not None:
            return cached
        from nanotpu.utils import pod as podutil

        containers = pod.containers
        hbm = tuple(c.limit(types.RESOURCE_TPU_HBM) for c in containers)
        demand = Demand(
            percents=tuple(
                podutil.get_tpu_percent_from_container(c) for c in containers
            ),
            container_names=tuple(c.name for c in containers),
            hbm_mib=hbm if any(hbm) else (),
        )
        try:
            pod._demand_memo = demand
        except AttributeError:  # slotted/foreign pod-like object
            pass
        return demand

    def hbm_of(self, i: int) -> int:
        return self.hbm_mib[i] if i < len(self.hbm_mib) else 0

    @property
    def total(self) -> int:
        return sum(self.percents)

    def whole_chips(self, i: int) -> int:
        """Whole chips demanded by container i (0 for fractional demands)."""
        p = self.percents[i]
        return p // types.PERCENT_PER_CHIP if p >= types.PERCENT_PER_CHIP else 0

    def is_valid(self) -> bool:
        """Multi-chip demands must be whole multiples of one chip — '250%'
        has no placement semantics on TPU (no MIG/MPS analogue)."""
        if self.hbm_mib and (
            len(self.hbm_mib) != len(self.percents)
            or any(h < 0 for h in self.hbm_mib)
        ):
            return False
        return all(
            p >= 0
            and (p <= types.PERCENT_PER_CHIP or p % types.PERCENT_PER_CHIP == 0)
            for p in self.percents
        )

    def hash(self) -> str:
        """Plan-cache key: first 8 hex chars of sha256 (allocate.go:72-75).

        Memoized on the instance — Assume/Score call this once per
        candidate node (256x per verb on a large pool), and even a
        cache lookup plus tuple coercion showed up in profiles."""
        h = getattr(self, "_hash", None)
        if h is None:
            # tuple() coercion: callers may construct Demand with list
            # fields (the frozen dataclass doesn't coerce)
            h = _demand_hash(
                tuple(self.container_names), tuple(self.percents),
                tuple(self.hbm_mib),
            )
            object.__setattr__(self, "_hash", h)  # frozen dataclass memo
        return h


def _demand_hash(container_names: tuple[str, ...], percents: tuple[int, ...],
                 hbm_mib: tuple[int, ...] = ()) -> str:
    payload = ",".join(
        f"{n}={p}" for n, p in zip(container_names, percents)
    ) or ",".join(str(p) for p in percents)
    if any(hbm_mib):  # keep pre-HBM hashes stable for HBM-less demands
        payload += "|hbm:" + ",".join(str(h) for h in hbm_mib)
    return hashlib.sha256(payload.encode()).hexdigest()[:8]


@dataclass
class Plan:
    """A placement decision for one pod on one node (allocate.go:22-27).

    ``assignments[i]`` is the chip id list for container i (empty == no TPU).
    """

    demand: Demand
    assignments: list[list[int]]
    score: int = 0
    compactness: float = 1.0

    def by_container_name(self) -> dict[str, list[int]]:
        names = self.demand.container_names or tuple(
            str(i) for i in range(len(self.assignments))
        )
        return {n: chips for n, chips in zip(names, self.assignments)}


class ChipSet:
    """All chips of one node on their local torus (GPUs, allocate.go:88-131)."""

    def __init__(self, torus: Torus, chips: list[ChipResource] | None = None, key: str = ""):
        #: stable identity (node name) for deterministic tie-breaking
        self.key = key
        self.torus = torus
        self.chips: list[ChipResource] = (
            chips if chips is not None else [ChipResource() for _ in range(torus.num_chips)]
        )
        if len(self.chips) != torus.num_chips:
            raise ValueError(
                f"{len(self.chips)} chips for torus {torus.dims} "
                f"({torus.num_chips} positions)"
            )

    @staticmethod
    def for_node(chip_count: int, topology_spec: str | None = None, generation: str = "v5p") -> "ChipSet":
        """Build from node capacity (NewNodeInfo path, node.go:25-42).
        Per-chip HBM capacity comes from the generation table, making
        ``tpu.io/hbm-mib`` a real scheduled dimension on known hardware."""
        if topology_spec:
            torus = Torus.from_spec(topology_spec, generation)
            if torus.num_chips != chip_count:
                # label disagrees with capacity: trust capacity, fall back flat
                torus = Torus((chip_count, 1, 1), generation)
        else:
            torus = Torus((chip_count, 1, 1), generation)
        hbm = types.HBM_MIB_PER_CHIP.get(generation, 0)
        return ChipSet(
            torus,
            [
                ChipResource(hbm_free_mib=hbm, hbm_total_mib=hbm)
                for _ in range(torus.num_chips)
            ],
        )

    @staticmethod
    def restore(chip_count: int, topology_spec: str | None,
                generation: str, rows: list) -> "ChipSet":
        """Rebuild from checkpointed per-chip state (docs/ha.md warm
        restart): ``rows`` = ``[percent_free, percent_total,
        hbm_free_mib, hbm_total_mib, load]`` per chip, exactly what
        :meth:`chip_rows` wrote. Bypasses the dataclass constructor —
        the restart path builds tens of thousands of chips and the
        field-by-field ``__init__`` was a measured quarter of the whole
        warm boot."""
        torus = _torus_for(chip_count, topology_spec or "", generation)
        chips: list[ChipResource] = []
        for free, total, hbm_free, hbm_total, load in rows:
            c = ChipResource.__new__(ChipResource)
            c.percent_free = free
            c.percent_total = total
            c.load = load
            c.hbm_free_mib = hbm_free
            c.hbm_total_mib = hbm_total
            chips.append(c)
        return ChipSet(torus, chips)

    def chip_rows(self) -> list[list]:
        """Checkpoint serialization of per-chip state (see
        :meth:`restore`)."""
        return [
            [c.percent_free, c.percent_total, c.hbm_free_mib,
             c.hbm_total_mib, round(c.load, 6)]
            for c in self.chips
        ]

    def __len__(self) -> int:
        return len(self.chips)

    def whole_free_indexes(self) -> frozenset:
        """Indexes of fully-free chips — THE "whole free" definition
        shared by the defragmenter's gain rule (nanotpu/recovery/plane.py),
        the dealer's telemetry tap, and the fleet fragmentation walk
        (nanotpu/dealer/frag.py), so the three can never silently
        disagree on what counts as free."""
        return frozenset(
            i for i, c in enumerate(self.chips)
            if c.percent_free == c.percent_total
        )

    def whole_free(self) -> int:
        """Fully-free chip count (see :meth:`whole_free_indexes`)."""
        return len(self.whole_free_indexes())

    # -- feasibility -------------------------------------------------------
    def can_fit(self, demand: Demand) -> bool:
        """Cheap OPTIMISTIC pre-filter using only *necessary* conditions —
        it must never reject a demand choose() could place (a false negative
        here strands pods Pending with capacity available), and may accept
        demands choose() then rejects (connectivity, packing). choose() is
        the feasibility authority."""
        if not demand.is_valid():
            return False
        free = [c.percent_free for c in self.chips]
        if demand.total > sum(free):
            return False
        whole = sum(demand.whole_chips(i) for i in range(len(demand.percents)))
        fulls = sum(1 for f in free if f == types.PERCENT_PER_CHIP)
        if whole > fulls:
            return False
        # the largest fractional demand needs SOME chip with that headroom
        max_frac = max(
            (p for p in demand.percents if 0 < p < types.PERCENT_PER_CHIP),
            default=0,
        )
        if max_frac and max(free, default=0) < max_frac:
            return False
        # HBM (optimistic): each TPU-demanding container needs SOME chip
        # with its per-chip HBM request free (only on HBM-tracked chips)
        if demand.hbm_mib:
            max_hbm_free = max(
                (
                    c.hbm_free_mib if c.hbm_total_mib else float("inf")
                    for c in self.chips
                ),
                default=0,
            )
            for i, p in enumerate(demand.percents):
                if p > 0 and demand.hbm_of(i) > max_hbm_free:
                    return False
        return True

    # -- mutation with undo log (fixes allocate.go:110-112 rollback bug) ---
    def allocate(self, plan: Plan) -> None:
        undo: list[tuple[int, int, int]] = []
        try:
            for i, chips in enumerate(plan.assignments):
                percent = plan.demand.percents[i]
                hbm = plan.demand.hbm_of(i)  # per assigned chip
                if not chips:
                    continue
                per_chip = self._per_chip_split(percent, len(chips))
                for chip_id, p in zip(chips, per_chip):
                    self.chips[chip_id].sub(p, hbm)
                    undo.append((chip_id, p, hbm))
        except (ValueError, IndexError):
            for chip_id, p, h in reversed(undo):
                self.chips[chip_id].add(p, h)
            raise

    def release(self, plan: Plan) -> None:
        undo: list[tuple[int, int, int]] = []
        try:
            for i, chips in enumerate(plan.assignments):
                percent = plan.demand.percents[i]
                hbm = plan.demand.hbm_of(i)
                if not chips:
                    continue
                per_chip = self._per_chip_split(percent, len(chips))
                for chip_id, p in zip(chips, per_chip):
                    self.chips[chip_id].add(p, hbm)
                    undo.append((chip_id, p, hbm))
        except (ValueError, IndexError):
            for chip_id, p, h in reversed(undo):
                self.chips[chip_id].sub(p, h)
            raise

    @staticmethod
    def _per_chip_split(percent: int, n_chips: int) -> list[int]:
        """How a container's percent lands on its chips: whole demands put
        100 on each chip; fractional demands live on a single chip."""
        if n_chips == 0:
            return []
        if percent >= types.PERCENT_PER_CHIP:
            if percent != n_chips * types.PERCENT_PER_CHIP:
                raise ValueError(
                    f"whole-chip demand {percent}% does not match {n_chips} chips"
                )
            return [types.PERCENT_PER_CHIP] * n_chips
        if n_chips != 1:
            raise ValueError(f"fractional demand {percent}% must land on one chip")
        return [percent]

    # -- aggregate stats (allocate.go:164-223) ----------------------------
    def percent_used(self) -> int:
        return sum(c.percent_used for c in self.chips)

    def percent_total(self) -> int:
        return sum(c.percent_total for c in self.chips)

    def usage(self) -> float:
        total = self.percent_total()
        return self.percent_used() / total if total else 0.0

    def available_percent_and_free_chips(self) -> tuple[int, int]:
        avail = sum(c.percent_free for c in self.chips)
        free = sum(
            1 for c in self.chips if c.percent_free == c.percent_total
        )
        return avail, free

    def usage_variance(self) -> float:
        """Variance of per-chip usage fraction (allocate.go:205-223)."""
        if not self.chips:
            return 0.0
        fracs = [
            c.percent_used / c.percent_total if c.percent_total else 0.0
            for c in self.chips
        ]
        mean = sum(fracs) / len(fracs)
        return sum((f - mean) ** 2 for f in fracs) / len(fracs)

    def snapshot(self) -> list[dict]:
        """Debug/status view (PrintStatus analogue, dealer.go:303-309)."""
        return [
            {
                "chip": i,
                "coord": self.torus.coord(i),
                "free": c.percent_free,
                "total": c.percent_total,
                "load": round(c.load, 4),
                "hbm_free_mib": c.hbm_free_mib,
                "hbm_total_mib": c.hbm_total_mib,
            }
            for i, c in enumerate(self.chips)
        ]
