"""Placement policies: Binpack, Spread, Random, Sample.

Rebuild of ``pkg/dealer/rater.go`` with three deliberate changes:

* **topology-aware choose** — whole-chip demands are placed on contiguous
  ICI sub-boxes (via ``Torus.placements_for``), not arbitrary card sets; the
  reference's per-card greedy sort (rater.go:74-110) cannot express this;
* **clamped scores** — the reference's Rate could exceed ScoreMax / go
  negative (rater.go:69,122), outside what kube-scheduler expects; every
  rate here is clamped to [SCORE_MIN, SCORE_MAX];
* **random policy exists** — README.md:14 advertises it but the reference
  never shipped it; here it is a real, deterministic-per-(node,demand)
  feasible placement.

Raters are pure: ``rate``/``choose`` read a ChipSet + Demand and return
values, never touching Dealer or policy state. The reference threaded Dealer
and PolicySpec through Rate when load-aware scheduling was bolted on
(rater.go:17), which rotted its tests (SURVEY §4); live load instead arrives
pre-folded into ``ChipResource.load``.
"""

from __future__ import annotations

import hashlib
from typing import Protocol

from nanotpu import types
from nanotpu.allocator.core import ChipSet, Demand, Plan

#: Weight of live load in node scoring (reference used 50, rater.go:59-70).
LOAD_WEIGHT = 50

#: Portion of the score band reserved for ICI-compactness of the plan.
COMPACTNESS_BAND = 10


def clamp_score(score: float) -> int:
    return max(types.SCORE_MIN, min(types.SCORE_MAX, int(score)))


class Rater(Protocol):
    name: str

    def rate(self, chips: ChipSet, demand: Demand) -> int: ...

    def choose(self, chips: ChipSet, demand: Demand) -> Plan | None: ...


def _mean_load(chips: ChipSet) -> float:
    if not chips.chips:
        return 0.0
    return sum(c.load for c in chips.chips) / len(chips.chips)


def _finalize(chips: ChipSet, demand: Demand, assignments: list[list[int]], base: int) -> Plan:
    all_chips = {c for a in assignments for c in a}
    compactness = chips.torus.compactness(all_chips) if all_chips else 1.0
    score = clamp_score(
        min(base, types.SCORE_MAX - COMPACTNESS_BAND) + compactness * COMPACTNESS_BAND
    )
    return Plan(demand=demand, assignments=assignments, score=score, compactness=compactness)


def _order_demands(demand: Demand) -> list[int]:
    """Container indexes, largest demand first (rater.go:75-81 sorts desc so
    big requests see the most room)."""
    return sorted(
        range(len(demand.percents)), key=lambda i: -demand.percents[i]
    )


def _whole_chip_candidates(
    chips: ChipSet, free: list[int], k: int,
    hbm_free: list[int | None] | None = None, hbm_need: int = 0,
) -> list[frozenset[int]]:
    """Fully-free candidate placements for k whole chips: axis-aligned
    sub-boxes when the volume admits one, else greedy connected sets grown
    from every free seed (covers non-box volumes like 3 or 5 chips).
    ``hbm_need`` additionally requires that much HBM free on every chip
    (None entries in ``hbm_free`` = untracked, always eligible)."""
    fully_free = {
        c for c in range(len(free))
        if free[c] == chips.chips[c].percent_total
        and (
            not hbm_need
            or hbm_free is None
            or hbm_free[c] is None
            or hbm_free[c] >= hbm_need
        )
    }
    boxes = [
        box for box in chips.torus.placements_for(k) if box <= fully_free
    ]
    if boxes:
        return boxes
    seen: set[frozenset[int]] = set()
    out: list[frozenset[int]] = []
    for seed in sorted(fully_free):
        grown = chips.torus.grow_connected(seed, k, fully_free)
        if grown is not None and grown not in seen:
            seen.add(grown)
            out.append(grown)
    return out


def _choose(chips: ChipSet, demand: Demand, prefer_used: bool, rng_key: str | None = None) -> list[list[int]] | None:
    """Shared placement engine: native C++ hot path with Python fallback.

    ``prefer_used=True`` == binpack (stack onto the fullest feasible chips /
    next to allocated regions); False == spread (emptiest chips / far from
    allocated regions). ``rng_key`` switches to deterministic-random
    candidate selection (Python only — sha256 ranking is not hot).

    The native engine (native/allocator.cc) implements :func:`_choose_py`'s
    binpack/spread placement with exact result parity, fuzz-enforced by
    tests/test_native.py.
    """
    if not demand.is_valid():
        return None
    if rng_key is None:
        from nanotpu import native

        try:
            return native.choose(
                chips.torus.dims,
                [c.percent_free for c in chips.chips],
                [c.percent_total for c in chips.chips],
                [c.load for c in chips.chips],
                list(demand.percents),
                prefer_used,
                types.PERCENT_PER_CHIP,
                # -1 == HBM untracked on that chip
                hbm_free=[
                    c.hbm_free_mib if c.hbm_total_mib else -1
                    for c in chips.chips
                ],
                hbm_demand=[
                    demand.hbm_of(i) for i in range(len(demand.percents))
                ],
            )
        except native.NativeUnavailable:
            pass
    return _choose_py(chips, demand, prefer_used, rng_key)


def _choose_py(chips: ChipSet, demand: Demand, prefer_used: bool, rng_key: str | None = None) -> list[list[int]] | None:
    """Pure-Python placement engine — the reference implementation the
    native path must match. Assumes ``demand.is_valid()``."""
    free = [c.percent_free for c in chips.chips]
    # None == HBM untracked on this chip (always eligible)
    hbm_free: list[int | None] = [
        c.hbm_free_mib if c.hbm_total_mib else None for c in chips.chips
    ]
    assignments: list[list[int]] = [[] for _ in demand.percents]

    def used_frac(chip_id: int) -> float:
        total = chips.chips[chip_id].percent_total
        return 1 - free[chip_id] / total if total else 0.0

    def boundary_contact(box: frozenset[int]) -> int:
        """ICI links from the box to chips that are (partially) used —
        binpack wants contact (defragment), spread wants isolation."""
        contact = 0
        for c in box:
            for n in chips.torus.neighbors(c):
                if n not in box and free[n] < chips.chips[n].percent_total:
                    contact += 1
        return contact

    def rng_rank(candidate_key: str) -> int:
        digest = hashlib.sha256(f"{rng_key}:{candidate_key}".encode()).digest()
        return int.from_bytes(digest[:4], "big")

    for i in _order_demands(demand):
        percent = demand.percents[i]
        hbm = demand.hbm_of(i)
        if percent <= 0:
            continue
        if percent >= types.PERCENT_PER_CHIP:
            k = percent // types.PERCENT_PER_CHIP
            candidates = _whole_chip_candidates(chips, free, k, hbm_free, hbm)
            if not candidates:
                return None
            if rng_key is not None:
                best = min(candidates, key=lambda b: rng_rank(str(sorted(b))))
            elif prefer_used:
                # most contact with used regions, then lowest chip ids for
                # determinism; placements_for already orders compact-first
                best = max(
                    candidates,
                    key=lambda b: (boundary_contact(b), -min(b)),
                )
            else:
                best = min(
                    candidates,
                    key=lambda b: (boundary_contact(b), min(b)),
                )
            for c in best:
                free[c] = 0
                if hbm and hbm_free[c] is not None:
                    hbm_free[c] -= hbm
            assignments[i] = sorted(best)
        else:
            feasible = [
                c for c in range(len(free))
                if free[c] >= percent
                and (not hbm or hbm_free[c] is None or hbm_free[c] >= hbm)
            ]
            if not feasible:
                return None
            if rng_key is not None:
                pick = min(feasible, key=lambda c: rng_rank(str(c)))
            elif prefer_used:
                # fullest feasible chip first; tiebreak low load then low id
                # (SortableGPUs analogue, allocate.go:238-247)
                pick = max(
                    feasible,
                    key=lambda c: (used_frac(c), -chips.chips[c].load, -c),
                )
            else:
                pick = min(
                    feasible,
                    key=lambda c: (used_frac(c), chips.chips[c].load, c),
                )
            free[pick] -= percent
            if hbm and hbm_free[pick] is not None:
                hbm_free[pick] -= hbm
            assignments[i] = [pick]
    return assignments


class Binpack:
    """Pack work onto the fewest, fullest nodes/chips (rater.go:53-110)."""

    name = types.POLICY_BINPACK

    def rate(self, chips: ChipSet, demand: Demand) -> int:
        # fuller node => higher score; hot node => penalized (the reference
        # *rewarded* load under binpack, rater.go:59-70 — inverted for SLO
        # sanity: load-aware scheduling exists to steer away from hot chips)
        return clamp_score(chips.usage() * 100 - _mean_load(chips) * LOAD_WEIGHT)

    def choose(self, chips: ChipSet, demand: Demand) -> Plan | None:
        assignments = _choose(chips, demand, prefer_used=True)
        if assignments is None:
            return None
        return _finalize(chips, demand, assignments, self.rate(chips, demand))


class Spread:
    """Spread work across the emptiest nodes/chips (rater.go:113-163)."""

    name = types.POLICY_SPREAD

    def rate(self, chips: ChipSet, demand: Demand) -> int:
        avail, free_chips = chips.available_percent_and_free_chips()
        total = chips.percent_total() or 1
        n = len(chips.chips) or 1
        # emptier node => higher score; blend free-chip count (whole-chip
        # headroom) with free percent (fractional headroom)
        score = 60 * (free_chips / n) + 40 * (avail / total)
        return clamp_score(score - _mean_load(chips) * LOAD_WEIGHT)

    def choose(self, chips: ChipSet, demand: Demand) -> Plan | None:
        assignments = _choose(chips, demand, prefer_used=False)
        if assignments is None:
            return None
        return _finalize(chips, demand, assignments, self.rate(chips, demand))


class Random:
    """Feasible placement chosen by a deterministic hash — README.md:14
    promises this policy; the reference never implemented it. Deterministic
    per (salt, demand) so Filter/Score/Bind agree on the same plan."""

    name = types.POLICY_RANDOM

    def __init__(self, salt: str = ""):
        self.salt = salt

    def rate(self, chips: ChipSet, demand: Demand) -> int:
        digest = hashlib.sha256(
            f"{self.salt}:{chips.key}:{demand.hash()}".encode()
        ).digest()
        return digest[0] % (types.SCORE_MAX + 1)

    def choose(self, chips: ChipSet, demand: Demand) -> Plan | None:
        key = f"{self.salt}:{chips.key}:{demand.hash()}"
        assignments = _choose(chips, demand, prefer_used=False, rng_key=key)
        if assignments is None:
            return None
        return _finalize(chips, demand, assignments, self.rate(chips, demand))


class Sample:
    """First-fit, constant score — test stand-in (rater.go:21-50)."""

    name = "sample"

    def rate(self, chips: ChipSet, demand: Demand) -> int:
        return types.SCORE_MAX

    def choose(self, chips: ChipSet, demand: Demand) -> Plan | None:
        if not demand.is_valid():
            return None
        free = [c.percent_free for c in chips.chips]
        hbm_free: list[int | None] = [
            c.hbm_free_mib if c.hbm_total_mib else None for c in chips.chips
        ]
        assignments: list[list[int]] = [[] for _ in demand.percents]
        for i, percent in enumerate(demand.percents):
            hbm = demand.hbm_of(i)
            if percent <= 0:
                continue
            if percent >= types.PERCENT_PER_CHIP:
                k = percent // types.PERCENT_PER_CHIP
                candidates = _whole_chip_candidates(
                    chips, free, k, hbm_free, hbm
                )
                if not candidates:
                    return None
                box = candidates[0]
                for c in box:
                    free[c] = 0
                    if hbm and hbm_free[c] is not None:
                        hbm_free[c] -= hbm
                assignments[i] = sorted(box)
            else:
                for c in range(len(free)):
                    if free[c] >= percent and (
                        not hbm or hbm_free[c] is None or hbm_free[c] >= hbm
                    ):
                        free[c] -= percent
                        if hbm and hbm_free[c] is not None:
                            hbm_free[c] -= hbm
                        assignments[i] = [c]
                        break
                else:
                    return None
        return Plan(demand=demand, assignments=assignments, score=types.SCORE_MAX)


def _make_throughput():
    # local import: throughput.py imports Plan/_choose back from here
    from nanotpu.allocator.throughput import Throughput

    return Throughput()


_RATERS = {
    types.POLICY_BINPACK: Binpack,
    types.POLICY_SPREAD: Spread,
    types.POLICY_RANDOM: Random,
    types.POLICY_THROUGHPUT: _make_throughput,
    "sample": Sample,
}


def make_rater(name: str) -> Rater:
    """Policy name -> rater (cmd/main.go:83-91's flag dispatch).

    ``program:<name>`` resolves a verified policy program
    (docs/policy-programs.md) — the in-tree source is verified and
    compiled here, so an unprovable program fails construction loudly
    instead of serving."""
    if name.startswith("program:"):
        from nanotpu.policy_ir import load_program

        return load_program(name[len("program:"):])
    try:
        return _RATERS[name]()
    except KeyError:
        raise ValueError(
            f"unknown priority policy {name!r}; want one of {sorted(_RATERS)}"
        ) from None
