"""Q16 scoring-term extraction: the policy-program input ABI.

Verified policy programs (docs/policy-programs.md) score over FIVE
integer terms, all derived here from the same per-chip state every
other scoring path reads — a ChipSet on the per-node path, a frozen
BatchScorer row on the batch path. Keeping the extraction in ONE module
is the bit-determinism argument: the per-node ``rate`` and the batch
``score_hook`` hand a program literally the same integers, so a program
cannot diverge between paths the way a float formula could.

The terms (all Q16 unless noted, docs/scoring.md):

* ``occupancy``  — bound fraction of the node's chip capacity,
  ``((total - free) * Q_ONE) // total``; 0 for a capacity-less node.
* ``fragmentation`` — share of the free capacity that sits on WHOLLY
  free chips (whole-chip headroom), ``(whole_free * Q_ONE) // free``;
  0 when nothing is free. Same ``whole_free`` rule as the throughput
  rater's frag term (a chip counts only when ``free == total > 0``).
* ``contention`` — mean per-card quantized load, ``sum(load_q) // n``.
* ``base_q`` — the model base fraction. Programs are model-free, so
  both extraction paths pass the neutral ``Q_ONE``; the slot exists so
  the ABI matches the r9 fused-score term layout.
* ``gang_bonus`` — [0, SCORE_MAX] integer. 0 on the batch path: the
  dealer folds the gang bonus AFTER the hook (``_hook_gang_bonus``),
  exactly as it does for the throughput rater, so a program must not
  add it again.

Every division is floor division of non-negative integers — C's
truncating ``/`` on the same operands, the same parity discipline as
``Throughput._combine``.
"""

from __future__ import annotations

from nanotpu.allocator.throughput import Q_ONE, quantize

__all__ = ["Q_ONE", "q16_row_terms", "q16_chipset_terms"]


def q16_row_terms(free, total, load_q) -> tuple[int, int, int]:
    """``(occupancy, fragmentation, contention)`` from one batch row's
    raw integer chip percents + pre-quantized loads (the SAME arrays
    the native mirror path consumes — no float touches them here)."""
    total_sum = sum(total)
    free_sum = sum(free)
    occupancy = (
        ((total_sum - free_sum) * Q_ONE) // total_sum if total_sum else 0
    )
    whole_free = sum(f for f, t in zip(free, total) if f == t and t > 0)
    fragmentation = (whole_free * Q_ONE) // free_sum if free_sum else 0
    n = len(load_q)
    contention = sum(load_q) // n if n else 0
    return occupancy, fragmentation, contention


def q16_chipset_terms(chips) -> tuple[int, int, int]:
    """Per-node-path adapter: the same terms from a ChipSet, quantizing
    each card's float load at the float/int edge (the one place floats
    may appear, same rule as ``Throughput._score_terms``)."""
    return q16_row_terms(
        [c.percent_free for c in chips.chips],
        [c.percent_total for c in chips.chips],
        [quantize(c.load) for c in chips.chips],
    )
