"""Reconciler: keeps the Dealer eventually consistent with the cluster.

Rebuild of ``pkg/controller/controller.go``. Same event semantics:

* pod ADDED   -> enqueue if it's a TPU-sharing pod (controller.go:90-106)
* pod MODIFIED-> enqueue iff (tracked pod turned completed) or (untracked,
  unreleased pod became assumed) (controller.go:289-335)
* pod DELETED -> Dealer.forget (controller.go:337-357)
* syncPod: completed -> Release; scheduled & active & assumed -> Allocate
  (controller.go:210-243)
* node DELETED -> Dealer.remove_node (MISSING in the reference — NodeMaps
  never evicted, SURVEY §2 #3 bugs list)

Transient sync errors retry through the workqueue with exponential backoff,
capped attempts (controller.go:202-268's rate-limited queue; node queue used
10s->360s, controller.go:126).
"""

from __future__ import annotations

import logging
import queue
import threading
import time

from nanotpu.dealer import Dealer
from nanotpu.k8s.client import ApiError, Clientset, NotFoundError
from nanotpu.k8s.objects import Pod
from nanotpu.utils import pod as podutil

log = logging.getLogger("nanotpu.controller")

MAX_SYNC_RETRIES = 5
BACKOFF_BASE_S = 0.05
BACKOFF_MAX_S = 5.0


class Controller:
    def __init__(
        self,
        client: Clientset,
        dealer: Dealer,
        workers: int = 2,
        resync_period_s: float = 30.0,
    ):
        self.client = client
        self.dealer = dealer
        self.workers = workers
        #: periodic full re-list (informer resync analogue, cmd/main.go:31);
        #: safety net for events lost across watch reconnects. <=0 disables.
        self.resync_period_s = resync_period_s
        self._queue: "queue.Queue[tuple[str, str, int] | None]" = queue.Queue()
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._pod_watch = None
        self._node_watch = None
        # key -> last seen pod object (the informer cache analogue)
        self._cache_lock = threading.Lock()
        self._pod_cache: dict[str, Pod] = {}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """List-then-watch startup (WaitForCacheSync analogue,
        controller.go:147-157): existing pods are synced before watching."""
        try:
            for pod in self.client.list_pods():
                if podutil.is_tpu_sharing_pod(pod):
                    self._remember(pod)
                    self._enqueue(pod)
        except ApiError as e:
            log.warning("initial pod list failed: %s", e)
        self._pod_watch = self.client.watch_pods()
        self._node_watch = self.client.watch_nodes()
        self._threads = [
            threading.Thread(target=self._pod_loop, daemon=True, name="pods"),
            threading.Thread(target=self._node_loop, daemon=True, name="nodes"),
        ]
        self._threads += [
            threading.Thread(target=self._worker, daemon=True, name=f"sync-{i}")
            for i in range(self.workers)
        ]
        if self.resync_period_s > 0:
            self._threads.append(
                threading.Thread(target=self._resync_loop, daemon=True, name="resync")
            )
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        if self._pod_watch:
            self._pod_watch.stop()
        if self._node_watch:
            self._node_watch.stop()
        for _ in range(self.workers):
            self._queue.put(None)

    def wait_idle(self, timeout: float = 5.0) -> bool:
        """Test helper: block until the workqueue drains."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self._queue.unfinished_tasks == 0:
                return True
            time.sleep(0.01)
        return False

    # -- informer-side -----------------------------------------------------
    def _remember(self, pod: Pod) -> None:
        with self._cache_lock:
            self._pod_cache[pod.key()] = pod

    def _known(self, key: str) -> Pod | None:
        with self._cache_lock:
            return self._pod_cache.get(key)

    def _enqueue(self, pod: Pod, attempt: int = 0) -> None:
        self._queue.put((pod.namespace, pod.name, attempt))

    def _pod_loop(self) -> None:
        for event in self._pod_watch:
            if self._stop.is_set():
                break
            self.handle_pod_event(event)

    def handle_pod_event(self, event) -> None:
        """One pod watch event through the reconciler's dispatch rules.

        Public so a deterministic driver (nanotpu.sim) can feed the REAL
        event logic without the watch threads; ``start()`` routes its own
        watch stream through here too, so there is exactly one dispatch."""
        pod = event.obj
        if not podutil.is_tpu_sharing_pod(pod):
            return
        if event.type == "ADDED":
            self._remember(pod)
            self._enqueue(pod)
        elif event.type == "MODIFIED":
            old = self._known(pod.key())
            self._remember(pod)
            # enqueue only on the two meaningful transitions
            # (controller.go:289-335)
            if podutil.is_completed_pod(pod):
                self._enqueue(pod)
            elif old is None and podutil.is_assumed(pod):
                self._enqueue(pod)
            elif podutil.is_assumed(pod) and old is not None and not podutil.is_assumed(old):
                self._enqueue(pod)
        elif event.type == "DELETED":
            with self._cache_lock:
                self._pod_cache.pop(pod.key(), None)
            self.dealer.forget(pod)

    def _node_loop(self) -> None:
        for event in self._node_watch:
            if self._stop.is_set():
                break
            self.handle_node_event(event)

    def handle_node_event(self, event) -> None:
        """One node watch event (see handle_pod_event for why public)."""
        if event.type == "DELETED":
            self.dealer.remove_node(event.obj.name)
        elif event.type == "ADDED":
            self.dealer.observe_node(event.obj)
        elif event.type == "MODIFIED":
            # resize/relabel detection (the reference ignored these)
            self.dealer.refresh_node(event.obj)

    def _resync_loop(self) -> None:
        """Periodic full reconcile: re-list pods and nodes, enqueue every TPU
        pod, release dealer-tracked pods that vanished, evict dealer nodes
        that no longer exist. Catches anything a dropped watch missed."""
        while not self._stop.wait(self.resync_period_s):
            try:
                self.resync_once()
            except ApiError as e:
                log.warning("resync failed: %s", e)

    def resync_once(self) -> None:
        # snapshot BEFORE the list: a pod bound after the list was taken is
        # tracked but legitimately missing from the (older) list — only pods
        # tracked before AND absent after are genuinely gone
        pre = {p.uid: p for p in self.dealer.tracked_pods()}
        live_pods = self.client.list_pods()
        for pod in live_pods:
            if podutil.is_tpu_sharing_pod(pod):
                self._remember(pod)
                self._enqueue(pod)
        live_uids = {p.uid for p in live_pods}
        for uid, pod in pre.items():
            if uid not in live_uids:
                # DELETED while the pod watch was down: without this diff
                # its chips stay allocated until scheduler restart (the
                # missed-DELETE leak; client-go informers get the delta
                # from their re-list, controller.go:89-123)
                log.info(
                    "resync: tracked pod %s vanished from the cluster; "
                    "releasing", pod.key(),
                )
                self.dealer.forget(pod)
                with self._cache_lock:
                    self._pod_cache.pop(pod.key(), None)
        live = {n.name: n for n in self.client.list_nodes()}
        for name in self.dealer.node_names():
            if name not in live:
                self.dealer.remove_node(name)
        for node in live.values():  # catch resizes a dropped
            self.dealer.refresh_node(node)  # watch event missed

    # -- work side ---------------------------------------------------------
    def drain_sync(self) -> int:
        """Synchronously process every queued pod sync in the caller's
        thread; retries happen inline instead of through timers, so the
        processing order is a pure function of the enqueue order. This is
        the deterministic counterpart of the worker threads — the sim
        drives a never-``start()``ed controller entirely through
        ``handle_*_event`` + this. Returns the number of syncs run."""
        processed = 0
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return processed
            try:
                if item is not None and self._process_item(
                    item,
                    lambda ns, n, a: self._queue.put((ns, n, a + 1)),
                ):
                    processed += 1
            finally:
                self._queue.task_done()

    def _process_item(self, item, requeue) -> bool:
        """One queued sync, shared by ``_worker`` and ``drain_sync`` so the
        retry cap and drop semantics live in exactly one place; only the
        requeue strategy differs (timer backoff vs inline re-put).
        ``requeue(namespace, name, attempt)`` receives the FAILED attempt
        number and must enqueue attempt + 1. Returns True iff the sync ran."""
        namespace, name, attempt = item
        try:
            self._sync_pod(namespace, name)
            return True
        except Exception as e:  # transient: retry via the caller's strategy
            if attempt + 1 > MAX_SYNC_RETRIES:
                log.error(
                    "dropping pod %s/%s after %d attempts: %s",
                    namespace, name, attempt, e,
                )
                return False
            requeue(namespace, name, attempt)
            return False

    def _requeue_backoff(self, namespace: str, name: str, attempt: int) -> None:
        delay = min(BACKOFF_BASE_S * (2 ** attempt), BACKOFF_MAX_S)
        timer = threading.Timer(
            delay,
            self._queue.put,
            args=((namespace, name, attempt + 1),),
        )
        timer.daemon = True
        timer.start()

    def _worker(self) -> None:
        while not self._stop.is_set():
            item = self._queue.get()
            try:
                if item is None:
                    return
                self._process_item(item, self._requeue_backoff)
            finally:
                self._queue.task_done()

    def _sync_pod(self, namespace: str, name: str) -> None:
        """controller.go:210-243."""
        try:
            pod = self.client.get_pod(namespace, name)
        except NotFoundError:
            cached = self._known(f"{namespace}/{name}")
            if cached is not None:
                self.dealer.forget(cached)
            return
        if podutil.is_completed_pod(pod):
            self.dealer.release(pod)
        elif pod.node_name and podutil.is_assumed(pod) and pod.phase in (
            "Pending", "Running",
        ):
            self.dealer.allocate(pod)
