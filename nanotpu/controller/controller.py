"""Reconciler: keeps the Dealer eventually consistent with the cluster.

Rebuild of ``pkg/controller/controller.go``. Same event semantics:

* pod ADDED   -> enqueue if it's a TPU-sharing pod (controller.go:90-106)
* pod MODIFIED-> enqueue iff (tracked pod turned completed) or (untracked,
  unreleased pod became assumed) (controller.go:289-335)
* pod DELETED -> Dealer.forget (controller.go:337-357)
* syncPod: completed -> Release; scheduled & active & assumed -> Allocate
  (controller.go:210-243)
* node DELETED -> Dealer.remove_node (MISSING in the reference — NodeMaps
  never evicted, SURVEY §2 #3 bugs list)

Transient sync errors retry through the workqueue with exponential backoff,
capped attempts (controller.go:202-268's rate-limited queue; node queue used
10s->360s, controller.go:126).

Overload behavior (docs/robustness.md): the workqueue is a bounded,
per-pod-coalescing queue (client-go's workqueue dedupes the same way) —
an event storm for one pod costs one queued sync, and a storm across many
pods sheds watch-driven syncs once the bound is hit (counted; the
periodic resync repairs whatever was shed). The assume-TTL sweeper
(:meth:`Controller.sweep_assumed_once`) expires pods that carry placement
annotations but never actually bound — a crashed scheduler's leftovers,
or a bind whose API write half-failed — rolling chip accounting back and
stripping the stale annotations so retries start clean.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import OrderedDict, deque

from nanotpu import types
from nanotpu.analysis.witness import make_condition, make_lock
from nanotpu.dealer import Dealer
from nanotpu.k8s.client import ApiError, Clientset, ConflictError, NotFoundError
from nanotpu.k8s.objects import Pod
from nanotpu.obs.decisions import REASON_ASSUME_EXPIRED, REASON_EPOCH_STALE
from nanotpu.utils import pod as podutil

log = logging.getLogger("nanotpu.controller")

MAX_SYNC_RETRIES = 5
BACKOFF_BASE_S = 0.05
BACKOFF_MAX_S = 5.0

#: Default bound on distinct pods queued for sync; beyond it, watch-driven
#: enqueues shed (resync repairs). Repair-path enqueues bypass the bound.
QUEUE_MAX_DEFAULT = 1024

#: Default TTL for assumed-but-never-bound placement annotations.
ASSUME_TTL_DEFAULT_S = 300.0


class CoalescingQueue:
    """Bounded pod-sync workqueue, latest-event-wins per pod key.

    Semantics match client-go's workqueue where it matters here: a key
    already queued absorbs repeat puts (one queued sync serves any number
    of events — ``_sync_pod`` re-GETs the pod, so the latest state wins by
    construction), FIFO across distinct keys, and ``None`` sentinels for
    worker shutdown are delivered only after real items drain (matching
    stdlib Queue's put-order behavior the workers were written against).

    The bound applies to WATCH-driven puts only: an event storm across
    more than ``maxsize`` distinct pods sheds the excess (counted as
    ``queue_dropped``; the periodic resync re-enqueues every live pod).
    Repair-path puts — resync itself, and capped retry re-puts — pass
    ``force=True``: dropping the repair mechanism would turn a transient
    shed into a permanent accounting divergence, and those paths are
    naturally bounded (live pods / retry cap) anyway.
    """

    def __init__(self, maxsize: int = QUEUE_MAX_DEFAULT, resilience=None):
        self._cv = make_condition("CoalescingQueue._cv")
        self._items: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._sentinels: deque = deque()
        self.maxsize = maxsize
        self.resilience = resilience
        self.unfinished_tasks = 0
        self.dropped = 0
        self.coalesced = 0

    def put(self, item, force: bool = False) -> bool:
        """Enqueue (namespace, name, attempt) or a ``None`` sentinel.
        Returns False iff the item was shed (bound hit, not forced)."""
        with self._cv:
            if item is None:
                self._sentinels.append(None)
                self.unfinished_tasks += 1
                self._cv.notify()
                return True
            namespace, name, attempt = item
            key = (namespace, name)
            existing = self._items.get(key)
            if existing is not None:
                # latest event wins; keep the larger attempt so the retry
                # cap still binds when a retry re-put coalesces
                self._items[key] = (namespace, name,
                                    max(attempt, existing[2]))
                self.coalesced += 1
                if self.resilience is not None:
                    self.resilience.inc("queue_coalesced")
                return True
            if not force and self.maxsize and len(self._items) >= self.maxsize:
                self.dropped += 1
                if self.resilience is not None:
                    self.resilience.inc("queue_dropped")
                log.warning(
                    "sync queue full (%d pods); shed sync for %s/%s "
                    "(resync will repair)", self.maxsize, namespace, name,
                )
                return False
            self._items[key] = item
            self.unfinished_tasks += 1
            self._cv.notify()
            return True

    def get(self, block: bool = True):
        with self._cv:
            while not self._items and not self._sentinels:
                if not block:
                    raise queue.Empty
                self._cv.wait()
            if self._items:
                _, item = self._items.popitem(last=False)
                return item
            return self._sentinels.popleft()

    def get_nowait(self):
        return self.get(block=False)

    def task_done(self) -> None:
        with self._cv:
            self.unfinished_tasks -= 1


class Controller:
    def __init__(
        self,
        client: Clientset,
        dealer: Dealer,
        workers: int = 2,
        resync_period_s: float = 30.0,
        queue_max: int = QUEUE_MAX_DEFAULT,
        assume_ttl_s: float = ASSUME_TTL_DEFAULT_S,
        resilience=None,
        obs=None,
    ):
        self.client = client
        self.dealer = dealer
        #: optional Observability bundle: the sweeper audits every expiry
        #: into the decision ledger so a pod whose annotations vanished
        #: has a causal record, not just a counter bump
        self.obs = obs
        self.workers = workers
        #: periodic full re-list (informer resync analogue, cmd/main.go:31);
        #: safety net for events lost across watch reconnects. <=0 disables.
        self.resync_period_s = resync_period_s
        #: TTL for assumed-but-never-bound annotations; <=0 disables the
        #: sweeper thread (sweep_assumed_once stays callable either way)
        self.assume_ttl_s = assume_ttl_s
        self.resilience = resilience
        self._queue = CoalescingQueue(maxsize=queue_max,
                                      resilience=resilience)
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._pod_watch = None
        self._node_watch = None
        # key -> last seen pod object (the informer cache analogue)
        self._cache_lock = make_lock("Controller._cache_lock")
        self._pod_cache: dict[str, Pod] = {}
        #: (pod key, resourceVersion) -> first time the sweeper saw it
        #: unbound-but-assumed; an rv change (new bind attempt) restarts
        #: the TTL clock automatically because it changes the key
        self._assume_seen: dict[tuple[str, str], float] = {}
        #: set once the initial list (or a later resync) has fed the dealer
        #: — the informer-sync half of /readyz
        self._synced = threading.Event()
        #: HA standby mode (docs/ha.md): True while this process is the
        #: warm standby. Informer events then update the cache and the
        #: dirty-key window ONLY — the delta stream drives the standby's
        #: dealer; node events still apply (pure in-memory, idempotent
        #: with the stream's node records).
        self.standby = False
        #: pod key -> (event type, pod) for events seen while standby
        #: whose matching delta has not arrived; at promotion the
        #: remainder IS the reconcile window — O(delta), not O(fleet).
        #: Bounded by HA_DIRTY_MAX: overflow latches
        #: ``_dirty_overflow`` and promotion full-resyncs instead.
        self._dirty: dict[str, tuple] = {}
        self._dirty_overflow = False
        #: optional callable -> the current leader-lease epoch
        #: (docs/ha.md "Split brain and fencing"): when set, the
        #: assume-TTL sweeper strips assumed-never-bound pods whose
        #: stamped ``tpu.io/epoch`` predates it WITHOUT waiting out the
        #: TTL — the post-heal cleanup for a deposed leader's half-bind.
        #: None (no fence wired) keeps sweep behavior byte-identical.
        self.epoch_of = None
        #: stale-epoch heals the sweeper performed (observability)
        self.epoch_heals = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """List-then-watch startup (WaitForCacheSync analogue,
        controller.go:147-157): existing pods are synced before watching."""
        try:
            for pod in self.client.list_pods():
                if podutil.is_tpu_sharing_pod(pod):
                    self._remember(pod)
                    self._enqueue(pod, force=True)  # boot sync is a repair
            self._synced.set()
        except ApiError as e:
            # not synced: /readyz stays 503 until a resync list succeeds
            log.warning("initial pod list failed: %s", e)
        self._pod_watch = self.client.watch_pods()
        self._node_watch = self.client.watch_nodes()
        self._threads = [
            threading.Thread(target=self._pod_loop, daemon=True, name="pods"),
            threading.Thread(target=self._node_loop, daemon=True, name="nodes"),
        ]
        self._threads += [
            threading.Thread(target=self._worker, daemon=True, name=f"sync-{i}")
            for i in range(self.workers)
        ]
        if self.resync_period_s > 0:
            self._threads.append(
                threading.Thread(target=self._resync_loop, daemon=True, name="resync")
            )
        if self.assume_ttl_s > 0:
            self._threads.append(
                threading.Thread(target=self._sweep_loop, daemon=True, name="assume-sweep")
            )
        for t in self._threads:
            t.start()

    def synced(self) -> bool:
        """True once a full pod list has fed the dealer at least once (the
        informer WaitForCacheSync analogue) — /readyz gates on this."""
        return self._synced.is_set()

    # -- HA standby mode (docs/ha.md) --------------------------------------
    def enter_standby(self) -> None:
        self.standby = True

    #: dirty-window bound: past it the window overflows and promotion
    #: falls back to ONE full resync — a peer-less or long-stalled
    #: standby must not grow an unbounded map it may never drain
    HA_DIRTY_MAX = 8192

    def exit_standby(self) -> None:
        """Leave standby mode. Events that arrived DURING the promotion
        reconcile (after ``ha_take_dirty`` drained the window, while the
        controller was still routing events into it) are not stale —
        they are the promotion race window. Flip live first, then hand
        every leftover to the now-live sync machinery: a completed pod's
        release must not wait for the next periodic resync."""
        self.standby = False
        with self._cache_lock:
            dirty, self._dirty = self._dirty, {}
            self._dirty_overflow = False
        for _key, (etype, pod) in sorted(dirty.items()):
            if etype == "DELETED":
                self.dealer.forget(pod)
            else:
                self._enqueue(pod, force=True)

    def ha_clear_dirty(self, key: str, kind: str = "released") -> None:
        """A delta covering this pod arrived: its informer event no
        longer needs promotion-time reconciliation.

        Kind-aware on purpose: the stream trails the informer, so a
        ``bound`` record can arrive AFTER the pod's completed/DELETED
        event was marked dirty — clearing that entry would strand the
        release in the lost lag window forever (the pod stays tracked on
        the promoted dealer; caught as a real double-accounting bug by
        the crash soak). A ``bound`` record therefore only clears
        non-terminal dirt; ``released`` clears everything."""
        with self._cache_lock:
            entry = self._dirty.get(key)
            if entry is None:
                return
            if kind == "bound":
                etype, pod = entry
                if etype == "DELETED" or podutil.is_completed_pod(pod):
                    return  # the terminal event still needs the reconcile
            self._dirty.pop(key, None)

    def ha_take_dirty(self) -> dict[str, tuple]:
        """Drain the dirty window (promotion reconcile input)."""
        with self._cache_lock:
            dirty, self._dirty = self._dirty, {}
        return dirty

    def sync_key(self, namespace: str, name: str) -> None:
        """One synchronous pod sync by key — the promotion reconcile's
        entry into the exact rules ``_sync_pod`` applies (completed ->
        release, assumed+placed -> allocate, vanished -> forget)."""
        self._sync_pod(namespace, name)

    def stop(self) -> None:
        self._stop.set()
        if self._pod_watch:
            self._pod_watch.stop()
        if self._node_watch:
            self._node_watch.stop()
        for _ in range(self.workers):
            self._queue.put(None)

    def wait_idle(self, timeout: float = 5.0) -> bool:
        """Test helper: block until the workqueue drains."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.unfinished_tasks == 0:
                return True
            time.sleep(0.01)
        return False

    # -- informer-side -----------------------------------------------------
    def _remember(self, pod: Pod) -> None:
        with self._cache_lock:
            self._pod_cache[pod.key()] = pod

    def _known(self, key: str) -> Pod | None:
        with self._cache_lock:
            return self._pod_cache.get(key)

    def unscheduled_pods(self) -> list[Pod]:
        """Every cached TPU-sharing pod with no node assignment yet —
        the batch admitter's drain source (docs/batch-admission.md).
        The informer cache is the same eventually-consistent view the
        coalescing queue works from: a pod bound milliseconds ago may
        still appear, which is safe — its bind answers idempotent
        success or ALREADY_BOUND and the admitter counts a fallback."""
        with self._cache_lock:
            pods = list(self._pod_cache.values())
        return [
            p for p in pods
            if not p.node_name and not podutil.is_completed_pod(p)
        ]

    def _enqueue(self, pod: Pod, attempt: int = 0,
                 force: bool = False) -> None:
        if self.standby:
            # a standby queues no syncs (the delta stream + dirty window
            # cover it; boot lists and resyncs land in the cache only)
            return
        self._queue.put((pod.namespace, pod.name, attempt), force=force)

    def requeue(self, pod: Pod) -> None:
        """Repair-path re-enqueue for out-of-band state changes (the
        capacity-recovery plane's preempt-and-requeue): force=True —
        like resync and capped retries, the repair mechanism must never
        shed itself on a full queue."""
        self._enqueue(pod, force=True)

    def _pod_loop(self) -> None:
        for event in self._pod_watch:
            if self._stop.is_set():
                break
            self.handle_pod_event(event)

    def handle_pod_event(self, event) -> None:
        """One pod watch event through the reconciler's dispatch rules.

        Public so a deterministic driver (nanotpu.sim) can feed the REAL
        event logic without the watch threads; ``start()`` routes its own
        watch stream through here too, so there is exactly one dispatch."""
        pod = event.obj
        if not podutil.is_tpu_sharing_pod(pod):
            return
        if self.standby:
            # standby tailing (docs/ha.md): cache + dirty window only.
            # The dirty predicate mirrors the active's enqueue rules —
            # an event the active would not act on needs no
            # promotion-time reconcile either.
            key = pod.key()
            with self._cache_lock:
                old = self._pod_cache.get(key)
                mark = None
                if event.type == "DELETED":
                    self._pod_cache.pop(key, None)
                    mark = ("DELETED", pod)
                else:
                    self._pod_cache[key] = pod
                    if podutil.is_completed_pod(pod) or (
                        podutil.is_assumed(pod)
                        and (old is None or not podutil.is_assumed(old))
                    ):
                        mark = (event.type, pod)
                if mark is not None and not self._dirty_overflow:
                    if (
                        key not in self._dirty
                        and len(self._dirty) >= self.HA_DIRTY_MAX
                    ):
                        # overflow: free the map, latch the flag — the
                        # promotion reconcile falls back to ONE full
                        # resync instead of a window nobody can trust
                        self._dirty.clear()
                        self._dirty_overflow = True
                        log.warning(
                            "ha dirty window overflowed (> %d pods); "
                            "promotion will full-resync",
                            self.HA_DIRTY_MAX,
                        )
                    else:
                        self._dirty[key] = mark
            return
        if event.type == "ADDED":
            self._remember(pod)
            self._enqueue(pod)
        elif event.type == "MODIFIED":
            old = self._known(pod.key())
            self._remember(pod)
            # enqueue only on the two meaningful transitions
            # (controller.go:289-335)
            if podutil.is_completed_pod(pod):
                self._enqueue(pod)
            elif old is None and podutil.is_assumed(pod):
                self._enqueue(pod)
            elif podutil.is_assumed(pod) and old is not None and not podutil.is_assumed(old):
                self._enqueue(pod)
        elif event.type == "DELETED":
            with self._cache_lock:
                self._pod_cache.pop(pod.key(), None)
            self.dealer.forget(pod)

    def _node_loop(self) -> None:
        for event in self._node_watch:
            if self._stop.is_set():
                break
            self.handle_node_event(event)

    def handle_node_event(self, event) -> None:
        """One node watch event (see handle_pod_event for why public)."""
        if event.type == "DELETED":
            self.dealer.remove_node(event.obj.name)
        elif event.type == "ADDED":
            self.dealer.observe_node(event.obj)
        elif event.type == "MODIFIED":
            # resize/relabel detection (the reference ignored these)
            self.dealer.refresh_node(event.obj)

    def _resync_loop(self) -> None:
        """Periodic full reconcile: re-list pods and nodes, enqueue every TPU
        pod, release dealer-tracked pods that vanished, evict dealer nodes
        that no longer exist. Catches anything a dropped watch missed."""
        while not self._stop.wait(self.resync_period_s):
            try:
                self.resync_once()
            except ApiError as e:
                log.warning("resync failed: %s", e)

    def resync_once(self) -> None:
        if self.standby:
            # standby: refresh the informer cache + the synced() gate
            # only; dealer repairs belong to the delta stream until
            # promotion (docs/ha.md)
            for pod in self.client.list_pods():
                if podutil.is_tpu_sharing_pod(pod):
                    self._remember(pod)
            self._synced.set()
            return
        # snapshot BEFORE the list: a pod bound after the list was taken is
        # tracked but legitimately missing from the (older) list — only pods
        # tracked before AND absent after are genuinely gone
        pre = {p.uid: p for p in self.dealer.tracked_pods()}
        live_pods = self.client.list_pods()
        for pod in live_pods:
            if podutil.is_tpu_sharing_pod(pod):
                self._remember(pod)
                # force: resync IS the repair path for shed watch syncs;
                # coalescing bounds it at one entry per live pod
                self._enqueue(pod, force=True)
        self._synced.set()
        live_uids = {p.uid for p in live_pods}
        for uid, pod in pre.items():
            if uid not in live_uids:
                # DELETED while the pod watch was down: without this diff
                # its chips stay allocated until scheduler restart (the
                # missed-DELETE leak; client-go informers get the delta
                # from their re-list, controller.go:89-123)
                log.info(
                    "resync: tracked pod %s vanished from the cluster; "
                    "releasing", pod.key(),
                )
                self.dealer.forget(pod)
                with self._cache_lock:
                    self._pod_cache.pop(pod.key(), None)
        live = {n.name: n for n in self.client.list_nodes()}
        for name in self.dealer.node_names():
            if name not in live:
                self.dealer.remove_node(name)
        for node in live.values():  # catch resizes a dropped
            self.dealer.refresh_node(node)  # watch event missed

    # -- assume-TTL sweeper ------------------------------------------------
    def _sweep_loop(self) -> None:
        period = max(self.assume_ttl_s / 2, 1.0)
        while not self._stop.wait(period):
            try:
                self.sweep_assumed_once()
            except Exception:  # the sweeper thread must outlive any sweep
                log.exception("assume sweep failed")

    def sweep_assumed_once(self, ttl_s: float | None = None,
                           now: float | None = None,
                           epoch: int | None = None) -> int:
        """Expire assumed-but-never-bound placement annotations.

        A pod carrying ``tpu.io/assume`` + chip annotations but no
        ``spec.nodeName`` is a half-completed bind: the annotation PUT
        landed, the pods/binding POST never did (API brownout, injected
        failure, scheduler crash between the two writes). Live retries
        rewrite the annotations (new resourceVersion -> fresh TTL clock),
        so only pods parked in that state for a full TTL at the SAME
        resourceVersion expire: their stale annotations are stripped so a
        later scheduler boot can never replay a placement that does not
        exist, and — if this dealer somehow still accounts the uid — the
        chips roll back through ``Dealer.forget`` under the same
        invariants the sim checks. Deterministic given ``now`` (the sim
        passes virtual time). Returns the number of pods expired."""
        ttl = self.assume_ttl_s if ttl_s is None else ttl_s
        now = time.monotonic() if now is None else now
        if epoch is None and self.epoch_of is not None:
            try:
                epoch = int(self.epoch_of())
            except Exception:
                epoch = None
        try:
            pods = self.client.list_pods(
                label_selector={types.ANNOTATION_ASSUME: "true"}
            )
        except ApiError as e:
            log.warning("assume sweep list failed: %s", e)
            return 0
        expired = 0
        seen: set[tuple[str, str]] = set()
        for pod in pods:
            if pod.node_name or podutil.is_completed_pod(pod):
                continue
            key = (pod.key(), pod.resource_version)
            seen.add(key)
            # stale-epoch heal (docs/ha.md): an assumed-never-bound pod
            # whose stamped writer epoch predates the CURRENT lease term
            # is a deposed leader's half-bind — its annotation PUT
            # slipped out before that leader's fence closed, and the
            # writer that could finish it no longer exists. Strip NOW;
            # waiting out the TTL only prolongs the phantom placement.
            # Unstamped pods (epoch 0: pre-fencing writers, single-
            # replica deployments) always take the TTL path.
            stamped = podutil.epoch_of(pod)
            stale_epoch = (
                epoch is not None and 0 < stamped < epoch
            )
            if not stale_epoch:
                first = self._assume_seen.setdefault(key, now)
                if now - first < ttl:
                    continue
            if self._expire_assumed(pod, ttl, stale_epoch=stale_epoch):
                expired += 1
                self._assume_seen.pop(key, None)
                seen.discard(key)
                if stale_epoch:
                    self.epoch_heals += 1
                if self.resilience is not None:
                    self.resilience.inc("assume_expired")
        # entries whose pod progressed (bound/deleted/re-annotated) are
        # stale bookkeeping; drop them so the map cannot grow unbounded
        self._assume_seen = {
            k: t for k, t in self._assume_seen.items() if k in seen
        }
        return expired

    def _expire_assumed(self, pod: Pod, ttl: float,
                        stale_epoch: bool = False) -> bool:
        # the one annotation-strip implementation, shared with the
        # capacity-recovery plane's preempt path (docs/defrag.md)
        stripped = podutil.strip_placement(pod)
        try:
            self.client.update_pod(stripped)
        except ConflictError:
            return False  # the pod just moved (e.g. a retry re-annotated)
        except NotFoundError:
            pass  # deleted under us: the forget below still applies
        except ApiError as e:
            log.warning("assume sweep could not strip %s: %s", pod.key(), e)
            return False
        if stale_epoch:
            log.warning(
                "healed stale-epoch placement annotations on %s (stamped "
                "by a superseded lease term; stripped without the TTL "
                "wait)", pod.key(),
            )
        else:
            log.warning(
                "expired stale placement annotations on %s (assumed but "
                "never bound within %gs)", pod.key(), ttl,
            )
        if self.obs is not None and self.obs.tracer.sampled(pod.uid):
            # close the pod's audit trail (final=True: the expiry is a
            # terminal verdict — without it the cycle would sit in the
            # building map reading as "still in flight" and never reach
            # /debug/decisions). Gated on the pod's sticky sampling
            # verdict, not just enabled: under 1-in-N a mass-expiry event
            # recording 100% of pods would evict the sampled pods'
            # complete cycles from the bounded ring.
            self.obs.ledger.bind_outcome(
                pod.uid, pod.node_name or "",
                REASON_EPOCH_STALE if stale_epoch else REASON_ASSUME_EXPIRED,
                False, pod=pod.key(), final=True,
            )
        if self.dealer.tracks(pod.uid):
            # defensive: accounting for an unbound pod is exactly the leak
            # the sweeper exists to stop — roll the chips back
            self.dealer.forget(pod)
        return True

    # -- work side ---------------------------------------------------------
    def drain_sync(self) -> int:
        """Synchronously process every queued pod sync in the caller's
        thread; retries happen inline instead of through timers, so the
        processing order is a pure function of the enqueue order. This is
        the deterministic counterpart of the worker threads — the sim
        drives a never-``start()``ed controller entirely through
        ``handle_*_event`` + this. Returns the number of syncs run."""
        processed = 0
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return processed
            try:
                if item is not None and self._process_item(
                    item,
                    lambda ns, n, a: self._queue.put(
                        (ns, n, a + 1), force=True
                    ),
                ):
                    processed += 1
            finally:
                self._queue.task_done()

    def _process_item(self, item, requeue) -> bool:
        """One queued sync, shared by ``_worker`` and ``drain_sync`` so the
        retry cap and drop semantics live in exactly one place; only the
        requeue strategy differs (timer backoff vs inline re-put).
        ``requeue(namespace, name, attempt)`` receives the FAILED attempt
        number and must enqueue attempt + 1. Returns True iff the sync ran."""
        namespace, name, attempt = item
        try:
            self._sync_pod(namespace, name)
            return True
        except Exception as e:  # transient: retry via the caller's strategy
            if attempt + 1 > MAX_SYNC_RETRIES:
                log.error(
                    "dropping pod %s/%s after %d attempts: %s",
                    namespace, name, attempt, e,
                )
                return False
            requeue(namespace, name, attempt)
            return False

    def _requeue_backoff(self, namespace: str, name: str, attempt: int) -> None:
        delay = min(BACKOFF_BASE_S * (2 ** attempt), BACKOFF_MAX_S)
        timer = threading.Timer(
            delay,
            self._queue.put,
            args=((namespace, name, attempt + 1),),
            kwargs={"force": True},  # capped retries never shed themselves
        )
        timer.daemon = True
        timer.start()

    def _worker(self) -> None:
        while not self._stop.is_set():
            item = self._queue.get()
            try:
                if item is None:
                    return
                self._process_item(item, self._requeue_backoff)
            finally:
                self._queue.task_done()

    def _sync_pod(self, namespace: str, name: str) -> None:
        """controller.go:210-243."""
        try:
            pod = self.client.get_pod(namespace, name)
        except NotFoundError:
            cached = self._known(f"{namespace}/{name}")
            if cached is not None:
                self.dealer.forget(cached)
            return
        if podutil.is_completed_pod(pod):
            self.dealer.release(pod)
        elif pod.node_name and podutil.is_assumed(pod) and pod.phase in (
            "Pending", "Running",
        ):
            self.dealer.allocate(pod)
