"""Load-aware scheduling data plane: metric sources + sync loops.

Rebuild of ``pkg/controller/node.go`` + ``pkg/prometheus``. Per policy
metric, a loop ticks at its sync period, reads per-chip utilization for every
TPU node, and writes it into the Dealer's usage store (which folds it into
``ChipResource.load`` for the raters). Differences from the reference:

* the primary source is the **TPU runtime metrics endpoint** on each node
  (libtpu exposes Prometheus text; duty cycle ~ core utilization, HBM usage
  ~ memory) — no DCGM, no GPU (BASELINE north_star);
* a PromQL server remains supported as a secondary source
  (``PrometheusSource``, the ``pkg/prometheus`` analogue), with the same
  two label-shape fallbacks (prometheus.go:68-83);
* node gate is :func:`nanotpu.utils.node.is_tpu_enabled`, not the NVIDIA
  label (controller/node.go:153-158);
* failures degrade: a node that cannot be scraped keeps retrying at the
  next tick with capped logging; ≤5 consecutive errors drop to debug level
  (node.go:68-83's retry-then-drop without losing the node forever).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.parse
import urllib.request
from typing import Protocol

from nanotpu import types
from nanotpu.analysis.witness import make_lock
from nanotpu.dealer import Dealer
from nanotpu.k8s.client import ApiError, Clientset
from nanotpu.k8s.objects import Node
from nanotpu.metrics.promtext import parse_prometheus_text
from nanotpu.policy import METRIC_CORE, METRIC_HBM, PolicyWatcher
from nanotpu.utils import node as nodeutil

log = logging.getLogger("nanotpu.metricsync")

#: Default port of the per-node TPU runtime metrics endpoint (libtpu's
#: prometheus exporter).
TPU_RUNTIME_METRICS_PORT = 8431

#: Metric names exposed by the TPU runtime, mapped to our policy metrics.
RUNTIME_METRIC_NAMES = {
    METRIC_CORE: ("tensorcore_duty_cycle_percent", 0.01),
    METRIC_HBM: ("memory_bandwidth_utilization", 0.01),
}


class MetricSource(Protocol):
    def chip_usage(self, node: Node, chip: int, metric: str) -> float | None:
        """Utilization fraction [0,1] or None when unavailable."""


class TpuRuntimeSource:
    """Scrapes each node's libtpu metrics endpoint directly."""

    def __init__(self, port: int = TPU_RUNTIME_METRICS_PORT, timeout_s: float = 5.0):
        self.port = port
        self.timeout_s = timeout_s
        self._cache_lock = make_lock("TpuRuntimeSource._cache_lock")
        self._cache: dict[str, list] = {}  # node -> parsed samples (per tick)

    def begin_tick(self) -> None:
        with self._cache_lock:
            self._cache.clear()

    def _node_address(self, node: Node) -> str | None:
        for addr in (node.status.get("addresses") or []):
            if addr.get("type") in ("InternalIP", "Hostname"):
                return addr.get("address")
        return node.name or None

    def _samples(self, node: Node):
        with self._cache_lock:
            if node.name in self._cache:
                return self._cache[node.name]
        host = self._node_address(node)
        if not host:
            return []
        url = f"http://{host}:{self.port}/metrics"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
                samples = parse_prometheus_text(resp.read().decode(errors="replace"))
        except OSError as e:
            log.debug("scrape %s failed: %s", url, e)
            samples = []
        with self._cache_lock:
            self._cache[node.name] = samples
        return samples

    def chip_usage(self, node: Node, chip: int, metric: str) -> float | None:
        name, scale = RUNTIME_METRIC_NAMES.get(metric, (metric, 1.0))
        for s in self._samples(node):
            if s.name != name:
                continue
            label = s.labels.get("chip") or s.labels.get("device_id") or s.labels.get("core")
            if label is not None and label != str(chip):
                continue
            return max(0.0, s.value * scale)
        return None


class PrometheusSource:
    """PromQL instant queries (pkg/prometheus/prometheus.go). Tries the two
    label shapes the reference supported: {node=,chip=} then {node=,chipNode=}
    (prometheus.go:68-83 used card/cardNode)."""

    def __init__(self, base_url: str, timeout_s: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def _query(self, promql: str) -> float | None:
        url = (
            f"{self.base_url}/api/v1/query?"
            + urllib.parse.urlencode({"query": promql})
        )
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
                doc = json.loads(resp.read())
        except (OSError, json.JSONDecodeError) as e:
            log.debug("promql %r failed: %s", promql, e)
            return None
        results = (doc.get("data") or {}).get("result") or []
        if not results:
            return None
        try:
            value = float(results[0]["value"][1])
        except (KeyError, IndexError, TypeError, ValueError):
            return None
        if value != value or value < 0:  # NaN / negative clamp (prometheus.go:34-65)
            return 0.0
        return value

    def chip_usage(self, node: Node, chip: int, metric: str) -> float | None:
        v = self._query(f'{metric}{{node=~"{node.name}",chip="{chip}"}} / 100')
        if v is None:
            v = self._query(
                f'{metric}{{node=~"{node.name}",chipNode="{chip}"}} / 100'
            )
        return v


class MetricSyncer:
    """One loop per policy metric (controller.go:172-177 started one
    syncMetricLoop per period)."""

    def __init__(
        self,
        dealer: Dealer,
        client: Clientset,
        source: MetricSource,
        policy: PolicyWatcher,
    ):
        self.dealer = dealer
        self.client = client
        self.source = source
        self.policy = policy
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._errors: dict[str, int] = {}

    def start(self) -> None:
        for metric in (METRIC_CORE, METRIC_HBM):
            t = threading.Thread(
                target=self._loop, args=(metric,), daemon=True,
                name=f"metricsync-{metric}",
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()

    def _loop(self, metric: str) -> None:
        while True:
            period = self.policy.spec().period_for(metric)
            if self._stop.wait(period):
                return
            self.sync_once(metric)

    def sync_once(self, metric: str) -> int:
        """One tick: scrape every enabled TPU node. Returns chips updated."""
        if hasattr(self.source, "begin_tick"):
            self.source.begin_tick()
        try:
            nodes = self.client.list_nodes()
        except ApiError as e:
            log.warning("metric sync list nodes failed: %s", e)
            return 0
        updated = 0
        touched: set[str] = set()
        for node in nodes:
            if not nodeutil.is_tpu_enabled(node) or not nodeutil.is_tpu_node(node):
                continue
            chip_count = nodeutil.get_chip_count(node)
            errored = False
            for chip in range(chip_count):
                try:
                    value = self.source.chip_usage(node, chip, metric)
                except Exception as e:  # a source must never kill the loop
                    errored = True
                    self._note_error(node.name, e)
                    continue
                if value is None:
                    continue
                kwargs = {"core": value} if metric == METRIC_CORE else {"memory": value}
                # publish deferred: one snapshot publish covers the whole
                # sweep below instead of one per chip (O(nodes x chips)
                # copy-on-write view clones per tick otherwise)
                self.dealer.update_chip_usage(
                    node.name, chip, publish=False, **kwargs
                )
                touched.add(node.name)
                updated += 1
            if not errored:
                # only a clean tick resets the log-throttle counter
                self._errors.pop(node.name, None)
        if touched:
            self.dealer.publish_usage(tuple(sorted(touched)))
        return updated

    def _note_error(self, node: str, err: Exception) -> None:
        count = self._errors.get(node, 0) + 1
        self._errors[node] = count
        # first 5 errors at warning, then debug (node.go:74-82 dropped after
        # 5 retries; we keep trying but stop shouting)
        if count <= 5:
            log.warning("metric scrape for node %s failed: %s", node, err)
        else:
            log.debug("metric scrape for node %s failed (#%d): %s", node, count, err)


def start_metric_sync(
    dealer: Dealer,
    client: Clientset,
    prometheus_url: str = "",
    policy_config: str = "",
    policy: PolicyWatcher | None = None,
) -> MetricSyncer:
    """Wire the load-aware pipeline (cmd/main.go:115-119 + controller.go:
    125-134). TPU runtime endpoint is the default source; a Prometheus URL
    switches to PromQL. ``policy`` reuses an existing watcher (cmd/main
    builds ONE per process so the throughput rater's table reload and
    the metric weights share a single mtime poll) instead of starting a
    second poll thread on the same file."""
    policy = policy or PolicyWatcher(policy_config)
    source: MetricSource
    if prometheus_url:
        source = PrometheusSource(prometheus_url)
    else:
        source = TpuRuntimeSource()
    syncer = MetricSyncer(dealer, client, source, policy)
    syncer.start()
    log.info(
        "load-aware metric sync started (source=%s)",
        type(source).__name__,
    )
    return syncer
