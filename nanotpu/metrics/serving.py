"""``nanotpu_serving_*`` exposition: the serving fleet's scrape surface
(docs/serving-loop.md).

The gauge values come from ONE producer —
:meth:`ServingMetricsSource.serving_gauge_values
<nanotpu.serving.feedback.ServingMetricsSource.serving_gauge_values>` —
which is also the timeline source's ``sample()`` body, so the scrape
surface, the ``ext.serving.*`` tick series, and the SLO-addressable
fields are one table that cannot drift. The nanolint
metrics-completeness pass cross-checks :data:`_SERVING_GAUGES` against
that producer BOTH directions (a suffix declared here but never
produced, or produced there but never declared, is a lint finding) —
the same honesty contract the throughput/timeline/SLO families live
under.
"""

from __future__ import annotations

import logging

log = logging.getLogger("nanotpu.metrics.serving")

_FAMILY = "nanotpu_serving_"

#: gauge suffix -> help text. Keys must match
#: ServingMetricsSource.serving_gauge_values() exactly — nanolint pins
#: the equivalence both ways.
_SERVING_GAUGES: dict[str, str] = {
    "tok_s":
        "Realized decode tokens/s EWMA across the serving fleet "
        "(cold/compile-contaminated chunks excluded)",
    "tok_s_per_chip":
        "Realized decode tokens/s per allocated chip — the placement "
        "objective the scheduler feedback loop optimizes",
    "queue_depth":
        "Generation requests queued and not yet admitted to a slot",
    "active_slots":
        "Slot-batch rows currently decoding across the fleet",
    "slots":
        "Total decode slots provisioned across the fleet",
    "kv_occupancy":
        "Fraction of KV-cache positions holding live context "
        "(admission pressure: near 1.0 means slots are long-context)",
    "chips":
        "Chips currently allocated to serving replicas",
    "replicas":
        "Live serving replica pods (bound + draining; the autoscaler's "
        "view when one is attached)",
    "ttft_p99_ms":
        "Time-to-first-token p99 over the recent request window "
        "(milliseconds) — the SLO-addressable latency objective "
        "(ext.serving.ttft_p99_ms)",
}


class ServingExporter:
    """Registry-compatible renderer (``Registry.register``) for the
    serving gauges. Registered exactly when a serving source is
    attached, so deployments without one export nothing new.

    The source may sit on a network poll (``RemoteStatsProvider`` over
    a replica's ``/v1/stats``), so a failing provider must degrade to
    ``nanotpu_serving_up 0`` instead of 500ing the WHOLE /metrics
    exposition — losing every scheduler metric family exactly when the
    serving fleet is unreachable would be the opposite of observability
    (the timeline source guard makes the same call with its
    ``{"error": 1}`` marker)."""

    def __init__(self, source):
        self.source = source

    def render(self) -> list[str]:
        up = _FAMILY + "up"
        out: list[str] = [
            f"# HELP {up} Whether the serving stats source answered "
            "the last scrape (0 = provider unreachable/raising; the "
            "value gauges below are omitted while down)",
            f"# TYPE {up} gauge",
        ]
        try:
            values = self.source.serving_gauge_values()
        except Exception:
            log.warning("serving stats source failed", exc_info=True)
            out.append(f"{up} 0")
            return out
        out.append(f"{up} 1")
        for suffix in sorted(_SERVING_GAUGES):
            name = _FAMILY + suffix
            out.append(f"# HELP {name} {_SERVING_GAUGES[suffix]}")
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {float(values[suffix])}")
        return out
