"""Prometheus text-exposition parser (consumer side).

The TPU runtime (libtpu) exposes its metrics endpoint in Prometheus text
format (duty cycle, HBM usage, per-chip) — the TPU-native replacement for the
reference's DCGM-via-Prometheus pipeline (``pkg/prometheus``). stdlib-only.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s]+)"
)
_LABEL_RE = re.compile(r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:\\.|[^"\\])*)"')

_UNESCAPES = {"\\\\": "\\", '\\"': '"', "\\n": "\n"}


def _unescape_label_value(raw: str) -> str:
    """Inverse of the exposition format's label-value escaping (the
    registry's ``_escape_label_value``): ``\\\\``, ``\\"``, ``\\n``.
    Processed left-to-right so ``\\\\n`` stays a backslash + ``n``."""
    out: list[str] = []
    i = 0
    while i < len(raw):
        pair = raw[i:i + 2]
        if pair in _UNESCAPES:
            out.append(_UNESCAPES[pair])
            i += 2
        else:
            out.append(raw[i])
            i += 1
    return "".join(out)


@dataclass(frozen=True)
class Sample:
    name: str
    labels: dict
    value: float

    def label(self, key: str, default: str = "") -> str:
        return self.labels.get(key, default)


def parse_prometheus_text(text: str) -> list[Sample]:
    """Parse exposition text into samples; malformed lines are skipped (a
    scrape must degrade, never raise)."""
    out: list[Sample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        if math.isnan(value):
            continue
        labels = {}
        if m.group("labels"):
            labels = {
                lm.group("k"): _unescape_label_value(lm.group("v"))
                for lm in _LABEL_RE.finditer(m.group("labels"))
            }
        out.append(Sample(m.group("name"), labels, value))
    return out


def find_sample(
    samples: list[Sample], name: str, **labels: str
) -> Sample | None:
    for s in samples:
        if s.name == name and all(s.labels.get(k) == v for k, v in labels.items()):
            return s
    return None
