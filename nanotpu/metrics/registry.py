"""Minimal Prometheus exposition (text format 0.0.4).

The reference only *consumed* Prometheus (pkg/prometheus) and exported
nothing — "No Prometheus export" is a documented gap (SURVEY §5) and the
BASELINE metric (occupancy %, verb latency) needs an exporter. stdlib-only;
thread-safe; enough of the text format for scrapers: counter, gauge,
histogram with cumulative buckets.
"""

from __future__ import annotations

from bisect import bisect_left

from nanotpu.analysis.witness import make_lock

#: Default latency buckets (seconds) tuned for scheduler verbs: sub-ms to 2.5s.
LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5
)


def _escape_label_value(value: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline (exposition format spec). Without it a node name
    or verb label containing ``"`` silently corrupts the whole scrape."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str):
        self.name, self.help = name, help_
        self._lock = make_lock("metrics.Counter._lock")
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._values.items())
        for key, val in items or [((), 0.0)]:
            out.append(f"{self.name}{_fmt_labels(dict(key))} {val}")
        return out


class Gauge:
    def __init__(self, name: str, help_: str):
        self.name, self.help = name, help_
        self._lock = make_lock("metrics.Gauge._lock")
        self._values: dict[tuple, float] = {}
        self._fn = None

    def set(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = value

    def set_function(self, fn) -> None:
        """Lazily evaluated unlabeled gauge (e.g. live occupancy)."""
        self._fn = fn

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        if self._fn is not None:
            try:
                out.append(f"{self.name} {float(self._fn())}")
            except Exception:  # metric must never break the scrape
                out.append(f"{self.name} NaN")
            return out
        with self._lock:
            items = sorted(self._values.items())
        for key, val in items or [((), 0.0)]:
            out.append(f"{self.name}{_fmt_labels(dict(key))} {val}")
        return out


class Histogram:
    def __init__(self, name: str, help_: str, buckets: tuple[float, ...] = LATENCY_BUCKETS):
        self.name, self.help = name, help_
        self.buckets = tuple(sorted(buckets))
        self._lock = make_lock("metrics.Histogram._lock")
        # per label-set: (bucket counts, total count, sum)
        self._series: dict[tuple, list] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            series = self._series.setdefault(
                key, [[0] * len(self.buckets), 0, 0.0]
            )
            # store per-bucket raw counts; cumulative sums computed at render.
            # le-semantics: value lands in the first bucket with le >= value
            idx = bisect_left(self.buckets, value)
            if idx < len(self.buckets):
                series[0][idx] += 1
            series[1] += 1
            series[2] += value

    def snapshot(self) -> dict[tuple, dict]:
        """Per-label-set point-in-time copy — raw (non-cumulative)
        bucket counts, total count, and sum — for delta consumers (the
        telemetry timeline's per-tick verb-latency deltas)."""
        with self._lock:
            return {
                key: {"raw": list(v[0]), "count": v[1], "sum": v[2]}
                for key, v in self._series.items()
            }

    def quantile(self, q: float, **labels: str) -> float:
        """Approximate quantile from buckets (upper bound of the bucket the
        q-th observation falls in). For bench reporting, not exposition."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            series = self._series.get(key)
            if not series or series[1] == 0:
                return 0.0
            raw, total = list(series[0]), series[1]
        target = q * total
        cum = 0
        for i, c in enumerate(raw):
            cum += c
            if cum >= target:
                return self.buckets[i]
        return float("inf")

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            series = {k: (list(v[0]), v[1], v[2]) for k, v in self._series.items()}
        for key, (raw, count, total) in sorted(series.items()):
            labels = dict(key)
            cum = 0
            for le, c in zip(self.buckets, raw):
                cum += c
                out.append(
                    f"{self.name}_bucket{_fmt_labels({**labels, 'le': repr(le)})} {cum}"
                )
            out.append(
                f"{self.name}_bucket{_fmt_labels({**labels, 'le': '+Inf'})} {count}"
            )
            out.append(f"{self.name}_sum{_fmt_labels(labels)} {total}")
            out.append(f"{self.name}_count{_fmt_labels(labels)} {count}")
        return out


class Registry:
    def __init__(self):
        self._lock = make_lock("metrics.Registry._lock")
        self._metrics: list = []

    def counter(self, name: str, help_: str) -> Counter:
        m = Counter(name, help_)
        with self._lock:
            self._metrics.append(m)
        return m

    def gauge(self, name: str, help_: str) -> Gauge:
        m = Gauge(name, help_)
        with self._lock:
            self._metrics.append(m)
        return m

    def histogram(self, name: str, help_: str, buckets: tuple[float, ...] = LATENCY_BUCKETS) -> Histogram:
        m = Histogram(name, help_, buckets)
        with self._lock:
            self._metrics.append(m)
        return m

    def register(self, metric) -> None:
        """Adopt an externally-owned renderable (anything with
        ``render() -> list[str]``), e.g. the resilience-counter exporter
        whose backing counters live outside the registry."""
        with self._lock:
            self._metrics.append(metric)

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics)
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"
