"""``nanotpu_sched_throughput_*`` exposition: the throughput model's
observable surface (docs/scoring.md).

Two kinds of series:

* unlabeled model gauges — the keys of :data:`_THROUGHPUT_GAUGES`,
  produced by :meth:`ThroughputModel.gauge_values
  <nanotpu.allocator.throughput.ThroughputModel.gauge_values>`. The
  nanolint metrics-completeness pass cross-checks the two tables BOTH
  directions (a gauge declared here but never produced, or produced
  there but never declared/exported, is a lint finding) — the same
  honesty contract the resilience counters and PerfCounters live under.
* ``nanotpu_sched_throughput_modeled_aggregate{shard=...}`` — modeled
  aggregate throughput of the pods bound to each snapshot shard's
  nodes, derated for card co-residency (the fleet's "how much work is
  the cluster actually delivering" number; the sim certifies the
  binpack-vs-throughput delta on exactly this model,
  examples/sim/het-throughput.json).
"""

from __future__ import annotations

from nanotpu.metrics.registry import _escape_label_value

_FAMILY = "nanotpu_sched_throughput_"

#: gauge suffix -> help text. Keys must match ThroughputModel.
#: gauge_values() exactly — nanolint pins the equivalence both ways.
_THROUGHPUT_GAUGES: dict[str, str] = {
    "calibration_age_seconds":
        "Seconds since the newest contention-EWMA calibration sample "
        "(-1: never calibrated)",
    "calibrated_nodes":
        "Nodes with at least one contention-EWMA calibration sample",
    "table_rows":
        "Rows in the effective-throughput table (seed defaults + "
        "policy.yaml overrides)",
}

_MODELED = _FAMILY + "modeled_aggregate"


def modeled_aggregate_by_shard(dealer, model) -> dict[str, float]:
    """Modeled aggregate throughput of bound pods, grouped by the
    snapshot shard owning each pod's node (``all`` in single-shard
    mode). Scrape-time walk over the dealer's tracked pods — O(pods),
    copies taken under the dealer lock via the public snapshot."""
    from nanotpu.allocator.throughput import pod_modeled_throughput
    from nanotpu.dealer.shard import DEFAULT_SHARD_KEY

    node_infos = dealer.debug_snapshot()["node_infos"]
    shard_of = getattr(dealer, "_shard_of", {})
    out: dict[str, float] = {}
    for pod in dealer.tracked_pods():
        info = node_infos.get(pod.node_name)
        if info is None:
            continue
        tput = pod_modeled_throughput(pod, info, model)
        if tput <= 0.0:
            continue
        key = shard_of.get(pod.node_name) or DEFAULT_SHARD_KEY
        out[key] = out.get(key, 0.0) + tput
    return {k: round(out[k], 4) for k in sorted(out)}


class ThroughputExporter:
    """Registry-compatible renderer (``Registry.register``) for the
    throughput model's gauges. Registered by SchedulerAPI exactly when
    the dealer's rater carries a model, so binpack/spread deployments
    export nothing new."""

    def __init__(self, dealer, model):
        self.dealer = dealer
        self.model = model

    def render(self) -> list[str]:
        out: list[str] = []
        values = self.model.gauge_values()
        for suffix in sorted(_THROUGHPUT_GAUGES):
            name = _FAMILY + suffix
            out.append(f"# HELP {name} {_THROUGHPUT_GAUGES[suffix]}")
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {float(values[suffix])}")
        out.append(
            f"# HELP {_MODELED} Modeled aggregate throughput of bound "
            "pods per snapshot shard (co-residency derated; "
            "docs/scoring.md)"
        )
        out.append(f"# TYPE {_MODELED} gauge")
        by_shard = modeled_aggregate_by_shard(self.dealer, self.model)
        if not by_shard:
            out.append(f'{_MODELED}{{shard="all"}} 0.0')
        for key in sorted(by_shard):
            out.append(
                f'{_MODELED}{{shard="{_escape_label_value(key)}"}} '
                f"{by_shard[key]}"
            )
        return out
