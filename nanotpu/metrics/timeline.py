"""``nanotpu_timeline_*`` exposition: the telemetry timeline's scrape
surface (docs/observability.md "The telemetry timeline").

Two kinds of series:

* unlabeled tick gauges — the keys of :data:`_TIMELINE_GAUGES`, produced
  by :meth:`Timeline.tick_gauge_values
  <nanotpu.obs.timeline.Timeline.tick_gauge_values>` from the newest
  retained tick. The nanolint metrics-completeness pass cross-checks the
  two tables BOTH directions (a gauge declared here but never produced,
  or produced there but never declared, is a lint finding) — the same
  honesty contract the resilience/throughput/recovery tables live under.
* ``nanotpu_timeline_pool_occupancy{pool=...}`` — per-pool occupancy
  from the newest tick's ``pools`` section, labeled by the same
  ``generation/slice-family`` key the snapshot shards use.

Scrapes read the RING, not the fleet: a tick is taken on the telemetry
cadence (sim event / production loop), so a scrape costs a dict walk
and never touches the dealer.
"""

from __future__ import annotations

from nanotpu.metrics.registry import _escape_label_value

_FAMILY = "nanotpu_timeline_"

#: gauge suffix -> help text. Keys must match Timeline.
#: tick_gauge_values() exactly — nanolint pins the equivalence both ways.
_TIMELINE_GAUGES: dict[str, str] = {
    "tick":
        "Sequence number of the newest telemetry tick (0 before the "
        "first; a stalled value means the telemetry cadence died)",
    "occupancy":
        "Fleet chip occupancy fraction at the newest tick",
    "fragmentation":
        "Two-level ICI fragmentation at the newest tick (0 = all free "
        "capacity contiguous)",
    "whole_free_chips":
        "Fully-free chips fleet-wide at the newest tick",
    "parked_gangs":
        "Distinct strict gangs with members parked at barriers at the "
        "newest tick",
    "parked_members":
        "Total parked strict-gang member reservations at the newest tick",
    "oldest_park_age_seconds":
        "Age of the oldest parked strict-gang reservation",
    "sources":
        "External TimelineSource producers currently registered",
}

_POOL = _FAMILY + "pool_occupancy"


class TimelineExporter:
    """Registry-compatible renderer (``Registry.register``) for the
    timeline's gauges. Registered exactly when a timeline is attached,
    so deployments without telemetry export nothing new."""

    def __init__(self, timeline):
        self.timeline = timeline

    def render(self) -> list[str]:
        out: list[str] = []
        values = self.timeline.tick_gauge_values()
        for suffix in sorted(_TIMELINE_GAUGES):
            name = _FAMILY + suffix
            out.append(f"# HELP {name} {_TIMELINE_GAUGES[suffix]}")
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {float(values[suffix])}")
        out.append(
            f"# HELP {_POOL} Per-pool chip occupancy fraction at the "
            "newest telemetry tick"
        )
        out.append(f"# TYPE {_POOL} gauge")
        latest = self.timeline.latest()
        pools = latest["pools"] if latest else {}
        if not pools:
            out.append(f'{_POOL}{{pool="all"}} 0.0')
        for key in sorted(pools):
            out.append(
                f'{_POOL}{{pool="{_escape_label_value(key)}"}} '
                f"{pools[key]['occupancy']}"
            )
        return out
