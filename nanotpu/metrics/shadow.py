"""``nanotpu_shadow_*`` exposition: shadow-mode A/B scrape surface
(docs/policy-programs.md).

The gauge values come from ONE producer —
:meth:`ShadowScorer.shadow_gauge_values
<nanotpu.policy_ir.shadow.ShadowScorer.shadow_gauge_values>` — so the
scrape surface, ``GET /debug/shadow``, and the sim's ``shadow`` report
section read the same numbers. The nanolint metrics-completeness pass
cross-checks :data:`_SHADOW_GAUGES` against that producer BOTH
directions (a suffix declared here but never produced, or produced
there but never declared, is a lint finding) — the same honesty
contract every other gauge family lives under. Registered only when a
shadow scorer is attached (``SchedulerAPI.attach_shadow``), so leaders
and shadow-less followers export nothing new."""

from __future__ import annotations

import logging

log = logging.getLogger("nanotpu.metrics.shadow")

_FAMILY = "nanotpu_shadow_"

#: gauge suffix -> help text. Keys must match
#: ShadowScorer.shadow_gauge_values() exactly — nanolint pins the
#: equivalence both ways.
_SHADOW_GAUGES: dict[str, str] = {
    "cycles":
        "Shadow scoring cycles this follower has run (one sampled "
        "demand scored against the whole snapshot per cycle)",
    "rows":
        "Feasible candidate rows scored by both the serving policy and "
        "the shadow candidate (infeasible rows are rater-independent "
        "and excluded)",
    "divergences":
        "Rows where the shadow candidate's score differed from the "
        "serving policy's wire score — each one is a typed "
        "shadow_divergence record in GET /debug/shadow",
    "max_abs_delta":
        "Largest |candidate - serving| score delta observed — how far "
        "the candidate would move a placement decision, worst case",
}


class ShadowExporter:
    """Registry-compatible renderer (``Registry.register``) for the
    shadow gauges."""

    def __init__(self, scorer):
        self.scorer = scorer

    def render(self) -> list[str]:
        out: list[str] = []
        try:
            values = self.scorer.shadow_gauge_values()
        except Exception:
            log.warning("shadow gauge producer failed", exc_info=True)
            return out
        for suffix in sorted(_SHADOW_GAUGES):
            name = _FAMILY + suffix
            out.append(f"# HELP {name} {_SHADOW_GAUGES[suffix]}")
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {float(values[suffix])}")
        return out
