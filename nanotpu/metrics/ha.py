"""``nanotpu_ha_*`` exposition: the HA pair's scrape surface (docs/ha.md).

The gauge values come from ONE producer —
:meth:`HACoordinator.ha_gauge_values
<nanotpu.ha.standby.HACoordinator.ha_gauge_values>` — so the scrape
surface and the timeline's ``ha`` section read the same numbers. The
nanolint metrics-completeness pass cross-checks :data:`_HA_GAUGES`
against that producer BOTH directions (a suffix declared here but never
produced, or produced there but never declared, is a lint finding) —
the same honesty contract the throughput/timeline/SLO/serving families
live under. The ``nanotpu_follower_*`` family (docs/read-plane.md) is
pinned the same way against
:meth:`HACoordinator.follower_gauge_values`, and registers only on
followers."""

from __future__ import annotations

import logging

log = logging.getLogger("nanotpu.metrics.ha")

_FAMILY = "nanotpu_ha_"

#: gauge suffix -> help text. Keys must match
#: HACoordinator.ha_gauge_values() exactly — nanolint pins the
#: equivalence both ways.
_HA_GAUGES: dict[str, str] = {
    "role":
        "This replica's HA role: 1 = active (holds the leader lease, "
        "serves writes), 0 = warm standby or read-serving follower "
        "(tails the delta stream)",
    "lag_events":
        "Delta records the active has emitted that this standby has not "
        "yet applied (0 on the active)",
    "lag_seconds":
        "Age of the newest applied delta while records are pending — "
        "how far behind the stream the standby's state is, in seconds",
    "applied_deltas":
        "Delta records this replica has applied into its own dealer "
        "since boot",
    "emitted_deltas":
        "Delta records this replica has emitted as the active (its "
        "standby tails these)",
    "promotions":
        "Standby-to-active promotions this process has performed",
    "reconciled_pods":
        "Pods reconciled against informer state during the last "
        "promotion (the lag window — O(delta), not O(fleet))",
    "apply_failures":
        "`bound` records that conflicted with stale local accounting "
        "(kept in the dirty window; the next reconcile heals them)",
    "tail_stale":
        "1 when the delta tail fell off the source ring and the next "
        "promotion must full-resync instead of the O(delta) window",
    "parked_noted":
        "Strict-gang reservations the active reported parked "
        "(bookkeeping only: reservations die with the active)",
    "fence_epoch":
        "The leader-lease epoch this replica's epoch fence is armed "
        "for (0 = no fence attached or lease never held) — every "
        "apiserver mutation is stamped with it (docs/ha.md)",
    "fence_valid":
        "1 while this replica can locally prove its lease term is "
        "still valid (renew + ttl - max_clock_skew); 0 = writes are "
        "fenced (typed FencedError, dealer rolls back)",
    "fence_rejections":
        "Apiserver writes fast-failed by the epoch fence because this "
        "replica could not prove it still held the lease — each one is "
        "a split-brain write that did NOT happen",
    "suspect_deltas":
        "Delta records skipped because their writer epoch predates the "
        "newest term seen (a superseded leader's stragglers; their "
        "pods reconcile against informer truth instead)",
    "verify_failures":
        "Post-promotion verify_state deep checks that found the "
        "dealer's placement accounting disagreeing with the live pod "
        "annotations (see GET /debug/verify)",
}


_FOLLOWER_FAMILY = "nanotpu_follower_"

#: gauge suffix -> help text for the read plane (docs/read-plane.md).
#: Keys must match HACoordinator.follower_gauge_values() exactly —
#: nanolint pins the equivalence both ways, same as _HA_GAUGES.
_FOLLOWER_GAUGES: dict[str, str] = {
    "lag_events":
        "Delta records the leader has emitted that this follower has "
        "not yet applied — the read plane's staleness, in events",
    "lag_seconds":
        "Age of the newest applied delta while records are pending — "
        "the read plane's staleness, in seconds",
    "lag_bound_events":
        "The configured staleness bound: reads answer 503 NotSynced "
        "once lag_events exceeds it (0 = unbounded)",
    "synced":
        "1 while this follower is inside its staleness bound and "
        "serving reads; 0 = reads refuse with 503 NotSynced",
    "draining":
        "1 while the operator has pulled this follower out of read "
        "rotation (rolling upgrade); the tail keeps running",
    "reads_refused":
        "Filter/Prioritize requests refused with 503 NotSynced because "
        "the tail lag exceeded the staleness bound",
    "tail_retries":
        "Delta-tail re-fetches attempted after a failed fetch's "
        "jittered backoff window elapsed (transport or crc failure)",
}


class HAExporter:
    """Registry-compatible renderer (``Registry.register``) for the HA
    gauges. Registered exactly when a coordinator is attached
    (``SchedulerAPI.attach_ha``), so single-replica deployments export
    nothing new."""

    def __init__(self, coordinator):
        self.coordinator = coordinator

    def render(self) -> list[str]:
        out: list[str] = []
        try:
            values = self.coordinator.ha_gauge_values()
        except Exception:
            log.warning("ha gauge producer failed", exc_info=True)
            return out
        for suffix in sorted(_HA_GAUGES):
            name = _FAMILY + suffix
            out.append(f"# HELP {name} {_HA_GAUGES[suffix]}")
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {float(values[suffix])}")
        return out


class FollowerExporter:
    """The ``nanotpu_follower_*`` family: registered by ``attach_ha``
    exactly when the coordinator's role is ``follower``, so leaders,
    standbys, and single-replica deployments export nothing new
    (docs/read-plane.md)."""

    def __init__(self, coordinator):
        self.coordinator = coordinator

    def render(self) -> list[str]:
        out: list[str] = []
        try:
            values = self.coordinator.follower_gauge_values()
        except Exception:
            log.warning("follower gauge producer failed", exc_info=True)
            return out
        for suffix in sorted(_FOLLOWER_GAUGES):
            name = _FOLLOWER_FAMILY + suffix
            out.append(f"# HELP {name} {_FOLLOWER_GAUGES[suffix]}")
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {float(values[suffix])}")
        return out
