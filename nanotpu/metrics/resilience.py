"""Overload/degradation attribution counters (`nanotpu_resilience_*`).

The overload-resilience layer (admission gate, per-verb deadlines, the
coalescing controller queue, the assume-TTL sweeper, the K8s write
breaker) *deliberately drops work* when the box or the API is unhealthy.
Every such drop must be attributable, or "graceful degradation" is
indistinguishable from a silent bug: these counters are the one ledger
all of those layers write to, exported live on ``/metrics`` and
snapshotted into the sim's deterministic report so a chaos run can prove
that every shed request, coalesced sync, expired reservation, and
fast-failed write was counted.

One instance is shared process-wide (cmd/main wires it through server,
controller, recorder, and client wrapper). Increments take a lock —
these are degradation paths, not the scheduling hot path, and exactness
is the point.
"""

from __future__ import annotations

from nanotpu.analysis.witness import make_lock

#: scalar counter fields and their Prometheus names
_SCALARS = {
    "queue_coalesced": (
        "nanotpu_resilience_queue_coalesced_total",
        "Controller sync-queue puts absorbed by an already-queued entry "
        "for the same pod (latest event wins)",
    ),
    "queue_dropped": (
        "nanotpu_resilience_queue_dropped_total",
        "Controller watch-event syncs shed because the bounded queue was "
        "full (periodic resync repairs the divergence)",
    ),
    "assume_expired": (
        "nanotpu_resilience_assume_expired_total",
        "Assumed-but-never-bound pods whose placement annotations the "
        "TTL sweeper expired and rolled back",
    ),
    "events_failopen": (
        "nanotpu_resilience_events_failopen_total",
        "K8s Events dropped open (queue full, breaker open, or retries "
        "exhausted) instead of blocking or failing scheduling",
    ),
    "events_unflushed": (
        "nanotpu_resilience_events_unflushed_total",
        "K8s Events still unposted when a shutdown flush timed out",
    ),
}

#: labeled counter fields: field -> (metric name, label key, help)
_LABELED = {
    "shed": (
        "nanotpu_resilience_shed_total", "verb",
        "Verb requests shed by the admission gate with 429 + Retry-After "
        "(Bind is never shed)",
    ),
    "deadline_expired": (
        "nanotpu_resilience_deadline_expired_total", "verb",
        "Verb requests aborted past their response budget (503; the "
        "budget derives from the extender httpTimeout contract)",
    ),
    "api_retries": (
        "nanotpu_resilience_api_retries_total", "target",
        "K8s API write retries spent by the resilient client wrapper",
    ),
    "breaker_opens": (
        "nanotpu_resilience_breaker_open_total", "target",
        "Circuit-breaker open transitions per write target",
    ),
    "breaker_fastfails": (
        "nanotpu_resilience_breaker_fastfail_total", "target",
        "K8s API writes fast-failed without a request because the "
        "target's breaker was open",
    ),
}


class ResilienceCounters:
    """Process-lifetime degradation ledger; see module docstring."""

    def __init__(self):
        self._lock = make_lock("ResilienceCounters._lock")
        for name in _SCALARS:
            setattr(self, name, 0)
        for name in _LABELED:
            setattr(self, name, {})

    def inc(self, field: str, key: str | None = None, n: int = 1) -> None:
        """Bump scalar ``field`` (key=None) or its per-``key`` series."""
        with self._lock:
            cur = getattr(self, field)  # unknown field -> AttributeError
            if isinstance(cur, dict):
                cur[key] = cur.get(key, 0) + n
            else:
                setattr(self, field, cur + n)

    def get(self, field: str, key: str | None = None) -> int:
        with self._lock:
            cur = getattr(self, field)
            return cur.get(key, 0) if isinstance(cur, dict) else cur

    def snapshot(self) -> dict:
        """Point-in-time copy: scalar fields as ints, labeled as sorted
        dicts (the sim report embeds this; key order must be stable)."""
        with self._lock:
            out: dict = {name: getattr(self, name) for name in _SCALARS}
            for name in _LABELED:
                out[name] = dict(sorted(getattr(self, name).items()))
            return out


class ResilienceExporter:
    """Registry-compatible renderer (``Registry.register``) exposing every
    counter in Prometheus text format."""

    def __init__(self, counters: ResilienceCounters):
        self.counters = counters

    def render(self) -> list[str]:
        snap = self.counters.snapshot()
        out: list[str] = []
        for field, (metric, help_) in _SCALARS.items():
            out += [f"# HELP {metric} {help_}", f"# TYPE {metric} counter",
                    f"{metric} {snap[field]}"]
        for field, (metric, label, help_) in _LABELED.items():
            out += [f"# HELP {metric} {help_}", f"# TYPE {metric} counter"]
            series = snap[field] or {"": 0}
            for key, val in series.items():
                lbl = f'{{{label}="{key}"}}' if key else ""
                out.append(f"{metric}{lbl} {val}")
        return out
