"""``nanotpu_sched_defrag_*`` / ``nanotpu_gang_backfill_*`` exposition:
the capacity-recovery plane's observable surface (docs/defrag.md).

Every deliberate capacity-recovery action — a preempted pod, a defrag
migration, a backfill lease granted or expired, a budget cap hit — is a
counter here, under the same honesty contract the resilience counters
live under: the :data:`_RECOVERY_METRICS` table (which the exporter
renders) and the :class:`RecoveryCounters` slots (which the plane bumps
as ``self.counters.<slot> += 1``) are cross-checked BOTH directions by
the nanolint metrics-completeness pass, so a slot nobody bumps or a bump
nobody exports is a lint finding, not a lying zero on ``/metrics``.

Two live gauges ride along from plane state rather than the counters:
open gang holes and active backfill leases.
"""

from __future__ import annotations


class RecoveryCounters:
    """Monotonic counters for the capacity-recovery plane. Bumped on the
    recovery cycle (sim: the single event thread; production: the
    recovery loop thread) — never on the verb hot path."""

    __slots__ = (
        "recovery_cycles",
        "preempted_pods",
        "preempt_infeasible",
        "eviction_budget_hits",
        "migrated_pods",
        "migration_failures",
        "migration_budget_hits",
        "holes_opened",
        "holes_closed",
        "backfill_leases",
        "backfill_lease_expiries",
        "drain_leases",
        "drain_lease_expiries",
    )

    def __init__(self):
        #: run_once invocations (the defragmenter/preemption loop ticks)
        self.recovery_cycles = 0
        #: lower-priority pods evicted (placement stripped + requeued) for
        #: a parked higher-priority gang
        self.preempted_pods = 0
        #: parked gang members no eviction set could make feasible this
        #: cycle (fleet genuinely full at or above their priority)
        self.preempt_infeasible = 0
        #: cycles that stopped evicting because the per-cycle eviction
        #: budget was exhausted (preemption can never thrash: the cap is
        #: the proof)
        self.eviction_budget_hits = 0
        #: pods moved by the defragmenter (annotation rewrite +
        #: assume/forget replay through Dealer.migrate)
        self.migrated_pods = 0
        #: migrations whose annotation write failed (brownout, breaker);
        #: accounting rolled back, source placement intact
        self.migration_failures = 0
        #: cycles that stopped migrating at the per-cycle migration budget
        self.migration_budget_hits = 0
        #: gang holes opened (capacity earmarked for a parked gang) and
        #: closed (gang bound / departed / hole TTL)
        self.holes_opened = 0
        self.holes_closed = 0
        #: backfill leases granted (short low-priority pod admitted into a
        #: reserved-but-waiting hole) and leases that EXPIRED with the pod
        #: still running (pod evicted, reason ``lease_expired``)
        self.backfill_leases = 0
        self.backfill_lease_expiries = 0
        #: scale-down drain leases granted (a serving replica finishing
        #: in-flight requests under a deadline, docs/serving-loop.md) and
        #: leases that EXPIRED with requests still in flight (pod
        #: deleted, reason ``drain_expired``)
        self.drain_leases = 0
        self.drain_lease_expiries = 0

    def snapshot(self) -> dict[str, int]:
        """Point-in-time copy (report sections / metrics render)."""
        return {name: getattr(self, name) for name in self.__slots__}


#: counter slot -> (full metric name, help). Keys must be exactly the
#: RecoveryCounters slots — nanolint pins the equivalence both ways.
_RECOVERY_METRICS: dict[str, tuple[str, str]] = {
    "recovery_cycles": (
        "nanotpu_sched_defrag_cycles_total",
        "Capacity-recovery cycles run (preemption + defragmentation + "
        "lease sweep)",
    ),
    "preempted_pods": (
        "nanotpu_sched_defrag_preempted_pods_total",
        "Lower-priority pods evicted and requeued for a parked "
        "higher-priority gang",
    ),
    "preempt_infeasible": (
        "nanotpu_sched_defrag_preempt_infeasible_total",
        "Parked gang members no eviction set could make feasible",
    ),
    "eviction_budget_hits": (
        "nanotpu_sched_defrag_eviction_budget_hits_total",
        "Recovery cycles that stopped evicting at the per-cycle "
        "eviction budget",
    ),
    "migrated_pods": (
        "nanotpu_sched_defrag_migrated_pods_total",
        "Pods moved by the defragmenter (annotation rewrite + "
        "assume/forget replay)",
    ),
    "migration_failures": (
        "nanotpu_sched_defrag_migration_failures_total",
        "Migrations rolled back on a failed annotation write",
    ),
    "migration_budget_hits": (
        "nanotpu_sched_defrag_migration_budget_hits_total",
        "Recovery cycles that stopped migrating at the per-cycle "
        "migration budget",
    ),
    "holes_opened": (
        "nanotpu_sched_defrag_holes_opened_total",
        "Gang holes opened (capacity earmarked for a parked gang)",
    ),
    "holes_closed": (
        "nanotpu_sched_defrag_holes_closed_total",
        "Gang holes closed (gang bound, departed, or hole TTL elapsed)",
    ),
    "backfill_leases": (
        "nanotpu_gang_backfill_leases_total",
        "Backfill leases granted inside reserved-but-waiting gang holes",
    ),
    "backfill_lease_expiries": (
        "nanotpu_gang_backfill_lease_expiries_total",
        "Backfill leases that expired with the pod still running "
        "(pod evicted, reason lease_expired)",
    ),
    "drain_leases": (
        "nanotpu_serving_drain_leases_total",
        "Scale-down drain leases granted (serving replica finishing "
        "in-flight requests under a deadline, docs/serving-loop.md)",
    ),
    "drain_lease_expiries": (
        "nanotpu_serving_drain_lease_expiries_total",
        "Drain leases that expired with requests still in flight "
        "(replica pod deleted, reason drain_expired)",
    ),
}

#: live-state gauges rendered from the plane, not the counters
_HOLES_GAUGE = "nanotpu_sched_defrag_holes_open"
_LEASES_GAUGE = "nanotpu_gang_backfill_active_leases"


class RecoveryExporter:
    """Registry-compatible renderer (``Registry.register``) for the
    recovery plane's counters + live hole/lease gauges. Registered
    exactly when a recovery plane is attached, so deployments without
    one export nothing new."""

    def __init__(self, plane):
        self.plane = plane

    def render(self) -> list[str]:
        out: list[str] = []
        snap = self.plane.counters.snapshot()
        for slot in sorted(_RECOVERY_METRICS):
            name, help_text = _RECOVERY_METRICS[slot]
            out.append(f"# HELP {name} {help_text}")
            out.append(f"# TYPE {name} counter")
            out.append(f"{name} {snap[slot]}")
        status = self.plane.status()
        for name, help_text, value in (
            (_HOLES_GAUGE, "Gang holes currently open", status["holes"]),
            (_LEASES_GAUGE, "Backfill leases currently active",
             status["leases"]),
        ):
            out.append(f"# HELP {name} {help_text}")
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {value}")
        return out
