"""Exact sample statistics for reports (sim, bench).

:class:`~nanotpu.metrics.registry.Histogram` serves Prometheus exposition,
where bucketed quantiles are the right trade; reports want EXACT
percentiles over the full sample set (bench.py's p99 convention:
``sorted(xs)[ceil(0.99 * n) - 1]``). One implementation here so the sim
report, bench, and any future trajectory tooling agree on what "p99"
means.
"""

from __future__ import annotations

import math


def percentile(samples: list[float], p: float) -> float | None:
    """Exact p-quantile (0 < p <= 1) by the nearest-rank method; None on an
    empty sample set."""
    if not samples:
        return None
    xs = sorted(samples)
    return xs[min(len(xs) - 1, max(0, math.ceil(p * len(xs)) - 1))]


def summarize(samples: list[float], scale: float = 1.0,
              digits: int = 3) -> dict | None:
    """p50/p95/p99/mean/max/count summary, values scaled (e.g. s -> ms)
    and rounded for stable JSON. None when there are no samples."""
    if not samples:
        return None
    xs = sorted(samples)
    n = len(xs)

    def r(v: float) -> float:
        return round(v * scale, digits)

    return {
        "count": n,
        "p50": r(percentile(xs, 0.50)),
        "p95": r(percentile(xs, 0.95)),
        "p99": r(percentile(xs, 0.99)),
        "mean": r(sum(xs) / n),
        "max": r(xs[-1]),
    }
