"""``nanotpu_fleet_*`` exposition: the fleet aggregation plane's scrape
surface (docs/observability.md "Fleet observability").

The gauge values come from ONE producer —
:meth:`FleetView.fleet_gauge_values
<nanotpu.obs.fleet.FleetView.fleet_gauge_values>` — so the scrape
surface and ``GET /debug/fleet`` read the same numbers. The nanolint
metrics-completeness pass cross-checks :data:`_FLEET_GAUGES` against
that producer BOTH directions (a suffix declared here but never
produced, or produced there but never declared, is a lint finding) —
the same honesty contract the ha/follower/shadow families live under.
Registered only when a view is attached (``SchedulerAPI.attach_fleet``),
so every existing deployment's ``/metrics`` body is unchanged."""

from __future__ import annotations

import logging

log = logging.getLogger("nanotpu.metrics.fleet")

_FAMILY = "nanotpu_fleet_"

#: gauge suffix -> help text. Keys must match
#: FleetView.fleet_gauge_values() exactly — nanolint pins the
#: equivalence both ways.
_FLEET_GAUGES: dict[str, str] = {
    "peers":
        "Replicas this leader's fleet view polls (the --ha-peers list; "
        "excludes the local process)",
    "peers_synced":
        "Replicas inside their read-plane staleness bound at the last "
        "fleet poll (actives always count; the local replica included)",
    "max_lag_events":
        "The worst delta-stream lag across the fleet at the last poll, "
        "in events — the fleet's read-staleness headline number",
    "stories_served":
        "GET /debug/story/<uid> cross-replica joins this process has "
        "served",
    "export_bytes":
        "Bytes framed into the durable decision export over this "
        "process's lifetime — across rotations, so the gauge is "
        "monotonic even though the live segment is size-bounded",
    "export_rotations":
        "Export segment rotations (live segment reached --obs-export-"
        "max-bytes and was renamed to <path>.1)",
    "export_drops":
        "Export records lost to sink write failures (counted, never "
        "raised — the export is forensics, the scheduler outlives it)",
}


class FleetExporter:
    """Registry-compatible renderer (``Registry.register``) for the
    fleet gauges. Registered exactly when a view is attached
    (``SchedulerAPI.attach_fleet``), so fleet-less deployments export
    nothing new."""

    def __init__(self, view):
        self.view = view

    def render(self) -> list[str]:
        out: list[str] = []
        try:
            values = self.view.fleet_gauge_values()
        except Exception:
            log.warning("fleet gauge producer failed", exc_info=True)
            return out
        for suffix in sorted(_FLEET_GAUGES):
            name = _FAMILY + suffix
            out.append(f"# HELP {name} {_FLEET_GAUGES[suffix]}")
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {float(values[suffix])}")
        return out
