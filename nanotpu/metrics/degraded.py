"""``nanotpu_degraded_*`` exposition: the degraded-mode scrape surface.

The gauge values come from ONE producer —
:meth:`DegradedMonitor.degraded_gauge_values
<nanotpu.ha.degraded.DegradedMonitor.degraded_gauge_values>` — so the
scrape surface and the timeline's ``degraded`` tick section read the
same numbers. The nanolint metrics-completeness pass cross-checks
:data:`_DEGRADED_GAUGES` against that producer BOTH directions — the
same honesty contract the throughput/timeline/SLO/serving/HA families
live under (docs/ha.md "Degraded mode")."""

from __future__ import annotations

import logging

log = logging.getLogger("nanotpu.metrics.degraded")

_FAMILY = "nanotpu_degraded_"

#: gauge suffix -> help text. Keys must match
#: DegradedMonitor.degraded_gauge_values() exactly — nanolint pins the
#: equivalence both ways.
_DEGRADED_GAUGES: dict[str, str] = {
    "active":
        "1 while this replica is in degraded mode (apiserver writes "
        "failing past budget): binds 503 with Retry-After, reads keep "
        "answering from RCU snapshots, write loops paused",
    "entries":
        "Degraded-mode entries since boot (apiserver unreachable past "
        "the configured budget of continuous write failure)",
    "exits":
        "Degraded-mode exits (the first successful apiserver write "
        "resumes binds and write loops — no restart needed)",
    "binds_rejected":
        "Bind/batchadmit requests answered 503 Degraded + Retry-After "
        "while in degraded mode (kube-scheduler retries them)",
    "failures_in_mode":
        "Apiserver write failures observed WHILE degraded — the doomed "
        "traffic the mode absorbed instead of burning retries on",
    "current_seconds":
        "Seconds spent in the CURRENT degraded episode (0 when healthy)",
    "total_seconds":
        "Cumulative seconds spent degraded since boot",
}


class DegradedExporter:
    """Registry-compatible renderer (``Registry.register``) for the
    degraded-mode gauges. Registered exactly when a monitor is attached
    (``SchedulerAPI.attach_degraded``), so deployments without one
    export nothing new."""

    def __init__(self, monitor):
        self.monitor = monitor

    def render(self) -> list[str]:
        out: list[str] = []
        try:
            values = self.monitor.degraded_gauge_values()
        except Exception:
            log.warning("degraded gauge producer failed", exc_info=True)
            return out
        for suffix in sorted(_DEGRADED_GAUGES):
            name = _FAMILY + suffix
            out.append(f"# HELP {name} {_DEGRADED_GAUGES[suffix]}")
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {float(values[suffix])}")
        return out
