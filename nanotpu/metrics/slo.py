"""SLO burn-rate watchdog over the telemetry timeline (`nanotpu_slo_*`).

Objectives are DECLARED, not coded: the ``slo:`` section of policy.yaml
(hot-reloaded through the existing :class:`~nanotpu.policy.PolicyWatcher`
— a config push, not a deploy) or the sim scenario's ``telemetry.slo``
list, both validated by :func:`parse_objectives`. Each objective names a
timeline series and is evaluated with the classic TWO-WINDOW burn rate
(docs/observability.md "SLO burn rates"):

    bad_fraction(W) = bad events / total events over window W
    burn_rate(W)    = bad_fraction(W) / (1 - target)

A burn rate of 1.0 means the error budget is being consumed exactly at
the rate that exhausts it over the objective's horizon; the watchdog
trips when BOTH the long window (sustained — filters blips) and the
short window (still happening — clears fast after recovery) reach the
objective's ``burn`` factor. Breach/clear are edge-triggered: one typed
ledger reason (``slo_breach``, aggregated uid-less so a breach storm can
never evict placement records), one ``nanotpu_slo_breach_total{slo=}``
bump, one journal line in the sim, one flight-recorder bundle.

Three objective kinds, each reading per-tick data from the ring:

* ``threshold`` — the tick is good iff ``series <op> threshold`` (e.g.
  occupancy floor: ``fleet.occupancy ge 0.5``). One event per tick.
* ``latency``  — ``series`` names a verb histogram section
  (``verbs.filter``); good events are the requests in buckets
  ``le <= threshold``, bad the remainder (Filter p99 vs the 2 s
  extender read budget is ``threshold: 2.0, target: 0.99``).
* ``ratio``    — ``bad`` and ``total`` name per-tick delta series
  (e.g. bind error rate: bad = breaker fast-fails + API errors, total
  = bind attempts).

The unlabeled ``nanotpu_slo_*`` gauges are the keys of
:data:`_SLO_GAUGES`, produced by :meth:`SLOWatchdog.slo_gauge_values` —
the nanolint metrics-completeness pass cross-checks the two BOTH
directions, the same honesty contract every other exported table lives
under. Per-objective series (`breach_total`, `burn_rate`, `breached`)
render labeled from watchdog state, like the throughput exporter's
per-shard aggregate.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from nanotpu.analysis.witness import make_lock
from nanotpu.metrics.registry import _escape_label_value
from nanotpu.obs.decisions import REASON_SLO_BREACH

_FAMILY = "nanotpu_slo_"

#: gauge suffix -> help text. Keys must match slo_gauge_values() exactly
#: — nanolint pins the equivalence both ways.
_SLO_GAUGES: dict[str, str] = {
    "objectives":
        "SLO objectives currently configured (policy.yaml slo: section)",
    "evaluations_total":
        "Watchdog evaluation passes over the timeline ring",
    "breaches_total":
        "SLO breach transitions across all objectives (per-objective "
        "counts ride on nanotpu_slo_breach_total{slo=})",
    "objectives_breached":
        "Objectives currently in breach (both burn windows over factor)",
}

_KINDS = ("threshold", "latency", "ratio")
_OPS = ("ge", "le")


@dataclass(frozen=True)
class SLObjective:
    """One declared objective (see module docstring for the kinds)."""

    name: str
    kind: str
    series: str = ""       # threshold/latency: dotted tick path
    bad: str = ""          # ratio: dotted path of the bad-event delta
    total: str = ""        # ratio: dotted path of the total-event delta
    op: str = "le"         # threshold kind: good iff value <op> threshold
    threshold: float = 0.0
    target: float = 0.99   # required good fraction; budget = 1 - target
    long_s: float = 300.0
    short_s: float = 30.0
    burn: float = 1.0      # burn-rate factor that trips the alert


def parse_objectives(raw) -> tuple[SLObjective, ...]:
    """Validate a list of objective dicts (YAML ``slo:`` section /
    scenario ``telemetry.slo``) into frozen :class:`SLObjective`s.
    Raises ValueError naming the bad entry — a policy hot-reload with a
    malformed section keeps the last good spec, a scenario fails load."""
    if raw is None:
        return ()
    if not isinstance(raw, (list, tuple)):
        raise ValueError("slo section must be a list of objectives")
    out: list[SLObjective] = []
    seen: set[str] = set()
    for entry in raw:
        if isinstance(entry, SLObjective):
            # already parsed (scenario re-normalization is idempotent)
            if entry.name in seen:
                raise ValueError(f"duplicate slo objective {entry.name!r}")
            seen.add(entry.name)
            out.append(entry)
            continue
        if not isinstance(entry, dict):
            raise ValueError(f"bad slo objective {entry!r}: not a mapping")
        try:
            name = str(entry["name"])
            if not name or name in seen:
                raise ValueError("name must be unique and non-empty")
            seen.add(name)
            kind = str(entry.get("kind", "threshold"))
            if kind not in _KINDS:
                raise ValueError(f"kind must be one of {_KINDS}")
            series = str(entry.get("series", ""))
            bad = str(entry.get("bad", ""))
            total = str(entry.get("total", ""))
            if kind == "ratio":
                if not bad or not total:
                    raise ValueError("ratio kind needs bad and total paths")
            elif not series:
                raise ValueError(f"{kind} kind needs a series path")
            op = str(entry.get("op", "le"))
            if op not in _OPS:
                raise ValueError(f"op must be one of {_OPS}")
            threshold = float(entry.get("threshold", 0.0))
            if kind == "latency" and threshold <= 0:
                # no histogram bucket bound is <= 0, so a defaulted/typoed
                # threshold would classify EVERY request as bad and fire
                # a spurious breach on the first evaluation with traffic
                raise ValueError("latency kind needs threshold > 0")
            target = float(entry.get("target", 0.99))
            if not 0.0 < target < 1.0:
                raise ValueError("target must be in (0, 1)")
            long_s = float(entry.get("long_s", 300.0))
            short_s = float(entry.get("short_s", 30.0))
            if not 0.0 < short_s <= long_s:
                raise ValueError("windows need 0 < short_s <= long_s")
            burn = float(entry.get("burn", 1.0))
            if burn <= 0:
                raise ValueError("burn must be > 0")
            out.append(SLObjective(
                name=name, kind=kind, series=series, bad=bad, total=total,
                op=op, threshold=threshold,
                target=target, long_s=long_s, short_s=short_s, burn=burn,
            ))
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"bad slo objective {entry!r}: {e}") from e
    return tuple(out)


def _resolve(tick: dict, path: str):
    """Dotted-path lookup into a tick; None when any hop is missing."""
    node = tick
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


def _events(obj: SLObjective, tick: dict) -> tuple[float, float]:
    """(good, bad) event counts one tick contributes to ``obj``."""
    if obj.kind == "threshold":
        value = _resolve(tick, obj.series)
        if not isinstance(value, (int, float)):
            return 0.0, 0.0
        good = value >= obj.threshold if obj.op == "ge" \
            else value <= obj.threshold
        return (1.0, 0.0) if good else (0.0, 1.0)
    if obj.kind == "latency":
        section = _resolve(tick, obj.series)
        if not isinstance(section, dict):
            return 0.0, 0.0
        count = float(section.get("count", 0) or 0)
        if count <= 0:
            return 0.0, 0.0
        good = 0.0
        for le, n in (section.get("le") or {}).items():
            try:
                bound = float(le)
            except ValueError:
                continue
            if bound <= obj.threshold:
                good += n
        good = min(good, count)
        return good, count - good
    # ratio
    bad = _resolve(tick, obj.bad)
    total = _resolve(tick, obj.total)
    bad = float(bad) if isinstance(bad, (int, float)) else 0.0
    total = float(total) if isinstance(total, (int, float)) else 0.0
    if total <= 0:
        return 0.0, 0.0
    bad = min(bad, total)
    return total - bad, bad


class SLOWatchdog:
    """Evaluates declared objectives over the timeline ring; see module
    docstring. ``configure`` is hot-reload-safe (PolicyWatcher.on_reload
    hands it each new spec); state for objectives that survive a reload
    is kept, so a table edit cannot reset breach counters."""

    def __init__(self, timeline, obs=None, clock=time.monotonic):
        self.timeline = timeline
        self.obs = obs
        self.clock = clock
        self._lock = make_lock("SLOWatchdog._lock")
        self._objectives: tuple[SLObjective, ...] = ()
        #: name -> {"breached", "breaches", "burn_long", "burn_short"}
        self._state: dict[str, dict] = {}
        self.evaluations = 0

    def configure(self, objectives) -> None:
        """Install a new objective set (tuple of :class:`SLObjective`,
        or raw dicts run through :func:`parse_objectives`)."""
        if objectives and not isinstance(objectives[0], SLObjective):
            objectives = parse_objectives(objectives)
        objectives = tuple(objectives or ())
        with self._lock:
            self._objectives = objectives
            names = {o.name for o in objectives}
            for name in list(self._state):
                if name not in names:
                    del self._state[name]
            for obj in objectives:
                self._state.setdefault(obj.name, {
                    "breached": False, "breaches": 0,
                    "burn_long": 0.0, "burn_short": 0.0,
                })

    def _burn(self, obj: SLObjective, ticks: list[dict],
              now: float, window_s: float) -> float:
        good = bad = 0.0
        for tick in ticks:
            if tick["t"] < now - window_s:
                continue
            g, b = _events(obj, tick)
            good += g
            bad += b
        total = good + bad
        if total <= 0:
            return 0.0  # no data is no burn, not a breach
        return (bad / total) / max(1e-9, 1.0 - obj.target)

    def evaluate(self, now: float | None = None) -> list[dict]:
        """One watchdog pass: recompute both burn windows per objective
        and return the edge transitions (``{"event": "breach"|"clear",
        "name", "burn_long", "burn_short"}``). Breach transitions bump
        the per-objective counter and land in the decision ledger as
        the typed uid-less ``slo_breach`` aggregate."""
        if now is None:
            now = self.clock()
        ticks = self.timeline.since(0)
        transitions: list[dict] = []
        with self._lock:
            self.evaluations += 1
            for obj in self._objectives:
                state = self._state[obj.name]
                burn_long = self._burn(obj, ticks, now, obj.long_s)
                burn_short = self._burn(obj, ticks, now, obj.short_s)
                state["burn_long"] = round(burn_long, 6)
                state["burn_short"] = round(burn_short, 6)
                breached = burn_long >= obj.burn and burn_short >= obj.burn
                if breached and not state["breached"]:
                    state["breached"] = True
                    state["breaches"] += 1
                    transitions.append({
                        "event": "breach", "name": obj.name,
                        "burn_long": state["burn_long"],
                        "burn_short": state["burn_short"],
                    })
                elif state["breached"] and not breached:
                    state["breached"] = False
                    transitions.append({
                        "event": "clear", "name": obj.name,
                        "burn_long": state["burn_long"],
                        "burn_short": state["burn_short"],
                    })
        if self.obs is not None:
            for tr in transitions:
                if tr["event"] == "breach":
                    # uid-less aggregate ("slo_breach:<name>"), never a
                    # ring record: a breach storm must not evict the
                    # per-pod placement records (docs/observability.md)
                    self.obs.ledger.abort(
                        "", tr["name"], REASON_SLO_BREACH
                    )
        return transitions

    # -- exposition --------------------------------------------------------
    def status(self) -> dict:
        """Per-objective state for ``/debug/timeline`` (sorted keys)."""
        with self._lock:
            return {
                name: dict(self._state[name])
                for name in sorted(self._state)
            }

    def slo_gauge_values(self) -> dict:
        """Unlabeled ``nanotpu_slo_*`` gauge values. Keys must match
        :data:`_SLO_GAUGES` exactly (nanolint pins both directions)."""
        with self._lock:
            return {
                "objectives": len(self._objectives),
                "evaluations_total": self.evaluations,
                "breaches_total": sum(
                    s["breaches"] for s in self._state.values()
                ),
                "objectives_breached": sum(
                    1 for s in self._state.values() if s["breached"]
                ),
            }


class SLOExporter:
    """Registry-compatible renderer (``Registry.register``) for the
    watchdog's gauges + per-objective series. Registered exactly when a
    watchdog is attached, so deployments without telemetry export
    nothing new."""

    def __init__(self, watchdog: SLOWatchdog):
        self.watchdog = watchdog

    def render(self) -> list[str]:
        out: list[str] = []
        values = self.watchdog.slo_gauge_values()
        for suffix in sorted(_SLO_GAUGES):
            name = _FAMILY + suffix
            out.append(f"# HELP {name} {_SLO_GAUGES[suffix]}")
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {float(values[suffix])}")
        status = self.watchdog.status()
        breach = _FAMILY + "breach_total"
        out.append(
            f"# HELP {breach} SLO breach transitions per objective "
            "(two-window burn rate both over factor)"
        )
        out.append(f"# TYPE {breach} counter")
        for name in sorted(status):
            out.append(
                f'{breach}{{slo="{_escape_label_value(name)}"}} '
                f"{status[name]['breaches']}"
            )
        burn = _FAMILY + "burn_rate"
        out.append(
            f"# HELP {burn} Current error-budget burn rate per objective "
            "and window (1.0 consumes the budget exactly at horizon)"
        )
        out.append(f"# TYPE {burn} gauge")
        for name in sorted(status):
            esc = _escape_label_value(name)
            out.append(
                f'{burn}{{slo="{esc}",window="long"}} '
                f"{status[name]['burn_long']}"
            )
            out.append(
                f'{burn}{{slo="{esc}",window="short"}} '
                f"{status[name]['burn_short']}"
            )
        breached = _FAMILY + "breached"
        out.append(
            f"# HELP {breached} Whether each objective is currently in "
            "breach (1) or inside SLO (0)"
        )
        out.append(f"# TYPE {breached} gauge")
        for name in sorted(status):
            out.append(
                f'{breached}{{slo="{_escape_label_value(name)}"}} '
                f"{int(status[name]['breached'])}"
            )
        return out
