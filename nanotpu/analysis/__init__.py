"""nanolint: project-specific static analysis + runtime concurrency witness.

PRs 2-3 grew a real concurrent control plane — RCU snapshot publishing in
the dealer, a coalescing workqueue, per-target circuit breakers, deadline
tokens threaded server -> dealer — whose correctness rests on conventions
(lock order, snapshot immutability, injected clock/rng in sim-driven
code, attributable degradation counters) that code review alone cannot
hold. This package is the machine check for those conventions:

* **Static passes** (stdlib ``ast``, no new deps) run via
  ``python -m nanotpu.analysis`` (``make lint``, part of ``make all``):

  - ``lock-discipline``       lock-order cycles + blocking calls under
                              the dealer's hot locks
  - ``snapshot-immutability`` attribute stores on published ``_Snapshot``
                              / frozen ``BatchScorer`` state outside the
                              publisher path
  - ``deadline-threading``    verb-path functions that drop the
                              ``Deadline`` token instead of forwarding it
  - ``sim-determinism``       wall clock, ambient randomness, and
                              unordered-set iteration in sim-driven code
  - ``metrics-completeness``  counters incremented but not exported (and
                              exported but never incremented)

  See docs/static-analysis.md for the pass catalogue and the
  ``# nanolint: ignore[<pass>]: <justification>`` escape hatch.

* **Runtime witness** (:mod:`nanotpu.analysis.witness`): an opt-in
  instrumented lock wrapper (``NANOTPU_LOCK_WITNESS=1`` — tests and the
  chaos soak turn it on) that records the global lock-acquisition-order
  graph across threads and asserts acyclicity at teardown, turning a
  latent lock inversion into a deterministic failure with a witness
  stack for each edge of the cycle.

This ``__init__`` stays import-light on purpose: production modules
import :mod:`nanotpu.analysis.witness` for their lock factories, and that
must not drag the analysis framework (or anything heavier) along.
"""
