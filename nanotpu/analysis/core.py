"""nanolint framework: module loading, findings, the ignore budget.

A *pass* is an object with ``name``, ``doc``, ``scope`` (dotted module
prefixes it applies to — fixture modules outside the ``nanotpu`` package
are always in scope so tests can feed seeded violations), and
``run(modules) -> list[Finding]``. Passes are pure AST walks: no imports
of the code under analysis, so a module with a syntax error or an
unimportable dependency still gets analyzed (or reported as unparsable)
without executing anything.

The escape hatch::

    risky_call()  # nanolint: ignore[lock-discipline]: probe cannot block
                  # here - the node is already materialized

suppresses findings of the named pass(es) on that line (a directive on a
comment-only line covers the next line). Every ignore MUST carry a
justification after the closing bracket — the report lists all of them,
and an ignore without one is itself a finding (``ignore-budget``), so
silencing the linter is always a reviewed, explained act. An ignore that
suppresses nothing is reported too (stale ignores rot into camouflage).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path

#: directive syntax (in a comment): ``nanolint: ignore[pass-a,pass-b]``
#: followed by ``:`` or ``--`` and the justification text
_IGNORE_RE = re.compile(
    r"#\s*nanolint:\s*ignore\[([a-z0-9_,\s-]+)\]\s*(?::|--)?\s*(.*)$"
)


@dataclass
class Finding:
    pass_name: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_name}] {self.message}"

    def as_dict(self) -> dict:
        return {
            "pass": self.pass_name,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


@dataclass
class Ignore:
    path: str
    line: int
    passes: tuple[str, ...]
    justification: str
    #: the code line this directive covers: its own line for a trailing
    #: comment; the next non-comment line for a comment-only directive
    #: (so a directive atop a multi-line comment block still lands)
    target_line: int = 0
    used: bool = False

    def as_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "passes": list(self.passes),
            "justification": self.justification,
            "used": self.used,
        }


class Module:
    """One parsed source file: AST + source lines + ignore directives."""

    def __init__(self, path: Path, name: str, text: str):
        self.path = path
        self.name = name  # dotted, e.g. "nanotpu.dealer.dealer"
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=str(path))
        # directives live in real COMMENT tokens only — a docstring that
        # *describes* the syntax (like this framework's own) is not one
        self.ignores: list[Ignore] = []
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
        except (tokenize.TokenError, IndentationError):
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _IGNORE_RE.search(tok.string)
            if m is None:
                continue
            passes = tuple(
                p.strip() for p in m.group(1).split(",") if p.strip()
            )
            line = tok.start[0]
            target = line
            if self.lines[line - 1].lstrip().startswith("#"):
                # comment-only directive: covers the next code line
                target = line + 1
                while target <= len(self.lines):
                    stripped = self.lines[target - 1].strip()
                    if stripped and not stripped.startswith("#"):
                        break
                    target += 1
            self.ignores.append(
                Ignore(str(path), line, passes, m.group(2).strip(), target)
            )
        self._parents: dict[ast.AST, ast.AST] | None = None

    def parent_of(self, node: ast.AST) -> ast.AST | None:
        if self._parents is None:
            self._parents = {}
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent
        return self._parents.get(node)

    def in_scope(self, prefixes: tuple[str, ...]) -> bool:
        """Fixture modules (anything not under ``nanotpu``) are always in
        scope; real modules must match a pass's prefix list."""
        if not self.name.startswith("nanotpu"):
            return True
        return any(
            self.name == p or self.name.startswith(p + ".")
            for p in prefixes
        )


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain; None for anything else
    (subscripts, calls in the chain, literals)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def module_name_for(path: Path, root: Path) -> str:
    """Dotted name of ``path`` rooted at ``root``'s parent, so analyzing
    ``<repo>/nanotpu`` yields ``nanotpu.dealer.dealer`` names."""
    rel = path.relative_to(root.parent)
    parts = list(rel.with_suffix("").parts)
    if parts[-1] == "__init__":
        parts.pop()
    return ".".join(parts)


def collect_modules(root: Path) -> tuple[list[Module], list[Finding]]:
    """Parse every ``*.py`` under ``root``. Unparsable files become
    findings rather than crashes — a syntax error must fail lint, not
    hide the rest of the tree from it."""
    modules: list[Module] = []
    errors: list[Finding] = []
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        text = path.read_text()
        try:
            modules.append(Module(path, module_name_for(path, root), text))
        except SyntaxError as e:
            errors.append(
                Finding("parse", str(path), e.lineno or 0, f"syntax error: {e.msg}")
            )
    return modules, errors


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    ignores: list[Ignore] = field(default_factory=list)
    suppressed: int = 0
    passes_run: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        return {
            "ok": self.ok,
            "passes": self.passes_run,
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": self.suppressed,
            "ignores": [i.as_dict() for i in self.ignores],
        }


def _apply_ignores(findings: list[Finding], modules: list[Module],
                   report: Report) -> list[Finding]:
    """Suppress findings covered by a justified ignore on the same line
    (or the line below a comment-only directive); convert unjustified or
    stale ignores into findings of their own."""
    by_site: dict[tuple[str, int, str], list[Ignore]] = {}
    for mod in modules:
        for ig in mod.ignores:
            report.ignores.append(ig)
            for p in ig.passes:
                by_site.setdefault((ig.path, ig.line, p), []).append(ig)
                by_site.setdefault(
                    (ig.path, ig.target_line, p), []
                ).append(ig)
    kept: list[Finding] = []
    for f in findings:
        hits = by_site.get((f.path, f.line, f.pass_name))
        if hits:
            for ig in hits:
                ig.used = True
            report.suppressed += 1
        else:
            kept.append(f)
    ran = set(report.passes_run)
    for ig in report.ignores:
        # budget checks only bind when the directive was in play this
        # run: a subset run (--pass X) must not call another pass's
        # justified ignore "stale" (it never had the chance to be used),
        # and staleness is only provable when EVERY named pass ran
        if not ran & set(ig.passes):
            continue
        if not ig.justification:
            kept.append(Finding(
                "ignore-budget", ig.path, ig.line,
                f"ignore[{','.join(ig.passes)}] has no justification — "
                "every suppression must say why it is sound",
            ))
        elif not ig.used and set(ig.passes) <= ran:
            kept.append(Finding(
                "ignore-budget", ig.path, ig.line,
                f"ignore[{','.join(ig.passes)}] suppresses nothing — "
                "stale directive, delete it",
            ))
    return kept


def run_analysis(root: Path, passes) -> Report:
    """Run ``passes`` over every module under ``root``; apply the ignore
    budget; return the full report (the CLI renders it)."""
    modules, parse_errors = collect_modules(Path(root))
    report = Report()
    findings = list(parse_errors)
    for p in passes:
        report.passes_run.append(p.name)
        scoped = [m for m in modules if m.in_scope(p.scope)]
        findings.extend(p.run(scoped))
    findings = _apply_ignores(findings, modules, report)
    findings.sort(key=lambda f: (f.path, f.line, f.pass_name))
    report.findings = findings
    return report
