"""CLI: ``python -m nanotpu.analysis`` — the ``make lint`` gate.

Exit-code contract (CI leans on it):

* ``0`` — every enabled pass is clean AND every ignore directive carries
  a justification (justified ignores are fine; they are listed).
* ``1`` — findings (including unjustified or stale ignores).
* ``2`` — bad usage (unknown pass, unreadable root).

Human-readable report on stderr; ``--json`` writes the machine-readable
report to stdout (findings, ignores, pass list — stable key order).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from nanotpu.analysis.core import run_analysis
from nanotpu.analysis.passes import ALL_PASSES, BY_NAME


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m nanotpu.analysis",
        description="nanolint: scheduler concurrency/determinism "
        "invariant checks (docs/static-analysis.md)",
    )
    parser.add_argument(
        "--root", default=None,
        help="package root to analyze (default: the installed nanotpu/)",
    )
    parser.add_argument(
        "--pass", dest="passes", action="append", default=None,
        metavar="NAME", help="run only this pass (repeatable)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable report on stdout",
    )
    parser.add_argument(
        "--list-passes", action="store_true", help="list passes and exit"
    )
    args = parser.parse_args(argv)

    if args.list_passes:
        for p in ALL_PASSES:
            print(f"{p.name:24s} {p.doc}")
        return 0

    if args.passes:
        unknown = [n for n in args.passes if n not in BY_NAME]
        if unknown:
            print(f"error: unknown pass(es): {', '.join(unknown)}; "
                  f"known: {', '.join(sorted(BY_NAME))}", file=sys.stderr)
            return 2
        passes = [BY_NAME[n] for n in args.passes]
    else:
        passes = list(ALL_PASSES)

    root = Path(args.root) if args.root else Path(__file__).resolve().parents[1]
    if not root.is_dir():
        print(f"error: root {root} is not a directory", file=sys.stderr)
        return 2

    report = run_analysis(root, passes)

    if args.json:
        print(json.dumps(report.as_dict(), indent=2, sort_keys=True))
    for f in report.findings:
        print(f.render(), file=sys.stderr)
    justified = [i for i in report.ignores if i.justification]
    if justified:
        print(f"-- {len(justified)} justified ignore(s):", file=sys.stderr)
        for ig in justified:
            print(
                f"   {ig.path}:{ig.line}: ignore[{','.join(ig.passes)}] "
                f"— {ig.justification}",
                file=sys.stderr,
            )
    print(
        f"nanolint: {len(report.passes_run)} passes, "
        f"{len(report.findings)} finding(s), "
        f"{report.suppressed} suppressed by "
        f"{len(justified)} justified ignore(s)",
        file=sys.stderr,
    )
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
