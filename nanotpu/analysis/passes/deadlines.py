"""deadline-threading: the response-budget token must reach the dealer.

PR 3 threaded a :class:`~nanotpu.utils.deadline.Deadline` from the route
layer's per-verb budget through ``verb.handle`` into the dealer so an
over-budget request aborts at a safe point instead of burning a handler
thread on an answer kube-scheduler already abandoned. The token only
works if EVERY hop forwards it: one call site that drops ``deadline=``
silently reverts that verb path to unbounded work — no test fails,
latency just quietly regresses under overload.

Two checks:

* **roots accept** — every function in :data:`ROOTS` (the verb-path
  entry points) must declare a ``deadline`` parameter;
* **hops forward** — inside any function that declares ``deadline``, a
  call to a known deadline sink (:data:`SINKS`: ``<...>.dealer.assume/
  score/bind`` and ``<verb>.handle``) must pass ``deadline=``. Functions
  WITHOUT the parameter are exempt by design: ``deadline=None`` is the
  documented "no budget" mode the sim and direct tests use.

A declared-but-unused ``deadline`` parameter is also flagged: a hop that
accepts the token and neither forwards nor checks it is a drop with
extra steps.

A third check covers the COMMIT side of a bind (docs/bind-pipeline.md):
once a chip reservation exists, the bind must run to completion —
committing is idempotent-retry-safe, abandoning a half-written
annotation is not. So the budget may never be probed past the point a
reservation is created: inside any function, a probe
(``deadline_check(...)`` / ``deadline.check(...)``) lexically after a
call in :data:`RESERVE_CALLS` is a finding, and the functions in
:data:`COMMIT_SIDE` (the commit half and the pipeline's batched
gang-commit workers, which run entirely reservation-side) may not probe
at all.
"""

from __future__ import annotations

import ast

from nanotpu.analysis.core import Finding, Module, dotted

PASS_NAME = "deadline-threading"

SCOPE = ("nanotpu.dealer", "nanotpu.scheduler", "nanotpu.routes")

#: verb-path entry points that must accept the token (matched by
#: qualified name; fixture modules outside nanotpu match on name alone)
ROOT_QUALS = {
    "Dealer.assume", "Dealer.score", "Dealer.bind",
    "Predicate.handle", "Prioritize.handle", "Bind.handle",
}
ROOT_MODULES = ("nanotpu.dealer.dealer", "nanotpu.scheduler.verbs")

#: (receiver terminal, method) pairs that accept ``deadline=``; the
#: receiver filter keeps `info.score(...)` (NodeInfo, no deadline) from
#: false-positive matching on the method name alone
SINKS = {
    ("dealer", "assume"), ("dealer", "score"), ("dealer", "bind"),
    ("verb", "handle"),
}

#: calls that CREATE a chip reservation: past one of these, the caller
#: holds applied-but-uncommitted chip state and must commit through
RESERVE_CALLS = {"_reserve"}

#: functions that run entirely on the commit side of a reservation —
#: including the commit pipeline's async gang-commit workers
#: (docs/bind-pipeline.md): the deadline token must not reach them
COMMIT_SIDE = {
    "_commit_reserved", "_commit_reserved_inner", "_park_and_commit",
    "_commit_gang_batch", "_commit_gang_member",
}


def _is_probe(node: ast.Call) -> bool:
    """A deadline probe: ``deadline_check(...)`` (the canonical import
    alias), ``deadline.check(...)``, or a bare ``check(deadline, ...)``
    whose first argument is the token."""
    chain = dotted(node.func) or ""
    terminal = chain.rsplit(".", 1)[-1]
    if terminal == "deadline_check":
        return True
    if chain == "deadline.check":
        return True
    return terminal == "check" and any(
        isinstance(a, ast.Name) and a.id == "deadline" for a in node.args
    )


def _functions(mod: Module):
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield node.name, sub


def _has_deadline_param(fn) -> bool:
    names = [a.arg for a in fn.args.args + fn.args.kwonlyargs]
    return "deadline" in names


def _creates_deadline(fn) -> bool:
    """A function that builds its own token (``deadline = Deadline(...)``,
    the route layer) owes downstream sinks the forward just as much as
    one that received it."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            chain = dotted(node.value.func) or ""
            if chain.rsplit(".", 1)[-1] == "Deadline" and any(
                isinstance(t, ast.Name) and t.id == "deadline"
                for t in node.targets
            ):
                return True
    return False


class _DeadlinePass:
    name = PASS_NAME
    doc = "verb-path hops that drop the Deadline response-budget token"
    scope = SCOPE

    def run(self, modules: list[Module]) -> list[Finding]:
        findings: list[Finding] = []
        for mod in modules:
            is_root_module = (
                mod.name in ROOT_MODULES
                or not mod.name.startswith("nanotpu")
            )
            for cls_name, fn in _functions(mod):
                qual = f"{cls_name}.{fn.name}" if cls_name else fn.name
                has_param = _has_deadline_param(fn)
                if is_root_module and qual in ROOT_QUALS and not has_param:
                    findings.append(Finding(
                        self.name, str(mod.path), fn.lineno,
                        f"{qual} is a verb-path entry point but does "
                        "not accept a `deadline` parameter — the "
                        "response budget cannot reach it",
                    ))
                    continue
                findings.extend(self._check_commit_side(mod, qual, fn))
                if not has_param and not _creates_deadline(fn):
                    continue
                findings.extend(
                    self._check_body(mod, qual, fn, has_param)
                )
        return findings

    def _check_commit_side(self, mod: Module, qual: str,
                           fn) -> list[Finding]:
        """No deadline probe may run once a reservation exists: not
        lexically after a ``RESERVE_CALLS`` call, and never inside the
        ``COMMIT_SIDE`` functions (which hold one for their whole body —
        the commit pipeline's workers included)."""
        findings: list[Finding] = []
        commit_side = fn.name in COMMIT_SIDE
        reserve_line: int | None = None
        probes: list[tuple[int, str]] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted(node.func) or ""
            if chain.rsplit(".", 1)[-1] in RESERVE_CALLS:
                if reserve_line is None or node.lineno < reserve_line:
                    reserve_line = node.lineno
            elif _is_probe(node):
                probes.append((node.lineno, chain))
        for line, chain in sorted(probes):
            if commit_side:
                findings.append(Finding(
                    self.name, str(mod.path), line,
                    f"{qual} probes the deadline ({chain}) but runs on "
                    "the commit side of a reservation — an applied "
                    "reservation must commit through, never abort "
                    "(docs/bind-pipeline.md)",
                ))
            elif reserve_line is not None and line > reserve_line:
                findings.append(Finding(
                    self.name, str(mod.path), line,
                    f"{qual} probes the deadline ({chain}) after "
                    f"creating a reservation (line {reserve_line}) — "
                    "once chips are reserved the bind must run to "
                    "completion; probe before reserving instead",
                ))
        return findings

    def _check_body(self, mod: Module, qual: str, fn,
                    has_param: bool) -> list[Finding]:
        findings: list[Finding] = []
        used = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id == "deadline" and \
                    isinstance(node.ctx, ast.Load):
                used = True
            if not isinstance(node, ast.Call):
                continue
            chain = dotted(node.func)
            if chain is None or "." not in chain:
                continue
            receiver, method = chain.rsplit(".", 1)
            rterm = receiver.rsplit(".", 1)[-1]
            if (rterm, method) not in SINKS:
                continue
            forwards = any(
                kw.arg == "deadline" for kw in node.keywords
            ) or any(
                isinstance(a, ast.Name) and a.id == "deadline"
                for a in node.args
            )
            if not forwards:
                findings.append(Finding(
                    self.name, str(mod.path), node.lineno,
                    f"{qual} holds a deadline but calls {chain}() without "
                    "forwarding it — the budget stops here and the "
                    "downstream work becomes unbounded",
                ))
        if has_param and not used:
            findings.append(Finding(
                self.name, str(mod.path), fn.lineno,
                f"{qual} accepts `deadline` but never reads or forwards "
                "it — an accepted-and-dropped token is still a drop",
            ))
        return findings


PASS = _DeadlinePass()
