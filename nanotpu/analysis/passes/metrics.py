"""metrics-completeness: every counter both incremented and exported.

The robustness layer's promise (docs/robustness.md) is that every
deliberate degradation — shed request, coalesced sync, expired
annotation, fast-failed write — is attributable on ``/metrics``. That
promise has three string-ly typed seams this pass stitches shut:

* **ResilienceCounters** fields are declared in the ``_SCALARS`` /
  ``_LABELED`` tables of ``nanotpu/metrics/resilience.py`` (which the
  exporter renders), but bumped via ``counters.inc("<field>")`` string
  calls scattered across server / controller / k8s / events. An inc of
  an undeclared field raises AttributeError at degradation time (the
  worst possible moment); a declared field nobody bumps renders a
  forever-zero metric that reads as "this failure never happens" when it
  actually means "nobody counts it".

* **PerfCounters** slots are auto-exported by the route layer's
  ``perf.__slots__`` loop, so registration is structural — but a slot
  with no ``+=`` site anywhere is again a lying zero on ``/metrics``.

* **Throughput gauges** (``nanotpu_sched_throughput_*``,
  docs/scoring.md): the exporter's ``_THROUGHPUT_GAUGES`` table
  (``nanotpu/metrics/throughput.py``) declares the family; the model's
  ``gauge_values()`` dict literal produces the values. A suffix
  declared but never produced renders a scrape-time KeyError (the
  exporter indexes the values dict); a suffix produced but never
  declared is a computed value no scrape ever sees. Both directions
  are findings.

* **Timeline gauges** (``nanotpu_timeline_*``) and **SLO gauges**
  (``nanotpu_slo_*``, docs/observability.md): the same exporter shape —
  ``_TIMELINE_GAUGES`` (``nanotpu/metrics/timeline.py``) vs
  ``Timeline.tick_gauge_values()`` and ``_SLO_GAUGES``
  (``nanotpu/metrics/slo.py``) vs ``SLOWatchdog.slo_gauge_values()``,
  each cross-checked both directions. The producer function names are
  distinct per family on purpose: one shared name would pool the
  produced sets and flag every gauge as an undeclared member of the
  other families.

* **Serving gauges** (``nanotpu_serving_*``, docs/serving-loop.md):
  the ``_SERVING_GAUGES`` table (``nanotpu/metrics/serving.py``) vs
  ``ServingMetricsSource.serving_gauge_values()``
  (``nanotpu/serving/feedback.py``) — the producer is also the
  timeline source's ``sample()`` body, so this check pins the scrape
  surface, the ``ext.serving.*`` tick series, and the SLO-addressable
  fields to one table, both directions.

* **Recovery counters** (``nanotpu_sched_defrag_*`` /
  ``nanotpu_gang_backfill_*``, docs/defrag.md): the exporter renders the
  ``_RECOVERY_METRICS`` table of ``nanotpu/metrics/recovery.py`` over the
  ``RecoveryCounters`` slots, and the plane bumps them as
  ``self.counters.<slot> += 1``. Three-way check: every slot must appear
  in the table (else the exporter KeyErrors at scrape time), every table
  key must be a slot (else the render indexes a counter that does not
  exist), and every slot must have a ``counters.<slot> += ...`` site
  somewhere (else a forever-zero metric lies about the recovery plane
  never acting) — with unknown ``counters.*`` bump sites flagged the
  same way unknown ``perf.*`` bumps are.

* **Decision-audit reason codes** (``REASON_*`` in
  ``nanotpu/obs/decisions.py``, docs/observability.md): a code recorded
  somewhere but not declared in the enum would ship an uncatalogued
  string nobody can look up; a declared code no call site ever records
  is a catalogue entry that reads as "this can happen" when nothing
  produces it. Both directions are findings, plus every constant must
  appear in the ``REASONS`` description catalogue (and vice versa) so
  the operator-facing table can never drift from the enum. Use sites
  are any load of a ``REASON_*`` name imported from the declaring
  module (or referenced through a ``decisions.`` attribute) — keyword
  ``record(reason=...)`` arguments, mapping-table values, and
  ``BindError(..., reason=...)`` constructors all count.

* **HA / follower gauges** (``nanotpu_ha_*`` and ``nanotpu_follower_*``,
  docs/ha.md + docs/read-plane.md): ``_HA_GAUGES`` vs
  ``HACoordinator.ha_gauge_values()`` and ``_FOLLOWER_GAUGES`` vs
  ``HACoordinator.follower_gauge_values()`` — both directions each, so
  the read plane's staleness contract (lag, synced, draining,
  tail_retries) can never ship a lying zero or a scrape-time KeyError.

* **Shadow gauges** (``nanotpu_shadow_*``, docs/policy-programs.md):
  ``_SHADOW_GAUGES`` (``nanotpu/metrics/shadow.py``) vs
  ``ShadowScorer.shadow_gauge_values()`` — both directions, so the
  shadow-mode A/B evidence (cycles, rows, divergences, max_abs_delta)
  can never ship a lying zero or a scrape-time KeyError.

* **Fleet gauges** (``nanotpu_fleet_*``, docs/observability.md "Fleet
  observability"): ``_FLEET_GAUGES`` (``nanotpu/metrics/fleet.py``) vs
  ``FleetView.fleet_gauge_values()`` — both directions, so the fleet
  aggregation plane's headline numbers (peers, synced count, worst
  lag, story joins, export bytes/rotations/drops) can never ship a
  lying zero or a scrape-time KeyError.

Registry-built metrics (``registry.counter(...)`` etc.) register at
construction by design and need no check here.
"""

from __future__ import annotations

import ast
from pathlib import Path

from nanotpu.analysis.core import Finding, Module, dotted

PASS_NAME = "metrics-completeness"

#: inc-site receivers that denote the resilience ledger
_LEDGER_RECEIVERS = ("resilience", "counters", "_counters")

SCOPE = ("nanotpu",)  # inc sites can live anywhere in the package


def _declared_resilience(mod: Module) -> dict[str, int] | None:
    """field -> declaration line from the _SCALARS/_LABELED literals."""
    out: dict[str, int] = {}
    found = False
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not any(t in ("_SCALARS", "_LABELED") for t in targets):
            continue
        found = True
        if isinstance(node.value, ast.Dict):
            for key in node.value.keys:
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, str
                ):
                    out[key.value] = key.lineno
    return out if found else None


def _declared_reasons(mod: Module) -> tuple[dict[str, int], set[str]] | None:
    """(REASON_* constant -> declaration line, REASONS catalogue keys)
    for the module declaring the decision-audit enum; None when this
    module declares no ``REASONS`` catalogue."""
    constants: dict[str, int] = {}
    catalogue: set[str] = set()
    found = False
    for node in mod.tree.body:
        # the real catalogue is an ANNOTATED assignment
        # (``REASONS: dict[str, str] = {...}``) — ast.AnnAssign, not
        # ast.Assign; matching only the latter silently no-ops the
        # whole check on the production enum
        if isinstance(node, ast.AnnAssign):
            if node.value is None or not isinstance(node.target, ast.Name):
                continue
            targets = [node.target.id]
            value = node.value
        elif isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        else:
            continue
        if any(t == "REASONS" for t in targets):
            found = True
            if isinstance(value, ast.Dict):
                for key in value.keys:
                    if isinstance(key, ast.Name):
                        catalogue.add(key.id)
        for t in targets:
            if t.startswith("REASON_") and isinstance(
                value, ast.Constant
            ) and isinstance(value.value, str):
                constants[t] = node.lineno
    return (constants, catalogue) if found else None


def _reason_uses(mod: Module) -> dict[str, tuple[str, int]]:
    """REASON_* name -> first use site in ``mod``: loads of names
    imported from the decisions module, and ``decisions.REASON_*``
    attribute references."""
    imported: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom):
            # only imports FROM the decisions module count: other modules
            # legitimately export REASON_* strings of their own (e.g.
            # k8s/events' kubectl event reasons) and must not be held to
            # the decision-audit enum
            module = node.module or ""
            if module.rsplit(".", 1)[-1] != "decisions":
                continue
            for alias in node.names:
                if alias.name.startswith("REASON_"):
                    imported.add(alias.asname or alias.name)
    uses: dict[str, tuple[str, int]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in imported:
            uses.setdefault(node.id, (str(mod.path), node.lineno))
        elif isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ) and node.attr.startswith("REASON_"):
            base = dotted(node.value)
            if base is not None and base.split(".")[-1] == "decisions":
                uses.setdefault(node.attr, (str(mod.path), node.lineno))
    return uses


def _declared_gauge_table(mod: Module, table: str) -> dict[str, int] | None:
    """gauge suffix -> declaration line from a ``<table>`` dict literal
    (``_THROUGHPUT_GAUGES`` / ``_TIMELINE_GAUGES`` / ``_SLO_GAUGES``);
    None when this module declares no such table."""
    for node in mod.tree.body:
        if isinstance(node, ast.AnnAssign):
            if node.value is None or not isinstance(node.target, ast.Name):
                continue
            targets, value = [node.target.id], node.value
        elif isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        else:
            continue
        if table not in targets:
            continue
        out: dict[str, int] = {}
        if isinstance(value, ast.Dict):
            for key in value.keys:
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, str
                ):
                    out[key.value] = key.lineno
        return out
    return None


def _gauge_value_keys(mod: Module,
                      fn_name: str = "gauge_values") -> dict[str, tuple[str, int]]:
    """gauge suffix -> first production site: string keys of dict
    literals inside any function named ``fn_name``. The producer names
    are DISTINCT per table on purpose (``gauge_values`` /
    ``tick_gauge_values`` / ``slo_gauge_values``): a shared name would
    cross-pollinate the tables' produced sets and flag every gauge as
    an undeclared member of the other families."""
    out: dict[str, tuple[str, int]] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or node.name != fn_name:
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Dict):
                continue
            for key in sub.keys:
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, str
                ):
                    out.setdefault(
                        key.value, (str(mod.path), key.lineno)
                    )
    return out


def _declared_recovery_table(mod: Module) -> dict[str, int] | None:
    """slot -> declaration line from the ``_RECOVERY_METRICS`` dict
    literal; None when this module declares no such table."""
    return _declared_gauge_table(mod, "_RECOVERY_METRICS")


def _declared_slots(mod: Module, cls_name: str) -> dict[str, int] | None:
    for node in mod.tree.body:
        if not isinstance(node, ast.ClassDef) or node.name != cls_name:
            continue
        for sub in node.body:
            if isinstance(sub, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "__slots__"
                for t in sub.targets
            ) and isinstance(sub.value, (ast.Tuple, ast.List)):
                return {
                    e.value: e.lineno for e in sub.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                }
    return None


class _MetricsPass:
    name = PASS_NAME
    doc = "counters incremented but unregistered, or registered but never bumped"
    scope = SCOPE

    def run(self, modules: list[Module]) -> list[Finding]:
        findings: list[Finding] = []
        declared: dict[str, int] | None = None
        decl_mod: Module | None = None
        slots: dict[str, int] | None = None
        slots_mod: Module | None = None
        reasons: dict[str, int] | None = None
        catalogue: set[str] = set()
        reasons_mod: Module | None = None
        tgauges: dict[str, int] | None = None
        tgauges_mod: Module | None = None
        rslots: dict[str, int] | None = None
        rslots_mod: Module | None = None
        rtable: dict[str, int] | None = None
        rtable_mod: Module | None = None
        tlgauges: dict[str, int] | None = None
        tlgauges_mod: Module | None = None
        slogauges: dict[str, int] | None = None
        slogauges_mod: Module | None = None
        srvgauges: dict[str, int] | None = None
        srvgauges_mod: Module | None = None
        hagauges: dict[str, int] | None = None
        hagauges_mod: Module | None = None
        flgauges: dict[str, int] | None = None
        flgauges_mod: Module | None = None
        dggauges: dict[str, int] | None = None
        dggauges_mod: Module | None = None
        shgauges: dict[str, int] | None = None
        shgauges_mod: Module | None = None
        ftgauges: dict[str, int] | None = None
        ftgauges_mod: Module | None = None
        for mod in modules:
            d = _declared_resilience(mod)
            if d is not None:
                declared, decl_mod = d, mod
            s = _declared_slots(mod, "PerfCounters")
            if s is not None:
                slots, slots_mod = s, mod
            rs = _declared_slots(mod, "RecoveryCounters")
            if rs is not None:
                rslots, rslots_mod = rs, mod
            rt = _declared_recovery_table(mod)
            if rt is not None:
                rtable, rtable_mod = rt, mod
            r = _declared_reasons(mod)
            if r is not None:
                (reasons, catalogue), reasons_mod = r, mod
            t = _declared_gauge_table(mod, "_THROUGHPUT_GAUGES")
            if t is not None:
                tgauges, tgauges_mod = t, mod
            tl = _declared_gauge_table(mod, "_TIMELINE_GAUGES")
            if tl is not None:
                tlgauges, tlgauges_mod = tl, mod
            sg = _declared_gauge_table(mod, "_SLO_GAUGES")
            if sg is not None:
                slogauges, slogauges_mod = sg, mod
            sv = _declared_gauge_table(mod, "_SERVING_GAUGES")
            if sv is not None:
                srvgauges, srvgauges_mod = sv, mod
            hg = _declared_gauge_table(mod, "_HA_GAUGES")
            if hg is not None:
                hagauges, hagauges_mod = hg, mod
            fl = _declared_gauge_table(mod, "_FOLLOWER_GAUGES")
            if fl is not None:
                flgauges, flgauges_mod = fl, mod
            dg = _declared_gauge_table(mod, "_DEGRADED_GAUGES")
            if dg is not None:
                dggauges, dggauges_mod = dg, mod
            sh = _declared_gauge_table(mod, "_SHADOW_GAUGES")
            if sh is not None:
                shgauges, shgauges_mod = sh, mod
            ft = _declared_gauge_table(mod, "_FLEET_GAUGES")
            if ft is not None:
                ftgauges, ftgauges_mod = ft, mod

        inc_sites: dict[str, tuple[str, int]] = {}
        perf_incs: dict[str, tuple[str, int]] = {}
        recovery_incs: dict[str, tuple[str, int]] = {}
        for mod in modules:
            if decl_mod is not None and mod is decl_mod:
                continue  # the ledger's own inc() plumbing is not a site
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    chain = dotted(node.func)
                    if chain is None or not chain.endswith(".inc"):
                        continue
                    receiver = chain.rsplit(".", 2)[-2]
                    if receiver not in _LEDGER_RECEIVERS:
                        continue
                    if node.args and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        field = node.args[0].value
                        inc_sites.setdefault(
                            field, (str(mod.path), node.lineno)
                        )
                        if declared is not None and field not in declared:
                            findings.append(Finding(
                                self.name, str(mod.path), node.lineno,
                                f"resilience counter {field!r} is "
                                "incremented here but not declared in "
                                "_SCALARS/_LABELED — it will raise at "
                                "degradation time and never export",
                            ))
                elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Attribute
                ):
                    base = dotted(node.target.value)
                    if base is None:
                        continue
                    leaf = base.split(".")[-1]
                    if leaf in ("perf", "_perf"):
                        perf_incs.setdefault(
                            node.target.attr, (str(mod.path), node.lineno)
                        )
                    elif leaf in ("counters", "_counters") and (
                        mod is not rslots_mod
                    ):
                        # RecoveryCounters bump sites (the resilience
                        # ledger's receivers use .inc() calls, matched
                        # above, so an AugAssign through `counters` can
                        # only mean the recovery plane's slots)
                        recovery_incs.setdefault(
                            node.target.attr, (str(mod.path), node.lineno)
                        )

        if declared is not None and decl_mod is not None:
            for field, line in sorted(declared.items()):
                if field not in inc_sites:
                    findings.append(Finding(
                        self.name, str(decl_mod.path), line,
                        f"resilience counter {field!r} is exported but "
                        "never incremented anywhere — a forever-zero "
                        "metric reads as 'cannot happen'",
                    ))
        if slots is not None and slots_mod is not None:
            for slot, line in sorted(slots.items()):
                if slot not in perf_incs:
                    findings.append(Finding(
                        self.name, str(slots_mod.path), line,
                        f"PerfCounters slot {slot!r} is exported on "
                        "/metrics but never incremented anywhere",
                    ))
            for slot, (path, line) in sorted(perf_incs.items()):
                if slot not in slots:
                    findings.append(Finding(
                        self.name, path, line,
                        f"perf counter {slot!r} is incremented here but "
                        "is not a PerfCounters slot — it is never "
                        "exported (and will AttributeError at runtime)",
                    ))
        if rslots is not None and rslots_mod is not None:
            for slot, line in sorted(rslots.items()):
                if slot not in recovery_incs:
                    findings.append(Finding(
                        self.name, str(rslots_mod.path), line,
                        f"RecoveryCounters slot {slot!r} is exported on "
                        "/metrics but never incremented anywhere — a "
                        "forever-zero metric reads as 'the recovery "
                        "plane never does this'",
                    ))
                if rtable is not None and slot not in rtable:
                    findings.append(Finding(
                        self.name, str(rslots_mod.path), line,
                        f"RecoveryCounters slot {slot!r} is missing from "
                        "the _RECOVERY_METRICS table — the exporter "
                        "renders the table, so this counter never "
                        "reaches /metrics",
                    ))
            for slot, (path, line) in sorted(recovery_incs.items()):
                if slot not in rslots:
                    findings.append(Finding(
                        self.name, path, line,
                        f"recovery counter {slot!r} is incremented here "
                        "but is not a RecoveryCounters slot — it is "
                        "never exported (and will AttributeError at "
                        "runtime)",
                    ))
        if rtable is not None and rtable_mod is not None and \
                rslots is not None:
            for slot, line in sorted(rtable.items()):
                if slot not in rslots:
                    findings.append(Finding(
                        self.name, str(rtable_mod.path), line,
                        f"_RECOVERY_METRICS references {slot!r} which is "
                        "not a RecoveryCounters slot — the exporter will "
                        "KeyError at scrape time",
                    ))
        if reasons is not None and reasons_mod is not None:
            findings.extend(self._check_reasons(
                modules, reasons, catalogue, reasons_mod
            ))
        for family, table, table_mod, fn_name in (
            ("throughput", tgauges, tgauges_mod, "gauge_values"),
            ("timeline", tlgauges, tlgauges_mod, "tick_gauge_values"),
            ("slo", slogauges, slogauges_mod, "slo_gauge_values"),
            ("serving", srvgauges, srvgauges_mod, "serving_gauge_values"),
            ("ha", hagauges, hagauges_mod, "ha_gauge_values"),
            ("follower", flgauges, flgauges_mod, "follower_gauge_values"),
            ("degraded", dggauges, dggauges_mod, "degraded_gauge_values"),
            ("shadow", shgauges, shgauges_mod, "shadow_gauge_values"),
            ("fleet", ftgauges, ftgauges_mod, "fleet_gauge_values"),
        ):
            if table is not None and table_mod is not None:
                findings.extend(self._check_gauge_table(
                    modules, family, table, table_mod, fn_name
                ))
        return findings

    def _check_gauge_table(self, modules: list[Module], family: str,
                           table: dict[str, int], table_mod: Module,
                           fn_name: str) -> list[Finding]:
        """One exported-gauge table vs its producer function, both
        directions (throughput / timeline / SLO families all share the
        same exporter shape: the exporter renders the table's keys by
        indexing the producer's dict)."""
        table_name = f"_{family.upper()}_GAUGES"
        findings: list[Finding] = []
        produced: dict[str, tuple[str, int]] = {}
        for mod in modules:
            for suffix, site in _gauge_value_keys(mod, fn_name).items():
                produced.setdefault(suffix, site)
                if suffix not in table:
                    findings.append(Finding(
                        self.name, site[0], site[1],
                        f"{family} gauge {suffix!r} is produced by "
                        f"{fn_name}() here but not declared in "
                        f"{table_name} — it is computed on every scrape "
                        "and never exported",
                    ))
        for suffix, line in sorted(table.items()):
            if suffix not in produced:
                findings.append(Finding(
                    self.name, str(table_mod.path), line,
                    f"{family} gauge {suffix!r} is declared in "
                    f"{table_name} but no {fn_name}() produces it — "
                    "the exporter will KeyError at scrape time",
                ))
        return findings

    def _check_reasons(self, modules: list[Module],
                       reasons: dict[str, int], catalogue: set[str],
                       reasons_mod: Module) -> list[Finding]:
        """Decision-audit reason-code enum vs use sites, both directions,
        plus enum <-> REASONS catalogue equivalence."""
        findings: list[Finding] = []
        uses: dict[str, tuple[str, int]] = {}
        for mod in modules:
            if mod is reasons_mod:
                continue
            for name, site in _reason_uses(mod).items():
                uses.setdefault(name, site)
                if name not in reasons:
                    findings.append(Finding(
                        self.name, site[0], site[1],
                        f"reason code {name!r} is recorded here but not "
                        "declared in the decision-audit enum — the audit "
                        "would ship an uncatalogued code no operator can "
                        "look up",
                    ))
        for name, line in sorted(reasons.items()):
            if name not in uses:
                findings.append(Finding(
                    self.name, str(reasons_mod.path), line,
                    f"reason code {name!r} is declared but no call site "
                    "ever records it — a catalogue entry nothing "
                    "produces reads as 'this can happen'",
                ))
            if name not in catalogue:
                findings.append(Finding(
                    self.name, str(reasons_mod.path), line,
                    f"reason code {name!r} is missing from the REASONS "
                    "description catalogue — operators cannot look up "
                    "what it means",
                ))
        for name in sorted(catalogue - set(reasons)):
            findings.append(Finding(
                self.name, str(reasons_mod.path), 0,
                f"REASONS catalogue references {name!r} which is not a "
                "declared reason constant",
            ))
        return findings


PASS = _MetricsPass()
