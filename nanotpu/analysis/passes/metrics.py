"""metrics-completeness: every counter both incremented and exported.

The robustness layer's promise (docs/robustness.md) is that every
deliberate degradation — shed request, coalesced sync, expired
annotation, fast-failed write — is attributable on ``/metrics``. That
promise has two string-ly typed seams this pass stitches shut:

* **ResilienceCounters** fields are declared in the ``_SCALARS`` /
  ``_LABELED`` tables of ``nanotpu/metrics/resilience.py`` (which the
  exporter renders), but bumped via ``counters.inc("<field>")`` string
  calls scattered across server / controller / k8s / events. An inc of
  an undeclared field raises AttributeError at degradation time (the
  worst possible moment); a declared field nobody bumps renders a
  forever-zero metric that reads as "this failure never happens" when it
  actually means "nobody counts it".

* **PerfCounters** slots are auto-exported by the route layer's
  ``perf.__slots__`` loop, so registration is structural — but a slot
  with no ``+=`` site anywhere is again a lying zero on ``/metrics``.

Registry-built metrics (``registry.counter(...)`` etc.) register at
construction by design and need no check here.
"""

from __future__ import annotations

import ast
from pathlib import Path

from nanotpu.analysis.core import Finding, Module, dotted

PASS_NAME = "metrics-completeness"

#: inc-site receivers that denote the resilience ledger
_LEDGER_RECEIVERS = ("resilience", "counters", "_counters")

SCOPE = ("nanotpu",)  # inc sites can live anywhere in the package


def _declared_resilience(mod: Module) -> dict[str, int] | None:
    """field -> declaration line from the _SCALARS/_LABELED literals."""
    out: dict[str, int] = {}
    found = False
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not any(t in ("_SCALARS", "_LABELED") for t in targets):
            continue
        found = True
        if isinstance(node.value, ast.Dict):
            for key in node.value.keys:
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, str
                ):
                    out[key.value] = key.lineno
    return out if found else None


def _declared_slots(mod: Module, cls_name: str) -> dict[str, int] | None:
    for node in mod.tree.body:
        if not isinstance(node, ast.ClassDef) or node.name != cls_name:
            continue
        for sub in node.body:
            if isinstance(sub, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "__slots__"
                for t in sub.targets
            ) and isinstance(sub.value, (ast.Tuple, ast.List)):
                return {
                    e.value: e.lineno for e in sub.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                }
    return None


class _MetricsPass:
    name = PASS_NAME
    doc = "counters incremented but unregistered, or registered but never bumped"
    scope = SCOPE

    def run(self, modules: list[Module]) -> list[Finding]:
        findings: list[Finding] = []
        declared: dict[str, int] | None = None
        decl_mod: Module | None = None
        slots: dict[str, int] | None = None
        slots_mod: Module | None = None
        for mod in modules:
            d = _declared_resilience(mod)
            if d is not None:
                declared, decl_mod = d, mod
            s = _declared_slots(mod, "PerfCounters")
            if s is not None:
                slots, slots_mod = s, mod

        inc_sites: dict[str, tuple[str, int]] = {}
        perf_incs: dict[str, tuple[str, int]] = {}
        for mod in modules:
            if decl_mod is not None and mod is decl_mod:
                continue  # the ledger's own inc() plumbing is not a site
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    chain = dotted(node.func)
                    if chain is None or not chain.endswith(".inc"):
                        continue
                    receiver = chain.rsplit(".", 2)[-2]
                    if receiver not in _LEDGER_RECEIVERS:
                        continue
                    if node.args and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        field = node.args[0].value
                        inc_sites.setdefault(
                            field, (str(mod.path), node.lineno)
                        )
                        if declared is not None and field not in declared:
                            findings.append(Finding(
                                self.name, str(mod.path), node.lineno,
                                f"resilience counter {field!r} is "
                                "incremented here but not declared in "
                                "_SCALARS/_LABELED — it will raise at "
                                "degradation time and never export",
                            ))
                elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Attribute
                ):
                    base = dotted(node.target.value)
                    if base is not None and base.split(".")[-1] in (
                        "perf", "_perf"
                    ):
                        perf_incs.setdefault(
                            node.target.attr, (str(mod.path), node.lineno)
                        )

        if declared is not None and decl_mod is not None:
            for field, line in sorted(declared.items()):
                if field not in inc_sites:
                    findings.append(Finding(
                        self.name, str(decl_mod.path), line,
                        f"resilience counter {field!r} is exported but "
                        "never incremented anywhere — a forever-zero "
                        "metric reads as 'cannot happen'",
                    ))
        if slots is not None and slots_mod is not None:
            for slot, line in sorted(slots.items()):
                if slot not in perf_incs:
                    findings.append(Finding(
                        self.name, str(slots_mod.path), line,
                        f"PerfCounters slot {slot!r} is exported on "
                        "/metrics but never incremented anywhere",
                    ))
            for slot, (path, line) in sorted(perf_incs.items()):
                if slot not in slots:
                    findings.append(Finding(
                        self.name, path, line,
                        f"perf counter {slot!r} is incremented here but "
                        "is not a PerfCounters slot — it is never "
                        "exported (and will AttributeError at runtime)",
                    ))
        return findings


PASS = _MetricsPass()
