"""lock-discipline: acquisition-order cycles + blocking work under hot locks.

The dealer's concurrency design (dealer.py module docstring) rests on two
conventions nothing else enforces:

1. **One global lock order.** ``_republish`` takes ``_publish_lock`` then
   briefly ``_lock``; ``_bind_strict`` takes ``_lock`` then a barrier's
   ``cv``. Any code path establishing the reverse order of ANY two locks
   is a deadlock waiting for contention. This pass builds the
   acquisition graph — lexical ``with`` nesting plus a fixpoint over the
   intra-/cross-class call graph (``self.method()`` calls and calls
   through ``self.attr = ClassName(...)``-typed attributes) — and rejects
   cycles.

2. **Nothing blocking under the hot locks.** ``Dealer._lock`` serializes
   every verb commit and ``Dealer._publish_lock`` every snapshot swap; an
   apiserver round-trip, a socket write, a ``time.sleep``, or a native
   ctypes call made while holding one turns a microsecond critical
   section into a convoy (dealer.go's single-mutex p50 collapse, SURVEY
   §6 — the bug this codebase exists to not have). ``time.sleep`` is
   rejected under ANY lock.

Lock identity is by *name* — ``Class.attr`` — resolved in this order:
the literal handed to the witness factories (``make_lock("Dealer._lock")``),
``self.attr`` inside its class, annotated/constructed local types, then a
unique global owner of a lock-ish attribute. The same names the runtime
witness (nanotpu/analysis/witness.py) uses, so a static edge and a
witnessed edge land in one namespace.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from nanotpu.analysis.core import Finding, Module, dotted

PASS_NAME = "lock-discipline"

SCOPE = (
    "nanotpu.dealer", "nanotpu.controller", "nanotpu.routes",
    "nanotpu.scheduler", "nanotpu.k8s", "nanotpu.metrics", "nanotpu.sim",
    "nanotpu.native", "nanotpu.policy", "nanotpu.utils",
    "nanotpu.analysis", "nanotpu.allocator",
    # the replica autoscaler + serving feedback tap (docs/serving-loop.md):
    # ReplicaAutoscaler._lock nests with nothing by contract — every
    # client write and plane call runs outside it. The serving ENGINE
    # stays out of scope: its _cv legitimately wraps device-blocking
    # decode work, a different discipline than the scheduler's locks.
    "nanotpu.serving.feedback", "nanotpu.serving.autoscale",
    # the HA plane (docs/ha.md): the delta log is appended on the bind
    # hot path, and the coordinator's role lock nests with nothing by
    # contract — promotion's reconcile (apiserver syncs) runs outside it.
    # The follower read plane (docs/read-plane.md) lives in the same
    # modules and adds NO lock: the drain/rejoin flags flip under the
    # existing HACoordinator._lock, and the HttpDeltaSource backoff is
    # single-threaded (one tail loop per process), so HOT_LOCKS is
    # unchanged.
    "nanotpu.ha",
)

#: locks whose critical sections are the scheduling hot path: blocking
#: calls under these are findings (elsewhere only cycles + sleep are).
#: ``_Shard._publish_lock`` is the per-shard successor of the old
#: ``Dealer._publish_lock`` (kept for fixtures/back-compat): every
#: snapshot swap serializes on exactly one of them.
#: ``_Shard._pending_lock`` guards the commit pipeline's coalescing
#: queue (docs/bind-pipeline.md): every pipelined commit enqueues under
#: it, so its critical sections must stay set-ops-only.
#: ``ThroughputModel._lock`` is the mirror-sync lock (docs/scoring.md,
#: ABI 7): the metric-sync writer holds it per observe and every scoring
#: view's mirror resync snapshots under it while HOLDING the arena lock
#: — a blocking call inside it would stall both calibration and the
#: Filter/Prioritize read path at once.
#: ``BatchAdmitter._lock`` guards the batch admitter's cycle counter +
#: last-cycle summary (docs/batch-admission.md): the admitter's solve
#: (a GIL-releasing native crossing) and its commit fan-out (apiserver
#: writes) both run OUTSIDE it by contract — a blocking call inside it
#: would serialize /debug scrapes behind a batch cycle.
#: ``DeltaLog._lock`` guards the HA delta ring (docs/ha.md): every
#: commit point on the write path appends under it, so its critical
#: sections must stay append-only — checkpoint file I/O batches OUTSIDE
#: it by contract.
HOT_LOCKS = (
    "Dealer._lock", "Dealer._publish_lock", "_Shard._publish_lock",
    "_Shard._pending_lock", "ThroughputModel._lock",
    "BatchAdmitter._lock", "DeltaLog._lock",
)

#: per-node reservation locks (docs/bind-pipeline.md): the commit
#: pipeline's workers apply and roll back chip reservations under these,
#: so a blocking call while holding one would convoy every verb touching
#: that node — same rule as the hot locks, named separately because the
#: lock is per-NODE (fine-grained), not global.
RESERVATION_LOCKS = ("NodeInfo.lock",)

#: terminal attribute names treated as lock objects
_LOCKISH = ("cv", "_cv", "cond", "_cond", "_mu")
_FACTORIES = ("make_lock", "make_rlock", "make_condition")


def _is_lockish(attr: str) -> bool:
    return "lock" in attr.lower() or attr in _LOCKISH


def _blocking_reason(chain: str) -> str | None:
    """Why a dotted call chain counts as blocking, or None."""
    parts = chain.split(".")
    if chain == "time.sleep":
        return "time.sleep"
    terminal = parts[-1]
    if terminal == "urlopen":
        return "HTTP round-trip (urlopen)"
    if terminal in ("sendall", "recv", "connect"):
        return f"socket {terminal}"
    if any(p in ("wfile", "rfile") for p in parts[:-1]):
        return "handler socket I/O"
    if any(p in ("client", "clientset") for p in parts[:-1]):
        return f"apiserver round-trip ({chain})"
    if parts[0] == "native" and len(parts) > 1:
        return f"ctypes native call ({chain})"
    if terminal == "wait":
        return f"blocking wait ({chain})"
    return None


@dataclass
class _FnSummary:
    qual: str                       # "Class.method" or "function"
    cls: str | None
    path: str = ""
    acquires: set = field(default_factory=set)
    #: (reason, line) of directly blocking calls anywhere in the body
    blocking: set = field(default_factory=set)
    #: (callee class or None-for-same-module-function, callee name, line)
    calls: set = field(default_factory=set)
    #: under-lock observations: (held names tuple, node, chain)
    under: list = field(default_factory=list)
    #: (held names tuple, callee cls, callee name, line)
    under_calls: list = field(default_factory=list)
    edges: list = field(default_factory=list)  # (a, b, line)
    bare: list = field(default_factory=list)   # (chain, line) acquire()/release()
    #: (lock name, chain, line) of `.acquire(blocking=False)` attempts —
    #: the commit pipeline's publish-leader election idiom. Legal ONLY
    #: when the same function also releases the same lock (checked in
    #: run()); the span between acquire and release is tracked as held.
    tryacquired: list = field(default_factory=list)
    #: lock names `.release()`d while statically held by a try-acquire
    released: set = field(default_factory=set)


class _ModuleIndex:
    """Per-module name resolution state shared by the function walks."""

    def __init__(self, mod: Module):
        self.mod = mod
        short = mod.name.rsplit(".", 1)[-1]
        self.short = short
        self.classes: dict[str, ast.ClassDef] = {}
        #: (cls, attr) -> canonical lock name from a witness factory call
        self.factory_names: dict[tuple[str, str], str] = {}
        #: (cls, attr) -> class name, from ``self.attr = ClassName(...)``
        self.attr_types: dict[tuple[str, str], str] = {}
        #: lock-ish attr -> owner class, when globally unique in-module
        self.attr_owner: dict[str, str | None] = {}
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
        for cls in self.classes.values():
            for sub in ast.walk(cls):
                if not isinstance(sub, ast.Assign):
                    continue
                self._index_assign(cls.name, sub)

    def _index_assign(self, cls: str, assign: ast.Assign) -> None:
        for target in assign.targets:
            chain = dotted(target)
            if chain is None or not chain.startswith("self."):
                continue
            attr = chain[len("self."):]
            if "." in attr:
                continue
            value = assign.value
            # unwrap ``x or Fallback()`` injection defaults
            if isinstance(value, ast.BoolOp) and value.values:
                value = value.values[-1]
            if isinstance(value, ast.Call):
                fchain = dotted(value.func) or ""
                fname = fchain.rsplit(".", 1)[-1]
                if fname in _FACTORIES and value.args and isinstance(
                    value.args[0], ast.Constant
                ) and isinstance(value.args[0].value, str):
                    self.factory_names[(cls, attr)] = value.args[0].value
                elif fname in self.classes or (
                    fname and fname[0].isupper() and "." not in fchain
                ):
                    self.attr_types[(cls, attr)] = fname
                if _is_lockish(attr) and (
                    fname in _FACTORIES
                    or fchain in ("threading.Lock", "threading.RLock",
                                  "threading.Condition")
                ):
                    if attr in self.attr_owner and self.attr_owner[attr] != cls:
                        self.attr_owner[attr] = None  # ambiguous
                    else:
                        self.attr_owner.setdefault(attr, cls)


class _FnWalker(ast.NodeVisitor):
    def __init__(self, index: _ModuleIndex, cls: str | None, fn):
        self.index = index
        self.cls = cls
        self.fn = fn
        self.summary = _FnSummary(
            qual=f"{cls}.{fn.name}" if cls else fn.name, cls=cls
        )
        #: local/param name -> class name
        self.local_types: dict[str, str] = {}
        self.held: list[str] = []
        #: the subset of `held` opened by a try-acquire (not a `with`):
        #: only THESE may be closed by a bare release() — a release of a
        #: with-held lock stays an unbalanced-release finding
        self._try_held: list[str] = []
        for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
            ann = arg.annotation
            if isinstance(ann, ast.Name):
                self.local_types[arg.arg] = ann.id
            elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                self.local_types[arg.arg] = ann.value

    # -- name resolution ---------------------------------------------------
    def lock_name(self, expr: ast.AST) -> str | None:
        chain = dotted(expr)
        if chain is None:
            return None
        parts = chain.split(".")
        if not _is_lockish(parts[-1]):
            return None
        if parts[0] == "self" and self.cls:
            if len(parts) == 2:
                key = (self.cls, parts[1])
                if key in self.index.factory_names:
                    return self.index.factory_names[key]
                return f"{self.cls}.{parts[1]}"
            owner = self.index.attr_types.get((self.cls, parts[1]))
            if owner:
                return f"{owner}." + ".".join(parts[2:])
            return f"{self.cls}." + ".".join(parts[1:])
        if parts[0] in self.local_types and len(parts) >= 2:
            return f"{self.local_types[parts[0]]}." + ".".join(parts[1:])
        if len(parts) >= 2:
            owner = self.index.attr_owner.get(parts[-1])
            if owner:
                return f"{owner}.{parts[-1]}"
            return chain
        return f"{self.index.short}.{parts[0]}"

    def _callee(self, call: ast.Call):
        """(cls|None, name) for calls the fixpoint can chase."""
        chain = dotted(call.func)
        if chain is None:
            return None
        parts = chain.split(".")
        if parts[0] == "self" and self.cls:
            if len(parts) == 2:
                return (self.cls, parts[1])
            if len(parts) == 3:
                owner = self.index.attr_types.get((self.cls, parts[1]))
                if owner:
                    return (owner, parts[2])
            return None
        if len(parts) == 1:
            return (None, parts[0])  # same-module function
        if parts[0] in self.local_types and len(parts) == 2:
            return (self.local_types[parts[0]], parts[1])
        return None

    # -- traversal -----------------------------------------------------------
    def visit_FunctionDef(self, node):  # nested defs: don't descend
        if node is not self.fn:
            return
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign):
        value = node.value
        if isinstance(value, ast.BoolOp) and value.values:
            value = value.values[-1]
        if isinstance(value, ast.Call):
            fchain = dotted(value.func) or ""
            fname = fchain.rsplit(".", 1)[-1]
            if fname and fname[0].isupper() and (
                fname in self.index.classes or "." not in fchain
            ):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        self.local_types[target.id] = fname
        self.generic_visit(node)

    def visit_With(self, node: ast.With):
        acquired: list[str] = []
        for item in node.items:
            name = self.lock_name(item.context_expr)
            if name is None:
                self.visit(item.context_expr)
                continue
            for h in self.held:
                if h != name:
                    self.summary.edges.append((h, name, node.lineno))
            self.held.append(name)
            acquired.append(name)
            self.summary.acquires.add(name)
        for stmt in node.body:
            self.visit(stmt)
        for name in reversed(acquired):
            self.held.pop()

    @staticmethod
    def _is_nonblocking(node: ast.Call) -> bool:
        """``.acquire(blocking=False)`` / ``.acquire(False)`` — the
        commit pipeline's publish-leader try-acquire."""
        for kw in node.keywords:
            if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return True
        return bool(
            node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value is False
        )

    def visit_Call(self, node: ast.Call):
        chain = dotted(node.func)
        if chain is not None:
            terminal = chain.rsplit(".", 1)[-1]
            receiver = chain.rsplit(".", 1)[0] if "." in chain else ""
            if terminal in ("acquire", "release") and receiver and \
                    _is_lockish(receiver.rsplit(".", 1)[-1]):
                name = self.lock_name(node.func.value)
                if (
                    terminal == "acquire"
                    and name is not None
                    and self._is_nonblocking(node)
                ):
                    # try-acquire (leader election): an acquisition
                    # attempt, not an opaque bare acquire — record its
                    # ordering edges and hold the span until the matching
                    # release() in this function (required; checked in
                    # run()). A FAILED try-acquire returns without the
                    # lock, so treating the failure branch as held only
                    # ever over-approximates, never misses an edge.
                    for h in self.held:
                        if h != name:
                            self.summary.edges.append(
                                (h, name, node.lineno)
                            )
                    self.held.append(name)
                    self._try_held.append(name)
                    self.summary.acquires.add(name)
                    self.summary.tryacquired.append(
                        (name, chain, node.lineno)
                    )
                elif (
                    terminal == "release"
                    and name is not None
                    and name in self._try_held
                ):
                    # the matching release of a try-acquire span; a
                    # release of a `with`-held lock is NOT matched — it
                    # stays a bare-release finding like before
                    self.summary.released.add(name)
                    self._try_held.remove(name)
                    for i in range(len(self.held) - 1, -1, -1):
                        if self.held[i] == name:
                            del self.held[i]
                            break
                else:
                    self.summary.bare.append((chain, node.lineno))
            reason = _blocking_reason(chain)
            if reason is not None:
                self.summary.blocking.add((reason, node.lineno))
                if self.held:
                    self.summary.under.append(
                        (tuple(self.held), node.lineno, reason)
                    )
            callee = self._callee(node)
            if callee is not None:
                self.summary.calls.add((callee[0], callee[1], node.lineno))
                if self.held:
                    self.summary.under_calls.append(
                        (tuple(self.held), callee[0], callee[1], node.lineno)
                    )
        self.generic_visit(node)


def _summarize(modules: list[Module]):
    summaries: dict[tuple[str | None, str], _FnSummary] = {}
    per_module: dict[str, list[_FnSummary]] = {}
    for mod in modules:
        index = _ModuleIndex(mod)
        fns: list[tuple[str | None, ast.AST]] = [
            (None, n) for n in mod.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for cls in index.classes.values():
            fns += [
                (cls.name, n) for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
        out = []
        for cls_name, fn in fns:
            walker = _FnWalker(index, cls_name, fn)
            walker.visit_FunctionDef(fn)
            s = walker.summary
            s.path = str(mod.path)
            summaries[(cls_name, fn.name)] = s
            out.append(s)
        per_module[mod.name] = out
    return summaries, per_module


def _fixpoint(summaries) -> tuple[dict, dict]:
    """Transitive may_acquire / may_block over the resolvable call graph.
    Same-module plain-function callees resolve with cls=None; bounded by
    the lattice height (sets only grow)."""
    may_acquire = {k: set(s.acquires) for k, s in summaries.items()}
    may_block = {k: set(s.blocking) for k, s in summaries.items()}
    changed = True
    guard = 0
    while changed and guard < 50:
        changed = False
        guard += 1
        for key, s in summaries.items():
            for ccls, cname, _line in s.calls:
                ckey = (ccls, cname)
                if ckey not in summaries:
                    continue
                if not may_acquire[key] >= may_acquire[ckey]:
                    may_acquire[key] |= may_acquire[ckey]
                    changed = True
                if not may_block[key] >= may_block[ckey]:
                    may_block[key] |= may_block[ckey]
                    changed = True
    return may_acquire, may_block


def _find_cycles(edges: dict) -> list[list[str]]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles: list[list[str]] = []
    seen_cycles: set[tuple] = set()
    state: dict[str, int] = {}

    def visit(node: str, trail: list[str]):
        state[node] = 1
        trail.append(node)
        for nxt in sorted(graph.get(node, ())):
            if state.get(nxt, 0) == 1:
                cycle = trail[trail.index(nxt):] + [nxt]
                key = tuple(sorted(cycle))
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(cycle)
            elif state.get(nxt, 0) == 0:
                visit(nxt, trail)
        trail.pop()
        state[node] = 2

    for node in sorted(graph):
        if state.get(node, 0) == 0:
            visit(node, [])
    return cycles


class _LockPass:
    name = PASS_NAME
    doc = "lock-order cycles and blocking calls under the dealer's hot locks"
    scope = SCOPE
    hot_locks = HOT_LOCKS
    reservation_locks = RESERVATION_LOCKS

    def run(self, modules: list[Module]) -> list[Finding]:
        summaries, _per_module = _summarize(modules)
        may_acquire, may_block = _fixpoint(summaries)
        findings: list[Finding] = []
        #: (a, b) -> (path, line) of one witness site
        edges: dict[tuple[str, str], tuple[str, int]] = {}

        for key, s in summaries.items():
            path = s.path
            for a, b, line in s.edges:
                edges.setdefault((a, b), (path, line))
            # propagated edges: calling m while holding L orders L before
            # everything m may acquire
            for held, ccls, cname, line in s.under_calls:
                ckey = (ccls, cname)
                for lock in may_acquire.get(ckey, ()):
                    for h in held:
                        if h != lock:
                            edges.setdefault((h, lock), (path, line))
            # blocking directly under a lock
            for held, line, reason in s.under:
                if reason == "time.sleep":
                    findings.append(Finding(
                        self.name, path, line,
                        f"time.sleep while holding {held[-1]} — sleeping "
                        "under any lock convoys every waiter",
                    ))
                elif any(h in self.hot_locks for h in held):
                    hot = next(h for h in held if h in self.hot_locks)
                    findings.append(Finding(
                        self.name, path, line,
                        f"blocking call ({reason}) while holding hot lock "
                        f"{hot} — hot-path critical sections must stay "
                        "compute-only",
                    ))
                elif any(h in self.reservation_locks for h in held):
                    res = next(
                        h for h in held if h in self.reservation_locks
                    )
                    findings.append(Finding(
                        self.name, path, line,
                        f"blocking call ({reason}) while holding per-node "
                        f"reservation lock {res} — a parked apiserver "
                        "round-trip here convoys every verb touching "
                        "that node (docs/bind-pipeline.md)",
                    ))
            # blocking reached through a call chain under a hot or
            # per-node reservation lock
            for held, ccls, cname, line in s.under_calls:
                hot = next((h for h in held if h in self.hot_locks), None)
                res = None if hot is not None else next(
                    (h for h in held if h in self.reservation_locks), None
                )
                if hot is None and res is None:
                    continue
                blocked = sorted(may_block.get((ccls, cname), set()))
                if blocked:  # one finding per call site, first cause
                    reason = blocked[0][0]
                    callee = f"{ccls}.{cname}" if ccls else cname
                    findings.append(Finding(
                        self.name, path, line,
                        f"call to {callee} while holding {hot or res} may "
                        f"block ({reason}) — move it outside the "
                        "critical section or prove it cannot block here",
                    ))
            for name, chain, line in s.tryacquired:
                if name not in s.released:
                    findings.append(Finding(
                        self.name, path, line,
                        f"try-acquire {chain}(blocking=False) without a "
                        f"matching {name}.release() in the same function "
                        "— a leader that cannot be seen to release reads "
                        "as a leaked lock",
                    ))
            for chain, line in s.bare:
                findings.append(Finding(
                    self.name, path, line,
                    f"bare {chain}() — use `with` so nanolint (and "
                    "reviewers) can see the critical-section extent",
                ))

        for cycle in _find_cycles(edges):
            sites = "; ".join(
                f"{a}->{b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
                for a, b in zip(cycle, cycle[1:])
            )
            path, line = edges[(cycle[0], cycle[1])]
            findings.append(Finding(
                self.name, path, line,
                f"lock-order cycle {' -> '.join(cycle)} ({sites}) — two "
                "code paths disagree about acquisition order; under "
                "contention this deadlocks",
            ))
        return findings


PASS = _LockPass()
