"""The nanolint pass registry. Each submodule exports one ``PASS``."""

from __future__ import annotations

from nanotpu.analysis.passes.deadlines import PASS as DEADLINES
from nanotpu.analysis.passes.determinism import PASS as DETERMINISM
from nanotpu.analysis.passes.locks import PASS as LOCKS
from nanotpu.analysis.passes.metrics import PASS as METRICS
from nanotpu.analysis.passes.replication import PASS as REPLICATION
from nanotpu.analysis.passes.snapshots import PASS as SNAPSHOTS
from nanotpu.analysis.policyver import PASS as POLICYVER

#: registry order == report order (lock discipline first: its findings
#: are the ones that turn into 3am pages)
ALL_PASSES = (
    LOCKS, SNAPSHOTS, DEADLINES, DETERMINISM, METRICS, REPLICATION,
    POLICYVER,
)

BY_NAME = {p.name: p for p in ALL_PASSES}
