"""sim-determinism: sim-driven code must not read ambient entropy.

The simulator's whole value is its contract: two runs of (scenario,
seed) produce byte-identical reports, so a digest diff IS a behavior
diff (docs/simulation.md). The sim drives the REAL dealer / controller /
verbs / resilient client, which means those modules must draw time and
randomness only from what the sim injects — one ``time.time()`` or
ambient ``random.random()`` on a sim-reachable path and the digest
becomes a coin flip that `--check-determinism` may or may not catch.

Banned in scope:

* ``time.time()`` — wall clock. (``time.monotonic`` is tolerated: it
  never enters reports, only local timeout arithmetic, and the sim
  passes explicit ``now=`` on every determinism-relevant path.)
* ambient ``random.*`` module calls — ``random.random()``,
  ``random.choice``, … and UNSEEDED ``random.Random()``. Seeded
  ``random.Random(seed)`` streams are the required idiom.
* ``uuid.uuid4`` / ``os.urandom`` / ``secrets.*`` — entropy by any
  other name.
* iteration over locally-built ``set``/``frozenset`` values (for loops,
  comprehensions, ``list()``/``tuple()``/``enumerate()``/``iter()``/
  ``min()``/``max()`` wrapping) — string-set order depends on
  ``PYTHONHASHSEED``, so it reproduces within a process and diverges
  across processes, the worst kind of flake. ``sorted(...)`` over a set
  is the sanctioned spelling. Order-INSENSITIVE consumption is allowed:
  a generator feeding ``sum``/``len``/``any``/``all``, and a set
  comprehension over a set (set in, set out — no order escapes).
  ``min``/``max`` stay flagged because a ``key=`` with ties resolves by
  iteration order; a fully-discriminating key earns a justified ignore.

The **injection idiom is allowed**: a banned call as the fallback arm of
``x if <param> is None else <param>`` or ``<param> or <call>`` is how
production code declares an injectable clock/rng with a wall-clock
default — the sim always supplies the parameter.
"""

from __future__ import annotations

import ast

from nanotpu.analysis.core import Finding, Module, dotted

PASS_NAME = "sim-determinism"

#: the sim itself plus every module it drives (sim/core.py imports)
SCOPE = (
    "nanotpu.sim", "nanotpu.dealer", "nanotpu.controller",
    "nanotpu.scheduler", "nanotpu.allocator", "nanotpu.recovery",
    "nanotpu.metrics.recovery",
    # the scheduler<->serving loop (docs/serving-loop.md): the sim
    # drives the REAL tap/source and autoscaler, so both must draw
    # time/randomness only from what the sim injects. The engine
    # itself stays out of scope — the sim never imports it (the
    # virtual replica fleet stands in for it)
    "nanotpu.serving.feedback", "nanotpu.serving.autoscale",
    "nanotpu.metrics.serving",
    # the HA plane (docs/ha.md): the sim drives the REAL delta log,
    # lease, and coordinator on virtual time, so all three must draw
    # time only from their injectable clocks. The follower read plane
    # (docs/read-plane.md) rides in the same modules: the sim pumps
    # follower coordinators per event, and the HttpDeltaSource backoff
    # jitter draws only from its injectable clock/rng defaults
    "nanotpu.ha", "nanotpu.metrics.ha", "nanotpu.metrics.degraded",
    # verified policy programs (docs/policy-programs.md): the verifier
    # bans nondeterminism INSIDE programs; this pins the loader /
    # compiler / shadow plumbing around them to the same bar
    "nanotpu.policy_ir", "nanotpu.metrics.shadow",
    "nanotpu.k8s.objects", "nanotpu.k8s.client", "nanotpu.k8s.resilience",
    "nanotpu.k8s.events",
    "nanotpu.metrics.resilience", "nanotpu.metrics.stats",
    "nanotpu.obs",
    "nanotpu.utils", "nanotpu.topology", "nanotpu.types",
    "nanotpu.native",
)

_BANNED_CALLS = {
    "time.time": "wall clock",
    "uuid.uuid4": "random UUID",
    "os.urandom": "OS entropy",
    "datetime.now": "wall clock",
    "datetime.datetime.now": "wall clock",
}

_SET_WRAPPERS = ("list", "tuple", "enumerate", "iter", "max", "min")

#: calls whose result cannot depend on argument order: a generator over
#: a set feeding one of these is deterministic
_ORDER_FREE_SINKS = ("sum", "len", "any", "all", "set", "frozenset",
                     "sorted")


def _is_injection_fallback(mod: Module, node: ast.Call) -> bool:
    """True when ``node`` is the fallback arm of the injectable-default
    idiom: ``X() if param is None else param`` or ``param or X()``."""
    parent = mod.parent_of(node)
    if isinstance(parent, ast.IfExp) and parent.body is node:
        test = parent.test
        if (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.ops[0], ast.Is)
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
        ):
            return True
    if isinstance(parent, ast.BoolOp) and isinstance(parent.op, ast.Or) \
            and parent.values and parent.values[-1] is node:
        return True
    return False


def _set_producing(node: ast.AST, set_vars: set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        chain = dotted(node.func)
        if chain in ("set", "frozenset"):
            return True
    if isinstance(node, ast.Name) and node.id in set_vars:
        return True
    return False


class _FnWalk(ast.NodeVisitor):
    def __init__(self, mod: Module, findings: list[Finding], fn):
        self.mod = mod
        self.findings = findings
        self.fn = fn
        self.set_vars: set[str] = set()

    def visit_FunctionDef(self, node):
        if node is not self.fn:
            return
        # pre-scan: locals bound ONLY from set-producing expressions.
        # Every other binding form — for-loop targets, tuple unpacks,
        # `with ... as`, walrus — demotes the name, so a set var rebound
        # by a later loop is never falsely flagged at its new type
        assigned_set: set[str] = set()
        assigned_other: set[str] = set()

        def demote(target: ast.AST) -> None:
            for n in ast.walk(target):
                if isinstance(n, ast.Name):
                    assigned_other.add(n.id)

        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                if len(sub.targets) == 1 and isinstance(
                    sub.targets[0], ast.Name
                ) and _set_producing(sub.value, set()):
                    assigned_set.add(sub.targets[0].id)
                else:
                    for t in sub.targets:
                        demote(t)
            elif isinstance(sub, (ast.For, ast.AsyncFor)):
                demote(sub.target)
            elif isinstance(sub, ast.withitem) and \
                    sub.optional_vars is not None:
                demote(sub.optional_vars)
            elif isinstance(sub, ast.NamedExpr):
                if _set_producing(sub.value, set()):
                    assigned_set.add(sub.target.id)
                else:
                    demote(sub.target)
        self.set_vars = assigned_set - assigned_other
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _flag(self, line: int, msg: str) -> None:
        self.findings.append(
            Finding(PASS_NAME, str(self.mod.path), line, msg)
        )

    def _check_iter(self, iter_node: ast.AST, line: int) -> None:
        if _set_producing(iter_node, self.set_vars):
            self._flag(
                line,
                "iteration over an unordered set — order depends on "
                "PYTHONHASHSEED and diverges across processes; iterate "
                "sorted(...) instead",
            )

    def visit_For(self, node: ast.For):
        self._check_iter(node.iter, node.lineno)
        self.generic_visit(node)

    def _visit_comp(self, node):
        if isinstance(node, ast.SetComp):
            self.generic_visit(node)  # set in, set out: order never escapes
            return
        if isinstance(node, ast.GeneratorExp):
            parent = self.mod.parent_of(node)
            if isinstance(parent, ast.Call):
                chain = dotted(parent.func)
                if chain in _ORDER_FREE_SINKS:
                    self.generic_visit(node)
                    return
        for gen in node.generators:
            self._check_iter(gen.iter, node.lineno)
        self.generic_visit(node)

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_DictComp = _visit_comp
    visit_GeneratorExp = _visit_comp

    def visit_Call(self, node: ast.Call):
        chain = dotted(node.func)
        if chain is not None:
            reason = _BANNED_CALLS.get(chain)
            if reason is not None and not _is_injection_fallback(
                self.mod, node
            ):
                self._flag(
                    node.lineno,
                    f"{chain}() ({reason}) in sim-driven code — use the "
                    "injected clock/now parameter (the `X if now is None "
                    "else now` idiom declares the injectable default)",
                )
            elif chain.startswith("random.") and chain != "random.Random":
                self._flag(
                    node.lineno,
                    f"ambient {chain}() in sim-driven code — draw from an "
                    "injected, seeded random.Random stream",
                )
            elif chain == "random.Random" and not node.args and \
                    not node.keywords and \
                    not _is_injection_fallback(self.mod, node):
                self._flag(
                    node.lineno,
                    "unseeded random.Random() in sim-driven code — seed "
                    "it, or make it an injectable default "
                    "(`rng or random.Random()`)",
                )
            elif chain.startswith("secrets."):
                self._flag(node.lineno, f"{chain}() entropy in sim-driven code")
            if chain in _SET_WRAPPERS and node.args:
                self._check_iter(node.args[0], node.lineno)
        self.generic_visit(node)


class _DeterminismPass:
    name = PASS_NAME
    doc = "wall clock / ambient randomness / set iteration in sim-driven code"
    scope = SCOPE

    def run(self, modules: list[Module]) -> list[Finding]:
        findings: list[Finding] = []
        for mod in modules:
            fns = [
                n for n in ast.walk(mod.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            for fn in fns:
                walker = _FnWalk(mod, findings, fn)
                walker.visit_FunctionDef(fn)
        return findings


PASS = _DeterminismPass()
