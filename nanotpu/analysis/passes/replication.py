"""replication-completeness: the delta stream must carry every mutation.

The HA replication contract (docs/ha.md, docs/read-plane.md): every
Dealer/recovery commit point that publishes a mutation also appends ONE
typed DeltaLog record, and the standby/follower ``apply`` consumes
every kind the leader can emit. A kind emitted but missing from the
``STATE_KINDS``/``NOTE_KINDS`` catalogue is dropped on the follower's
forward-compat skip — silent replica/checkpoint drift, the exact bug
class this pass exists to make un-shippable. A kind declared but never
emitted is dead schema (or a silently MISSED emit at a ``_republish``
commit point); declared but never applied is follower drift from the
other side.

Like metrics-completeness, the check is a catalogue cross-check, BOTH
directions, over three record sets collected per module group:

* **declared** — the ``STATE_KINDS = (...)`` / ``NOTE_KINDS = (...)``
  tuple-of-string assignments (nanotpu.ha.delta on the real tree);
* **emitted** — the literal first argument of every ``*._ha_emit(...)``
  / ``*._ha_note(...)`` call (the one-liner wrappers every commit point
  routes through; a NON-literal kind is its own finding — a dynamic
  kind cannot be cross-checked, so it cannot be reviewed either);
* **applied** — kinds consumed inside ``apply``/``apply_delta``:
  string literals compared with ``==``/``in (tuple)``, plus a
  ``kind in STATE_KINDS`` membership test, which marks the whole state
  catalogue applied (the dealer dispatches those internally).

All checks gate on a catalogue being present in the analyzed module
set, so unrelated fixture trees are no-ops.
"""

from __future__ import annotations

import ast

from nanotpu.analysis.core import Finding, Module, dotted

PASS_NAME = "replication-completeness"

SCOPE = ("nanotpu.ha", "nanotpu.dealer", "nanotpu.recovery")

_CATALOGUES = ("STATE_KINDS", "NOTE_KINDS")
_EMIT_SUFFIXES = ("_ha_emit", "_ha_note")
_APPLY_FNS = ("apply", "apply_delta")


def _declared_kinds(mod: Module) -> dict[str, tuple[str, int]]:
    """kind -> (catalogue name, line) for every string in a top-level
    STATE_KINDS/NOTE_KINDS tuple assignment."""
    out: dict[str, tuple[str, int]] = {}
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name) or (
            target.id not in _CATALOGUES
        ):
            continue
        if not isinstance(node.value, (ast.Tuple, ast.List)):
            continue
        for elt in node.value.elts:
            if isinstance(elt, ast.Constant) and isinstance(
                elt.value, str
            ):
                out[elt.value] = (target.id, node.lineno)
    return out


def _emit_sites(mod: Module):
    """Yield ``(kind | None, line)`` per ``*._ha_emit``/``*._ha_note``
    call; None == non-literal kind argument."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name is None or not name.endswith(_EMIT_SUFFIXES):
            continue
        if not node.args:
            yield None, node.lineno
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(
            first.value, str
        ):
            yield first.value, node.lineno
        else:
            yield None, node.lineno


def _applied_kinds(mod: Module):
    """``(kinds, state_membership, sites)`` consumed by apply-side
    dispatch: literal kinds (with the line of each compare) and whether
    a ``... in STATE_KINDS`` membership test covers the state
    catalogue wholesale."""
    kinds: dict[str, int] = {}
    state_membership = False
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.FunctionDef) or (
            node.name not in _APPLY_FNS
        ):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Compare):
                continue
            # only compares whose subject is a plain local (the `kind`
            # variable) count as kind dispatch — payload compares like
            # `data.get("action") == "open"` are not dispatch
            if not isinstance(sub.left, ast.Name):
                continue
            for op, comp in zip(sub.ops, sub.comparators):
                if isinstance(op, (ast.In, ast.NotIn)):
                    if isinstance(comp, ast.Name) and (
                        comp.id == "STATE_KINDS"
                    ) and isinstance(op, ast.In):
                        state_membership = True
                    elif isinstance(comp, (ast.Tuple, ast.List)) and (
                        isinstance(op, ast.In)
                    ):
                        for elt in comp.elts:
                            if isinstance(elt, ast.Constant) and (
                                isinstance(elt.value, str)
                            ):
                                kinds.setdefault(elt.value, sub.lineno)
                elif isinstance(op, ast.Eq):
                    if isinstance(comp, ast.Constant) and (
                        isinstance(comp.value, str)
                    ):
                        kinds.setdefault(comp.value, sub.lineno)
    return kinds, state_membership


class _ReplicationPass:
    name = PASS_NAME
    doc = (
        "every delta kind a commit point emits is declared in the "
        "STATE_KINDS/NOTE_KINDS catalogue, every declared kind is "
        "emitted somewhere, and the standby apply path consumes all of "
        "them — a miss in any direction is silent follower drift"
    )
    scope = SCOPE

    def run(self, modules: list[Module]) -> list[Finding]:
        declared: dict[str, tuple[str, int, Module]] = {}
        emitted: dict[str, int] = {}
        emit_findings: list[tuple[Module, int]] = []
        applied: dict[str, int] = {}
        applied_sites: dict[str, Module] = {}
        state_membership = False
        emit_mods: dict[str, Module] = {}
        for mod in modules:
            for kind, (cat, line) in _declared_kinds(mod).items():
                declared.setdefault(kind, (cat, line, mod))
            for kind, line in _emit_sites(mod):
                if kind is None:
                    emit_findings.append((mod, line))
                else:
                    emitted.setdefault(kind, line)
                    emit_mods.setdefault(kind, mod)
            mod_applied, mod_membership = _applied_kinds(mod)
            state_membership = state_membership or mod_membership
            for kind, line in mod_applied.items():
                applied.setdefault(kind, line)
                applied_sites.setdefault(kind, mod)
        if not declared:
            return []  # no catalogue in this module set: nothing to pin
        findings: list[Finding] = []
        for mod, line in emit_findings:
            findings.append(Finding(
                PASS_NAME, str(mod.path), line,
                "delta emit with a non-literal kind — the catalogue "
                "cross-check (and review) cannot see it; emit a literal "
                "STATE_KINDS/NOTE_KINDS member",
            ))
        for kind, line in sorted(emitted.items()):
            if kind not in declared:
                findings.append(Finding(
                    PASS_NAME, str(emit_mods[kind].path), line,
                    f"delta kind {kind!r} is emitted but not declared "
                    "in STATE_KINDS/NOTE_KINDS — the follower's "
                    "forward-compat skip drops it on the floor "
                    "(silent replica drift)",
                ))
        for kind, (cat, line, mod) in sorted(declared.items()):
            if kind not in emitted:
                findings.append(Finding(
                    PASS_NAME, str(mod.path), line,
                    f"delta kind {kind!r} is declared in {cat} but no "
                    "commit point emits it — dead schema, or a "
                    "_republish commit point silently missing its "
                    "emit",
                ))
            if kind not in applied and not (
                cat == "STATE_KINDS" and state_membership
            ):
                findings.append(Finding(
                    PASS_NAME, str(mod.path), line,
                    f"delta kind {kind!r} is declared in {cat} but the "
                    "apply path never consumes it — followers drop the "
                    "record (replica drift from the read side)",
                ))
        for kind, line in sorted(applied.items()):
            if kind not in declared:
                findings.append(Finding(
                    PASS_NAME, str(applied_sites[kind].path), line,
                    f"apply dispatches on kind {kind!r} which is not "
                    "declared in STATE_KINDS/NOTE_KINDS — unreachable "
                    "dispatch (the emitter can never send it)",
                ))
        return findings


PASS = _ReplicationPass()
