"""snapshot-immutability: published RCU state is write-once.

The whole point of the dealer's RCU read path (PR 2) is that read verbs
consume ``Dealer._published`` WITHOUT the dealer lock — which is only
sound because a published ``_Snapshot`` and the frozen ``BatchScorer``
views hanging off it are never mutated after the swap. There is no
runtime enforcement (CPython has no frozen objects without cost on the
hot path), so the convention is exactly one unreviewed edit away from a
torn read. This pass is the enforcement:

* any attribute store (``x.attr = ...``, ``x.attr += ...``) on a value
  known to be a snapshot — a local assigned from ``_Snapshot(...)`` or
  from ``<anything>._published``, or a direct ``...._published.attr``
  chain — is a finding unless it happens inside the publisher path
  (``_Snapshot.__init__`` and the functions in :data:`PUBLISHER_FUNCS`,
  which build the NEXT snapshot before the swap);
* any attribute store on a value known to be a frozen view — a local
  assigned from ``<scorer>.advanced(...)`` — is a finding anywhere
  outside :data:`VIEW_MODULE` (``advanced()`` itself builds the clone's
  fresh arrays before freezing it; that module owns the freeze protocol).

Subscript mutation of ``snap.views`` by readers is legal by design (the
lazy view cache — dict ops are GIL-atomic and documented in _Snapshot's
docstring), so only attribute stores are policed.
"""

from __future__ import annotations

import ast

from nanotpu.analysis.core import Finding, Module, dotted

PASS_NAME = "snapshot-immutability"

SCOPE = (
    "nanotpu.dealer", "nanotpu.controller", "nanotpu.routes",
    "nanotpu.scheduler", "nanotpu.sim",
)

#: functions allowed to store attributes on a _Snapshot: the publisher
#: (per-shard since the sharded-dealer refactor — Dealer._republish only
#: routes commits to the owning shard's _republish_shard)
PUBLISHER_FUNCS = {
    "Dealer._republish", "Dealer._republish_shard",
    "Dealer._publish_shard_locked", "_Snapshot.__init__",
}

#: the module that owns BatchScorer's freeze/clone protocol
VIEW_MODULE = "nanotpu.dealer.batch"

#: constructors whose results are snapshots
_SNAPSHOT_CTORS = {"_Snapshot"}


class _Walker(ast.NodeVisitor):
    def __init__(self, qual: str, fn, findings: list[Finding], path: str,
                 in_publisher: bool, in_view_module: bool):
        self.qual = qual
        self.fn = fn
        self.findings = findings
        self.path = path
        self.in_publisher = in_publisher
        self.in_view_module = in_view_module
        self.snapshot_vars: set[str] = set()
        self.frozen_vars: set[str] = set()

    def visit_FunctionDef(self, node):
        if node is not self.fn:
            return  # nested defs keep their own tracking scope
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _classify_value(self, value: ast.AST) -> str | None:
        """'snapshot' / 'frozen' when the expression produces one."""
        if isinstance(value, ast.Call):
            chain = dotted(value.func) or ""
            name = chain.rsplit(".", 1)[-1]
            if name in _SNAPSHOT_CTORS:
                return "snapshot"
            if name == "advanced":
                return "frozen"
        chain = dotted(value)
        if chain is not None and chain.split(".")[-1] == "_published":
            return "snapshot"
        if isinstance(value, ast.Name):
            if value.id in self.snapshot_vars:
                return "snapshot"
            if value.id in self.frozen_vars:
                return "frozen"
        return None

    def _check_store(self, target: ast.AST, line: int) -> None:
        if not isinstance(target, ast.Attribute):
            return
        base = target.value
        kind = None
        chain = dotted(base)
        if isinstance(base, ast.Name):
            if base.id in self.snapshot_vars:
                kind = "snapshot"
            elif base.id in self.frozen_vars:
                kind = "frozen"
        if kind is None and chain is not None and \
                chain.split(".")[-1] == "_published":
            kind = "snapshot"
        if kind == "snapshot" and not self.in_publisher:
            self.findings.append(Finding(
                PASS_NAME, self.path, line,
                f"store to published snapshot attribute "
                f"`.{target.attr}` in {self.qual} — snapshots are "
                "immutable after the RCU swap; build a successor and "
                "republish instead",
            ))
        elif kind == "frozen" and not self.in_view_module:
            self.findings.append(Finding(
                PASS_NAME, self.path, line,
                f"store to frozen BatchScorer attribute `.{target.attr}` "
                f"in {self.qual} — frozen views are write-once; state "
                "drift must go through advanced()",
            ))

    def visit_Assign(self, node: ast.Assign):
        kind = self._classify_value(node.value)
        for target in node.targets:
            self._check_store(target, node.lineno)
            if kind is not None and isinstance(target, ast.Name):
                (self.snapshot_vars if kind == "snapshot"
                 else self.frozen_vars).add(target.id)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._check_store(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            kind = self._classify_value(node.value)
            self._check_store(node.target, node.lineno)
            if kind is not None and isinstance(node.target, ast.Name):
                (self.snapshot_vars if kind == "snapshot"
                 else self.frozen_vars).add(node.target.id)
        self.generic_visit(node)


class _SnapshotPass:
    name = PASS_NAME
    doc = "attribute stores on published/frozen RCU state outside the publisher"
    scope = SCOPE

    def run(self, modules: list[Module]) -> list[Finding]:
        findings: list[Finding] = []
        for mod in modules:
            in_view_module = mod.name == VIEW_MODULE or (
                not mod.name.startswith("nanotpu")
                and mod.name.endswith("batch")
            )
            fns: list[tuple[str | None, ast.AST]] = [
                (None, n) for n in mod.tree.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            for cls in mod.tree.body:
                if isinstance(cls, ast.ClassDef):
                    fns += [
                        (cls.name, n) for n in cls.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                    ]
            for cls_name, fn in fns:
                qual = f"{cls_name}.{fn.name}" if cls_name else fn.name
                walker = _Walker(
                    qual, fn, findings, str(mod.path),
                    in_publisher=qual in PUBLISHER_FUNCS,
                    in_view_module=in_view_module,
                )
                walker.visit_FunctionDef(fn)
        return findings


PASS = _SnapshotPass()
