"""Runtime lock-order witness: turn latent lock inversions into failures.

The static ``lock-discipline`` pass sees lexical nesting and an
intra-class call graph; it cannot see orders established across objects
at runtime (thread A takes ``GangBarrier.cv`` then ``Dealer._lock`` while
thread B does the reverse through three call layers). This module closes
that gap dynamically: when active, every lock built through the
:func:`make_lock` / :func:`make_rlock` / :func:`make_condition` factories
is wrapped so each acquisition records, for every lock the acquiring
thread already holds, a directed edge ``held -> acquiring`` in one
process-global graph, together with the first stack that witnessed it.
:func:`assert_acyclic` (called at sim teardown and by the test session's
finish hook) then fails loudly — with both witness stacks — if any two
code paths ever disagreed about the order.

Locks are identified by the NAME given at the factory (``"Dealer._lock"``,
``"GangBarrier.cv"``): the witness checks the ordering discipline between
lock *classes*, which is how such disciplines are stated ("dealer lock
before barrier cv"), not between individual instances. Re-entrant
re-acquisition of the same class is therefore never an edge.

Cost model: when inactive (the default — no ``NANOTPU_LOCK_WITNESS=1`` in
the environment and no :func:`enable`), the factories return plain
``threading`` primitives; production pays nothing. When active, an
acquisition does a per-thread list walk plus GIL-atomic dict membership
probes, and takes the witness's own mutex only to record a NEVER-seen
edge — steady state adds no shared-lock traffic, so enabling it under the
race tests does not serialize the very contention they exercise.
"""

from __future__ import annotations

import os
import threading
import traceback

_ENV_FLAG = "NANOTPU_LOCK_WITNESS"


class LockOrderError(AssertionError):
    """The witnessed acquisition-order graph contains a cycle."""


class LockWitness:
    """One acquisition-order graph. A process-global instance backs the
    factories; tests that *construct* deliberate inversions use private
    instances so they cannot poison the global graph."""

    def __init__(self):
        self._mu = threading.Lock()  # guards _edges inserts only
        #: (held, acquired) -> "thread-name\nstack" of the first witness
        self._edges: dict[tuple[str, str], str] = {}
        self._held = threading.local()

    # -- bookkeeping (called by _WitnessLock) ------------------------------
    def _stack(self) -> list[str]:
        s = getattr(self._held, "stack", None)
        if s is None:
            s = self._held.stack = []
        return s

    def on_acquire(self, name: str) -> None:
        """Record edges held->name, then push. Called BEFORE the real
        acquire: the ordering intent exists at the attempt, and a thread
        that deadlocks inside the acquire still leaves its edge behind."""
        held = self._stack()
        for h in held:
            if h == name:
                continue  # re-entrant same-class hold, not an ordering
            key = (h, name)
            if key in self._edges:  # GIL-atomic probe; hot path stays
                continue            # off the witness mutex entirely
            with self._mu:
                if key not in self._edges:
                    self._edges[key] = (
                        f"thread {threading.current_thread().name}:\n"
                        + "".join(traceback.format_stack(limit=8)[:-2])
                    )
        held.append(name)

    def on_acquire_failed(self, name: str) -> None:
        """A non-blocking/timed acquire that did not get the lock: undo
        the push (the edges stay — the *attempt* ordered the locks)."""
        self._pop(name)

    def on_release(self, name: str) -> None:
        self._pop(name)

    def _pop(self, name: str) -> None:
        held = self._stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def on_release_all(self, name: str) -> int:
        """Drop every hold of ``name`` (Condition.wait's _release_save on
        a re-entrant lock); returns the count for _acquire_restore."""
        held = self._stack()
        n = held.count(name)
        if n:
            self._held.stack = [h for h in held if h != name]
        return n

    def on_acquire_n(self, name: str, n: int) -> None:
        self.on_acquire(name)
        self._stack().extend([name] * (n - 1))

    # -- inspection --------------------------------------------------------
    def edges(self) -> list[tuple[str, str]]:
        # snapshot under the mutex: teardown asserts can run while daemon
        # threads (event recorder, assume pool) still insert first-seen
        # edges, and iterating a mutating dict raises
        with self._mu:
            return sorted(self._edges)

    def edge_count(self) -> int:
        return len(self._edges)

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()

    def find_cycle(self) -> list[str] | None:
        """Some cycle in the order graph as [a, b, ..., a], or None."""
        graph: dict[str, list[str]] = {}
        for a, b in self.edges():
            graph.setdefault(a, []).append(b)
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}
        parent: dict[str, str] = {}

        def visit(node: str) -> list[str] | None:
            color[node] = GRAY
            for nxt in graph.get(node, ()):
                c = color.get(nxt, WHITE)
                if c == GRAY:  # back edge: walk parents to print the loop
                    cycle = [nxt, node]
                    cur = node
                    while cur != nxt:
                        cur = parent[cur]
                        cycle.append(cur)
                    cycle.reverse()
                    return cycle
                if c == WHITE:
                    parent[nxt] = node
                    found = visit(nxt)
                    if found:
                        return found
            color[node] = BLACK
            return None

        for node in sorted(graph):
            if color.get(node, WHITE) == WHITE:
                found = visit(node)
                if found:
                    return found
        return None

    def assert_acyclic(self) -> None:
        cycle = self.find_cycle()
        if cycle is None:
            return
        lines = [
            "lock-order cycle witnessed at runtime: "
            + " -> ".join(cycle),
            "each edge below was first acquired in this order by:",
        ]
        for a, b in zip(cycle, cycle[1:]):
            lines.append(f"--- {a} -> {b} ---")
            lines.append(self._edges.get((a, b), "(edge lost)").rstrip())
        raise LockOrderError("\n".join(lines))


#: the process-global witness behind the factories
_GLOBAL = LockWitness()
_forced: bool | None = None  # enable()/disable() override for tests


def global_witness() -> LockWitness:
    return _GLOBAL


def active() -> bool:
    if _forced is not None:
        return _forced
    return os.environ.get(_ENV_FLAG, "") not in ("", "0")


def opted_out() -> bool:
    """An explicit ``NANOTPU_LOCK_WITNESS=0`` is a user decision that
    in-process arming (the sim's ``lock_witness`` scenario knob) must
    respect — enable() alone would silently override it for the rest of
    the process."""
    return os.environ.get(_ENV_FLAG, "") == "0"


def enable() -> None:
    global _forced
    _forced = True


def disable() -> None:
    global _forced
    _forced = False


class _WitnessLock:
    """Wraps a ``threading.Lock``/``RLock``; every acquisition path —
    including the ``_release_save``/``_acquire_restore`` protocol
    ``Condition.wait`` drives — keeps the witness's per-thread held
    stack truthful."""

    def __init__(self, inner, name: str, witness: LockWitness):
        self._inner = inner
        self.name = name
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._witness.on_acquire(self.name)
        got = self._inner.acquire(blocking, timeout)
        if not got:
            self._witness.on_acquire_failed(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        self._witness.on_release(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition.wait protocol (RLock inner only) ------------------------
    def _release_save(self):
        n = self._witness.on_release_all(self.name)
        return self._inner._release_save(), n

    def _acquire_restore(self, state) -> None:
        inner_state, n = state
        self._inner._acquire_restore(inner_state)
        self._witness.on_acquire_n(self.name, max(n, 1))

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def wrap(inner, name: str, witness: LockWitness | None = None):
    """Instrument an existing primitive (tests with private witnesses)."""
    return _WitnessLock(inner, name, witness or _GLOBAL)


def make_lock(name: str):
    """A ``threading.Lock`` — instrumented iff the witness is active at
    construction time (locks are built at object construction, so tests
    and the sim flip activation before building their stacks)."""
    if active():
        return _WitnessLock(threading.Lock(), name, _GLOBAL)
    return threading.Lock()


def make_rlock(name: str):
    if active():
        return _WitnessLock(threading.RLock(), name, _GLOBAL)
    return threading.RLock()


def rlock_factory(name: str):
    """A zero-arg RLock constructor with ``active()`` resolved ONCE —
    for bulk construction sites (the warm restart builds thousands of
    NodeInfo locks, and one env probe per lock was a measured slice of
    the whole boot). Same witness coverage as :func:`make_rlock`: the
    activation decision just moves to factory creation time, which is
    when the per-lock decision was made anyway."""
    if active():
        return lambda: _WitnessLock(threading.RLock(), name, _GLOBAL)
    return threading.RLock


def make_condition(name: str):
    """A ``threading.Condition`` whose underlying RLock is instrumented;
    ``wait()`` releases/re-acquires THROUGH the witness so the held
    stack never lies across a park."""
    if active():
        return threading.Condition(
            _WitnessLock(threading.RLock(), name, _GLOBAL)
        )
    return threading.Condition()
