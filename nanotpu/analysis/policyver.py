"""policyver: the policy-program verifier as a nanolint pass.

The runtime verifier (:mod:`nanotpu.policy_ir.verify`) proves a
candidate scoring program safe to hot-load; THIS pass runs the same
proof at lint time over the in-tree program corpus
(``nanotpu/policy_ir/programs/``), so ``make lint`` refuses a tree
carrying a program the ``PolicyWatcher`` would reject at reload — the
verifier's typed violations surface as ordinary findings under
nanolint's exit contract, ignore budget, and ``--json`` rendering
(docs/static-analysis.md).

One verifier, two mouths: the pass does NOT reimplement any rule — it
maps :class:`~nanotpu.policy_ir.verify.Violation` records into findings
(message prefixed ``[<code>]`` so tests pin the typed code), which is
what keeps ``python -m nanotpu.analysis --pass policyver`` and the
reload path's acceptance decision identical by construction.

Fixture modules (anything outside ``nanotpu``) are verified as whole
programs when they define a ``score`` function — that is how seeded
program fixtures pin each banned construct to its typed finding,
without the pass claiming every unrelated fixture module in a mixed
tmp tree is a malformed program.
"""

from __future__ import annotations

import ast

from nanotpu.analysis.core import Finding, Module
from nanotpu.policy_ir.verify import verify_tree

PASS_NAME = "policyver"

#: in-tree programs live here; the registry module itself (the package
#: ``__init__``) is loader code, not a program
SCOPE = ("nanotpu.policy_ir.programs",)
_REGISTRY_MODULE = "nanotpu.policy_ir.programs"


class _PolicyVerPass:
    name = PASS_NAME
    doc = (
        "policy programs must pass the hot-load verifier: isolation, "
        "integer-only Q16 ops, bounded loops, totality, clamp proof, "
        "zero nondeterminism"
    )
    scope = SCOPE

    def run(self, modules: list[Module]) -> list[Finding]:
        findings: list[Finding] = []
        for mod in modules:
            if mod.name == _REGISTRY_MODULE:
                continue
            if not mod.name.startswith(SCOPE) and not any(
                isinstance(n, ast.FunctionDef) and n.name == "score"
                for n in mod.tree.body
            ):
                # fixture module that is not a policy program at all —
                # in-tree corpus modules are always verified
                continue
            for v in verify_tree(mod.tree):
                findings.append(Finding(
                    PASS_NAME, str(mod.path), v.line,
                    f"[{v.code}] {v.message}",
                ))
        return findings


PASS = _PolicyVerPass()
