"""Fault-injection layer: the failure taxonomy the scheduler must survive.

Every fault is seeded and counted, so a failing scenario names exactly what
it injected. The taxonomy (scenario ``faults`` section):

* ``node_flap``     — a node object is DELETED mid-run (its pods evicted)
  and re-created ``down_s`` later, exercising
  ``Dealer.remove_node``/``observe_node`` and gang-member loss. Gangs that
  lose a member are killed whole and resubmitted (a real JAX job dies with
  any worker).
* ``bind_failure``  — the pods/binding API call raises (injected through
  ``FakeClientset.before_bind``); the dealer must roll chip accounting
  back and the pod retries.
* ``drop_event``    — an informer watch event is never delivered; the
  controller's periodic resync must repair the divergence.
* ``dup_event``     — an event is delivered twice; every handler must be
  idempotent.
* ``metric_sync``   — chip load samples arrive every ``every_s``, applied
  ``delay_s`` late (delayed metric-sync): scoring must degrade, never
  crash or drift accounting.
* ``agent_restart`` — the Dealer is torn down and rebuilt from cluster
  annotations at the listed times (``Dealer._warm_from_cluster`` replay);
  occupancy must round-trip exactly.
* ``overload``      — periodic arrival BURSTS multiply the Poisson rate
  for ``burst_s`` every ``burst_every_s`` (extra arrivals drawn from a
  dedicated rng stream so toggling the fault never shifts the base
  workload): the pending queue, the controller's bounded coalescing
  queue, and the assume-TTL sweeper must absorb the surge and converge.
* ``api_brownout``  — windows where the SCHEDULER's apiserver writes
  (annotation PUT, pods/binding POST) all fail 503, injected through
  :class:`BrownoutClient` between the dealer and the cluster: the
  resilient client wrapper must retry, trip its breaker, fast-fail, and
  recover through a half-open probe once the window closes — with chip
  accounting exact throughout.
* ``scheduler_crash`` — the ACTIVE dealer process is killed at the
  listed times (docs/ha.md): its delta stream stops mid-lag, the warm
  standby promotes (reconciling only the lag window against informer
  state), and a FRESH standby boots behind the new active. Requires the
  scenario's ``ha`` section enabled; converged equality and zero
  double-binds are the certification.

Non-fail-stop faults (docs/ha.md "Split brain and fencing"; all require
lease-arbitrated leadership, ``ha.lease.enabled``, except gray which
needs only ``ha``):

* ``network_partition`` — windows where the CURRENT active's links are
  cut while BOTH processes stay alive and keep trying: scope ``api``
  (active↔apiserver, including the lease API and its informer tap),
  ``stream`` (active↔standby delta tail), or ``full`` (both). The
  standby steals the lease after TTL+skew, promotes, and the deposed
  active's in-flight writes die on its epoch fence — zero double-binds
  with two live dealers is the certification.
* ``clock_skew`` — per-process offset/drift on the lease+fence clocks;
  the lease's configured ``max_clock_skew_s`` margin must absorb it
  (no premature steal, no deposed-leader validity overlap).
* ``lease_thrash`` — windows where lease API calls from BOTH sides
  fail with ``fail_prob``; steal hysteresis + jittered backoff must
  bound promotions-per-window.
* ``gray_degradation`` — slow-not-dead: the active's scheduler-side
  writes fail with ``fail_prob`` inside the windows (the timeout-heavy
  half-alive apiserver link), exercising breaker/degraded-mode behavior
  without a clean cut.
"""

from __future__ import annotations

import random

from nanotpu.k8s.client import ApiError


class BrownoutClient:
    """Clientset proxy the DEALER sees: fails scheduler-side API writes
    while a brownout window is active, this side's link is partitioned,
    or a gray window's seeded coin says the write timed out.

    Deliberately not a ``FakeClientset`` hook: the sim's own lifecycle
    writes (pod completion, eviction, the sweeper's annotation strip) are
    kubelet/controller traffic that does not flow through the scheduler's
    client in a real cluster, so the faults must not touch them.

    One instance per PROCESS-equivalent (the active stack and the
    standby stack each have their own): ``partitioned``/``gray`` are
    set by the sim's partition/gray window events on the tap of
    whichever side is active at window open, and the cut follows the
    PROCESS — a mid-window leader swap does not move it."""

    def __init__(self, inner, faults: "FaultPlan"):
        self._inner = inner
        self._faults = faults
        #: True while a network_partition window cuts this side's
        #: apiserver link (scope api|full)
        self.partitioned = False
        #: True while a gray_degradation window afflicts this side
        self.gray = False

    def _check_write(self, what: str) -> None:
        if self.partitioned:
            self._faults.count_partition_rejection()
            raise ApiError(
                f"injected network partition ({what})", code=503
            )
        if self.gray and self._faults.gray_coin():
            raise ApiError(
                f"injected gray-failure timeout ({what})", code=504
            )
        self._faults.check_brownout(what)

    def update_pod(self, pod):
        self._check_write("update_pod")
        return self._inner.update_pod(pod)

    def bind_pod(self, namespace: str, name: str, node_name: str) -> None:
        self._check_write("bind_pod")
        return self._inner.bind_pod(namespace, name, node_name)

    # -- the lease API (coordination.k8s.io) -------------------------------
    # A partition that cuts this side from the apiserver cuts its lease
    # traffic too — that is exactly the non-fail-stop case: the active
    # cannot renew AND cannot hear that it lost. lease_thrash flaps the
    # lease API for EVERY side (the lease object's etcd is sick, not one
    # link).
    def _check_lease(self, what: str) -> None:
        if self.partitioned:
            self._faults.count_partition_rejection()
            raise ApiError(
                f"injected network partition ({what})", code=503
            )
        self._faults.check_lease_call(what)

    def get_lease(self, namespace: str, name: str) -> dict:
        self._check_lease("get_lease")
        return self._inner.get_lease(namespace, name)

    def create_lease(self, namespace: str, name: str, lease: dict) -> dict:
        self._check_lease("create_lease")
        return self._inner.create_lease(namespace, name, lease)

    def update_lease(self, namespace: str, name: str, lease: dict) -> dict:
        self._check_lease("update_lease")
        return self._inner.update_lease(namespace, name, lease)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultPlan:
    """Seeded per-run fault decisions + injection counters."""

    def __init__(self, spec: dict, rng: random.Random,
                 rng_thrash: random.Random | None = None,
                 rng_gray: random.Random | None = None):
        self.spec = spec
        self.rng = rng
        #: dedicated streams for the non-fail-stop faults (docs/ha.md):
        #: per-call coins live here exclusively so toggling a fault can
        #: never shift a sibling stream (the rng-isolation rule every
        #: fault lives under)
        self.rng_thrash = rng_thrash or random.Random()
        self.rng_gray = rng_gray or random.Random()
        #: set False during the settle phase: convergence is only checkable
        #: once the fault tap stops perturbing the event stream
        self.armed = True
        #: True inside an api_brownout window (core.py toggles via events)
        self.brownout_active = False
        #: True inside a lease_thrash window (core.py toggles via events)
        self.thrash_active = False
        self.counts = {
            "node_flaps": 0,
            "pods_evicted": 0,
            "gangs_killed": 0,
            "events_dropped": 0,
            "events_duplicated": 0,
            "binds_failed_injected": 0,
            "agent_restarts": 0,
            "metric_syncs": 0,
            "metric_samples_delayed": 0,
            "overload_arrivals": 0,
            "brownouts": 0,
            "brownout_rejections": 0,
            "scheduler_crashes": 0,
        }
        #: non-fail-stop fault counters, kept SEPARATE from ``counts``
        #: and merged into the report's fault_counts only when one of
        #: the four faults is configured (``nfs_armed``) — existing
        #: scenarios' reports, and their pinned digests, stay
        #: byte-identical
        self.counts_nfs = {
            "partitions": 0,
            "partition_rejections": 0,
            "lease_thrash_windows": 0,
            "lease_calls_failed": 0,
            "gray_windows": 0,
            "gray_failures_injected": 0,
        }

    @property
    def nfs_armed(self) -> bool:
        """True when any non-fail-stop fault is configured — the gate
        for the report's extra counter block (docs/ha.md)."""
        return bool(
            (self.spec["network_partition"].get("windows"))
            or self.spec["lease_thrash"].get("at_s")
            or self.spec["gray_degradation"].get("at_s")
            or any(
                float(self.spec["clock_skew"].get(k, 0) or 0) != 0.0
                for k in ("active_offset_s", "standby_offset_s",
                          "active_drift_ppm", "standby_drift_ppm")
            )
        )

    # -- schedule-time queries (used once, at sim setup) --------------------
    def flap_times(self, horizon_s: float) -> list[float]:
        every = float(self.spec["node_flap"].get("every_s", 0) or 0)
        if every <= 0:
            return []
        # first flap at every_s, then periodic; jitter would add nothing —
        # the flapped NODE is already drawn from the seeded rng
        return [t * every for t in range(1, int(horizon_s / every) + 1)
                if t * every < horizon_s]

    @property
    def flap_down_s(self) -> float:
        return float(self.spec["node_flap"].get("down_s", 3.0))

    def restart_times(self, horizon_s: float) -> list[float]:
        return sorted(
            float(t) for t in self.spec["agent_restart"].get("at_s", [])
            if 0 < float(t) < horizon_s
        )

    def crash_times(self, horizon_s: float) -> list[float]:
        """Active-dealer kill times (the HA failover fault, docs/ha.md)."""
        return sorted(
            float(t) for t in self.spec["scheduler_crash"].get("at_s", [])
            if 0 < float(t) < horizon_s
        )

    def metric_cadence(self) -> tuple[float, float]:
        """(every_s, delay_s); every_s <= 0 disables the metric pipeline."""
        ms = self.spec["metric_sync"]
        return float(ms.get("every_s", 0) or 0), float(ms.get("delay_s", 0.0))

    def overload_windows(self, horizon_s: float) -> list[tuple[float, float]]:
        """Burst windows [(start, end)) within the horizon."""
        ov = self.spec["overload"]
        every = float(ov.get("burst_every_s", 0) or 0)
        burst = float(ov.get("burst_s", 0) or 0)
        if every <= 0 or burst <= 0:
            return []
        return [
            (t * every, min(t * every + burst, horizon_s))
            for t in range(1, int(horizon_s / every) + 1)
            if t * every < horizon_s
        ]

    def overload_arrivals(
        self, workload: dict, horizon_s: float, rng: random.Random
    ) -> list[tuple[float, str]]:
        """Extra (arrival time, config) pairs inside the burst windows,
        at ``(rate_multiplier - 1) x`` the base Poisson rate — stacked on
        the untouched base stream, the in-window rate is multiplied.
        Draws come only from the dedicated ``rng`` (sim's rng_overload):
        toggling the fault cannot shift the base arrival sequence."""
        windows = self.overload_windows(horizon_s)
        if not windows or "mix" not in workload:
            # disabled, or a trace workload (explicit arrivals have no mix
            # to draw burst shapes from — bursts are a Poisson-mode fault)
            return []
        mult = float(self.spec["overload"].get("rate_multiplier", 4.0))
        extra_rate = float(workload.get("rate_per_s", 1.0)) * max(
            mult - 1.0, 0.0
        )
        if extra_rate <= 0:
            return []
        mix = workload["mix"]
        kinds = [k for k in sorted(mix) if mix.get(k, 0) > 0]
        weights = [float(mix[k]) for k in kinds]
        out: list[tuple[float, str]] = []
        for start, end in windows:
            t = start
            while True:
                t += rng.expovariate(extra_rate)
                if t >= end:
                    break
                out.append((t, rng.choices(kinds, weights=weights)[0]))
        self.counts["overload_arrivals"] += len(out)
        return out

    def partition_windows(
        self, horizon_s: float
    ) -> list[tuple[float, float, str]]:
        """``(start, end, scope)`` partition windows clipped inside the
        horizon (a window must CLOSE before settle so convergence is
        checkable; validation already ordered them non-overlapping)."""
        out = []
        for win in self.spec["network_partition"].get("windows") or []:
            start = float(win["at_s"])
            if not 0 < start < horizon_s:
                continue
            out.append((
                start,
                min(start + float(win["duration_s"]), horizon_s),
                str(win.get("scope", "api")),
            ))
        return out

    def thrash_windows(self, horizon_s: float) -> list[tuple[float, float]]:
        th = self.spec["lease_thrash"]
        duration = float(th.get("duration_s", 0) or 0)
        if duration <= 0:
            return []
        return [
            (t, min(t + duration, horizon_s))
            for t in sorted(float(x) for x in th.get("at_s", []))
            if 0 < t < horizon_s
        ]

    def gray_windows(self, horizon_s: float) -> list[tuple[float, float]]:
        gd = self.spec["gray_degradation"]
        duration = float(gd.get("duration_s", 0) or 0)
        if duration <= 0:
            return []
        return [
            (t, min(t + duration, horizon_s))
            for t in sorted(float(x) for x in gd.get("at_s", []))
            if 0 < t < horizon_s
        ]

    def count_partition_rejection(self) -> None:
        self.counts_nfs["partition_rejections"] += 1

    def check_lease_call(self, what: str) -> None:
        """Raise for a lease API call inside a thrash window (coin on
        the dedicated rng_thrash stream; both sides flap — the lease
        backend is sick, not one link)."""
        if not (self.armed and self.thrash_active):
            return
        prob = float(self.spec["lease_thrash"].get("fail_prob", 0.5))
        if self.rng_thrash.random() < prob:
            self.counts_nfs["lease_calls_failed"] += 1
            raise ApiError(f"injected lease-API flap ({what})", code=503)

    def gray_coin(self) -> bool:
        """One seeded should-this-write-time-out decision inside a gray
        window (dedicated rng_gray stream)."""
        if not self.armed:
            return False
        prob = float(self.spec["gray_degradation"].get("fail_prob", 0.5))
        if self.rng_gray.random() < prob:
            self.counts_nfs["gray_failures_injected"] += 1
            return True
        return False

    def brownout_windows(self, horizon_s: float) -> list[tuple[float, float]]:
        """API-brownout windows [(start, end)) clipped inside the horizon
        (a window must CLOSE before settle so convergence is checkable)."""
        bo = self.spec["api_brownout"]
        duration = float(bo.get("duration_s", 0) or 0)
        if duration <= 0:
            return []
        return [
            (t, min(t + duration, horizon_s))
            for t in sorted(float(x) for x in bo.get("at_s", []))
            if 0 < t < horizon_s
        ]

    def check_brownout(self, what: str) -> None:
        """Raise 503 for a scheduler-side API write inside a brownout."""
        if self.armed and self.brownout_active:
            self.counts["brownout_rejections"] += 1
            raise ApiError(f"injected API brownout ({what})", code=503)

    # -- event-time decisions (seeded; order of calls is deterministic) -----
    def drop_event(self) -> bool:
        if not self.armed:
            return False
        if self.rng.random() < float(self.spec["drop_event"].get("prob", 0)):
            self.counts["events_dropped"] += 1
            return True
        return False

    def duplicate_event(self) -> bool:
        if not self.armed:
            return False
        if self.rng.random() < float(self.spec["dup_event"].get("prob", 0)):
            self.counts["events_duplicated"] += 1
            return True
        return False

    def make_bind_hook(self):
        """A ``FakeClientset.before_bind`` callable, or None when the
        fault is disabled. Installed once per dealer incarnation."""
        prob = float(self.spec["bind_failure"].get("prob", 0))
        if prob <= 0:
            return None

        def hook(namespace: str, name: str, node: str) -> None:
            if self.armed and self.rng.random() < prob:
                self.counts["binds_failed_injected"] += 1
                raise ApiError(
                    f"injected bind failure for {namespace}/{name}", code=503
                )

        return hook
