"""Fault-injection layer: the failure taxonomy the scheduler must survive.

Every fault is seeded and counted, so a failing scenario names exactly what
it injected. The taxonomy (scenario ``faults`` section):

* ``node_flap``     — a node object is DELETED mid-run (its pods evicted)
  and re-created ``down_s`` later, exercising
  ``Dealer.remove_node``/``observe_node`` and gang-member loss. Gangs that
  lose a member are killed whole and resubmitted (a real JAX job dies with
  any worker).
* ``bind_failure``  — the pods/binding API call raises (injected through
  ``FakeClientset.before_bind``); the dealer must roll chip accounting
  back and the pod retries.
* ``drop_event``    — an informer watch event is never delivered; the
  controller's periodic resync must repair the divergence.
* ``dup_event``     — an event is delivered twice; every handler must be
  idempotent.
* ``metric_sync``   — chip load samples arrive every ``every_s``, applied
  ``delay_s`` late (delayed metric-sync): scoring must degrade, never
  crash or drift accounting.
* ``agent_restart`` — the Dealer is torn down and rebuilt from cluster
  annotations at the listed times (``Dealer._warm_from_cluster`` replay);
  occupancy must round-trip exactly.
"""

from __future__ import annotations

import random

from nanotpu.k8s.client import ApiError


class FaultPlan:
    """Seeded per-run fault decisions + injection counters."""

    def __init__(self, spec: dict, rng: random.Random):
        self.spec = spec
        self.rng = rng
        #: set False during the settle phase: convergence is only checkable
        #: once the fault tap stops perturbing the event stream
        self.armed = True
        self.counts = {
            "node_flaps": 0,
            "pods_evicted": 0,
            "gangs_killed": 0,
            "events_dropped": 0,
            "events_duplicated": 0,
            "binds_failed_injected": 0,
            "agent_restarts": 0,
            "metric_syncs": 0,
            "metric_samples_delayed": 0,
        }

    # -- schedule-time queries (used once, at sim setup) --------------------
    def flap_times(self, horizon_s: float) -> list[float]:
        every = float(self.spec["node_flap"].get("every_s", 0) or 0)
        if every <= 0:
            return []
        # first flap at every_s, then periodic; jitter would add nothing —
        # the flapped NODE is already drawn from the seeded rng
        return [t * every for t in range(1, int(horizon_s / every) + 1)
                if t * every < horizon_s]

    @property
    def flap_down_s(self) -> float:
        return float(self.spec["node_flap"].get("down_s", 3.0))

    def restart_times(self, horizon_s: float) -> list[float]:
        return sorted(
            float(t) for t in self.spec["agent_restart"].get("at_s", [])
            if 0 < float(t) < horizon_s
        )

    def metric_cadence(self) -> tuple[float, float]:
        """(every_s, delay_s); every_s <= 0 disables the metric pipeline."""
        ms = self.spec["metric_sync"]
        return float(ms.get("every_s", 0) or 0), float(ms.get("delay_s", 0.0))

    # -- event-time decisions (seeded; order of calls is deterministic) -----
    def drop_event(self) -> bool:
        if not self.armed:
            return False
        if self.rng.random() < float(self.spec["drop_event"].get("prob", 0)):
            self.counts["events_dropped"] += 1
            return True
        return False

    def duplicate_event(self) -> bool:
        if not self.armed:
            return False
        if self.rng.random() < float(self.spec["dup_event"].get("prob", 0)):
            self.counts["events_duplicated"] += 1
            return True
        return False

    def make_bind_hook(self):
        """A ``FakeClientset.before_bind`` callable, or None when the
        fault is disabled. Installed once per dealer incarnation."""
        prob = float(self.spec["bind_failure"].get("prob", 0))
        if prob <= 0:
            return None

        def hook(namespace: str, name: str, node: str) -> None:
            if self.armed and self.rng.random() < prob:
                self.counts["binds_failed_injected"] += 1
                raise ApiError(
                    f"injected bind failure for {namespace}/{name}", code=503
                )

        return hook
