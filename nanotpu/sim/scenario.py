"""Scenario schema: parse + validate the JSON that drives a simulation.

A scenario file is the single reproducible artifact of a sim run: fleet,
workload, fault plan, and cadence knobs. ``load_scenario`` normalizes every
field to its default so the rest of the package never touches raw dicts
defensively. Schema (see docs/simulation.md for the full field reference)::

    {
      "name": "smoke",
      "fleet": {"pools": [{"generation": "v5p", "hosts": 16,
                           "slice_hosts": 8}]},
      "policy": "binpack",
      "horizon_s": 30.0,
      "workload": {
        "kind": "poisson",           # or "trace"
        "rate_per_s": 1.2,           # job arrival rate (poisson)
        "mix": {"fractional": 0.3, "spread": 0.2, "multi_container": 0.2,
                "gang_llama": 0.15, "mixtral": 0.15},
        "lifetime_s": {"dist": "exp", "mean": 12.0},
        "gang_size": 8,
        "arrivals": []               # trace mode: explicit [{t, config, ...}]
      },
      "faults": {
        "node_flap": {"every_s": 6.0, "down_s": 3.0},
        "bind_failure": {"prob": 0.05},
        "drop_event": {"prob": 0.03},
        "dup_event": {"prob": 0.03},
        "metric_sync": {"every_s": 2.0, "delay_s": 1.0},
        "agent_restart": {"at_s": [15.0]},
        "overload": {"burst_every_s": 8.0, "burst_s": 3.0,
                     "rate_multiplier": 4.0},
        "api_brownout": {"at_s": [12.0], "duration_s": 4.0},
        "scheduler_crash": {"at_s": [20.0]},  # kill the ACTIVE dealer —
                                     # requires ha.enabled (docs/ha.md)
        "network_partition": {       # non-fail-stop (docs/ha.md "Split
                                     # brain"): BOTH processes stay
                                     # alive; the window cuts the
                                     # CURRENT active's links. scope:
                                     # "api" (active<->apiserver incl.
                                     # the lease API + its informer),
                                     # "stream" (active<->standby delta
                                     # tail), "full" (both). Requires
                                     # ha.lease.enabled.
          "windows": [{"at_s": 10.0, "duration_s": 3.0, "scope": "api"}]
        },
        "clock_skew": {              # per-process lease/fence clock
                                     # offset+drift (requires
                                     # ha.lease.enabled); the lease's
                                     # skew margin must absorb it
          "active_offset_s": 0.0, "standby_offset_s": 0.0,
          "active_drift_ppm": 0.0, "standby_drift_ppm": 0.0
        },
        "lease_thrash": {            # flapping lease API: lease calls
                                     # from BOTH sides fail with prob
                                     # inside the windows (requires
                                     # ha.lease.enabled); steal
                                     # hysteresis + backoff must bound
                                     # promotions
          "at_s": [], "duration_s": 0.0, "fail_prob": 0.5
        },
        "gray_degradation": {        # slow-not-dead: the CURRENT
                                     # active's scheduler-side writes
                                     # fail with prob inside the
                                     # windows (timeouts), exercising
                                     # degraded mode without a clean
                                     # partition
          "at_s": [], "duration_s": 0.0, "fail_prob": 0.5
        }
      },
      "ha": {                        # warm-standby dealer pair
                                     # (docs/ha.md); absent/disabled
                                     # keeps every existing digest
                                     # byte-identical
        "enabled": false,
        "lag_events": 8,             # delta records the standby's apply
                                     # trails the stream by (the sim's
                                     # stream-latency model; the crash's
                                     # reconcile window)
        "lease": {                   # lease-arbitrated leadership on
                                     # virtual time (docs/ha.md "Split
                                     # brain and fencing"): epoch
                                     # fences on both write paths,
                                     # ha_tick renew/steal events, and
                                     # live leader swaps (both stacks
                                     # stay alive). Off keeps the
                                     # crash-fault promotion path — and
                                     # every existing digest —
                                     # byte-identical.
          "enabled": false,
          "ttl_s": 1.0,
          "period_s": 0.25,          # ha_tick cadence (renew + probes)
          "steal_hysteresis": 2,
          "max_clock_skew_s": 0.0,
          "backoff_s": 0.0
        },
        "degraded_budget_s": 0.0,    # >0: a DegradedMonitor per side
                                     # (docs/ha.md "Degraded mode") —
                                     # recovery/batch/autoscale cycles
                                     # skip while the active is
                                     # degraded, transitions journaled
        "promotion_bound": 0,        # >0: settle asserts total
                                     # promotions <= this (violation
                                     # otherwise) — the promotion-storm
                                     # certification
        "followers": 0,              # >0: that many read-plane follower
                                     # stacks (docs/read-plane.md) tail
                                     # the leader's stream, answer reads
                                     # within follower_lag_bound, and
                                     # re-anchor across crashes; settle
                                     # asserts zero occupancy drift and
                                     # zero read downtime. 0 keeps every
                                     # existing digest byte-identical
        "follower_lag_bound": 256    # staleness bound in delta events:
                                     # past it a follower's sampled read
                                     # counts as refused (NotSynced),
                                     # never as stale bytes served
      },
      "resync_every_s": 5.0,
      "sample_every_s": 1.0,
      "retry_every_s": 0.5,
      "invariant_every_events": 1,
      "assume_ttl_s": 0.0,           # >0: sweep assumed-never-bound pods
      "queue_max": 0,                # >0: bound the controller sync queue
      "shards": 1,                   # 1 (single publication domain,
                                     # byte-identical to the pre-shard
                                     # dealer) or "auto" (one RCU shard
                                     # per slice family — docs/sharding.md)
      "pipeline": 1,                 # commit-pipeline depth
                                     # (docs/bind-pipeline.md): 1 = the
                                     # pre-pipeline write path; >1 arms
                                     # publish coalescing + the batched
                                     # gang-commit pool (the sim is
                                     # single-threaded, so behavior — and
                                     # the digest — stays identical; the
                                     # soak proves the armed code path
                                     # keeps every invariant)
      "metric_from_allocation": false, # true: metric-sync samples mirror
                                     # the REAL per-card allocation
                                     # (used fraction) instead of seeded
                                     # noise — what calibrates the
                                     # throughput rater's contention
                                     # EWMA end to end (docs/scoring.md)
      "throughput_report": false,    # true: the report gains a
                                     # deterministic `throughput`
                                     # section (modeled aggregate vs
                                     # oracle, docs/scoring.md) and a
                                     # settle journal line — off keeps
                                     # existing scenario digests
                                     # byte-identical
      "recovery": {                  # capacity-recovery plane
                                     # (docs/defrag.md); absent/disabled
                                     # keeps every existing digest
                                     # byte-identical
        "enabled": false,
        "every_s": 0.5,              # recovery-cycle cadence
        "eviction_budget": 8,        # max preemptions per cycle
        "migration_budget": 4,       # max defrag migrations per cycle
        "sweep_budget": 2,           # steady-state consolidation trickle
        "backfill": true,            # lease short pods into gang holes
        "lease_grace_s": 0.5,
        "gang_start_horizon_s": 5.0, # hole's promised gang start
        "hole_ttl_s": 30.0
      },
      "telemetry": {                 # fleet telemetry timeline + SLO
                                     # watchdog + flight recorder
                                     # (docs/observability.md); absent/
                                     # disabled keeps every existing
                                     # digest byte-identical
        "enabled": false,
        "every_s": 1.0,              # telemetry_tick cadence (virtual)
        "capacity": 512,             # timeline ring depth
        "flight_ticks": 64,          # ticks bundled per flight dump
        "flight_path": "",           # bundle file ("" = in-memory only,
                                     # digest still pinned in the report)
        "slo": []                    # SLO objectives (same schema as
                                     # policy.yaml's slo: section)
      },
      "export": {                    # durable decision-record export
                                     # (docs/observability.md "Decision
                                     # export format"); absent/disabled
                                     # keeps every existing digest
                                     # byte-identical
        "enabled": false,
        "path": "",                  # "" = sink-less (counters + digest)
        "sample": 1,                 # sticky 1-in-N per pod uid (0 off)
        "max_bytes": 8388608         # segment bound before rotation
      },
      "batch": {                     # joint batch admission
                                     # (docs/batch-admission.md); absent/
                                     # disabled keeps every existing
                                     # digest byte-identical
        "enabled": false,
        "every_s": 0.5,              # batch_admit cycle cadence (virtual)
        "lookahead": 4,              # best-fit finalists per pick
        "max_batch": 128             # demands per joint solve
      },
      "serving": {                   # scheduler<->serving loop
                                     # (docs/serving-loop.md); absent/
                                     # disabled keeps every existing
                                     # digest byte-identical
        "enabled": false,
        "every_s": 0.25,             # serving_tick cadence (virtual)
        "users": 1000000,            # synthetic user base
        "requests_per_user_h": 1.08, # per-user request rate at PEAK
        "diurnal": {"period_s": 120.0, "trough_frac": 0.2},
        "tokens_out_mean": 64.0,     # drawn decode length per request
        "prefill_s": 0.15,           # admission prefill latency
        "slots_per_replica": 64,
        "tok_s_per_chip": 350.0,     # v5p-normalized decode rate
        "tok_s_per_request": 25.0,   # single-row decode ceiling
        "replica_percent": 400,      # chips per replica pod (tp=4)
        "replica_priority": 50,
        "degraded": {"every": 0, "derate": 0.5},  # hidden host derate
        "feedback": true,            # serving tap -> ThroughputModel
        "static_replicas": 0,        # fixed fleet when autoscale off
        "autoscale": {"enabled": true, "every_s": 0.5, "min": 1,
                      "max": 16, "target_util": 0.75,
                      "up_cooldown_s": 0.0, "down_cooldown_s": 5.0,
                      "drain_deadline_s": 10.0}
      },
      "lock_witness": false,         # true: instrument every lock and
                                     # assert acquisition-order acyclicity
                                     # at teardown (docs/static-analysis.md)
      "trace": true                  # sampling=all tracing + decision
                                     # audit on the virtual clock; the
                                     # report gains a deterministic
                                     # `traces` digest section
                                     # (docs/observability.md)
    }

Omitted sections disable that feature (``faults: {}`` == fault-free run).
"""

from __future__ import annotations

import json
from pathlib import Path

from nanotpu import types

#: The five BASELINE.json config archetypes the workload generator knows.
CONFIG_KINDS = (
    "fractional", "spread", "multi_container", "gang_llama", "mixtral",
)

_POLICIES = (
    types.POLICY_BINPACK, types.POLICY_SPREAD, types.POLICY_THROUGHPUT,
)


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"bad scenario: {msg}")


def normalize_scenario(raw: dict) -> dict:
    """Validate ``raw`` and return a fully-defaulted copy."""
    _require(isinstance(raw, dict), "scenario must be a JSON object")
    fleet = raw.get("fleet") or {}
    _require(bool(fleet.get("pools")), "fleet.pools is required")
    policy = raw.get("policy", types.POLICY_BINPACK)
    _require(
        policy in _POLICIES or policy.startswith("program:"),
        f"policy {policy!r} not in {_POLICIES} (random is "
        "non-deterministic; program:<name> serves a verified policy "
        "program, docs/policy-programs.md)",
    )
    if policy.startswith("program:"):
        # resolve NOW so a bad program name / unprovable program is a
        # scenario error, not a mid-run construction crash; integer-only
        # Q16 programs are deterministic by the verifier's proof
        from nanotpu.policy_ir import PolicyProgramError, load_program

        try:
            load_program(policy[len("program:"):])
        except (ValueError, PolicyProgramError) as e:
            _require(False, f"policy {policy!r}: {e}")
    horizon = float(raw.get("horizon_s", 30.0))
    _require(horizon > 0, "horizon_s must be > 0")

    w = dict(raw.get("workload") or {})
    kind = w.setdefault("kind", "poisson")
    _require(kind in ("poisson", "trace"), f"workload.kind {kind!r}")
    if kind == "poisson":
        w.setdefault("rate_per_s", 1.0)
        _require(w["rate_per_s"] > 0, "workload.rate_per_s must be > 0")
        mix = w.setdefault("mix", {k: 1.0 for k in CONFIG_KINDS})
        _require(
            mix and all(k in CONFIG_KINDS for k in mix),
            f"workload.mix keys must be among {CONFIG_KINDS}",
        )
        _require(
            sum(mix.values()) > 0 and all(v >= 0 for v in mix.values()),
            "workload.mix weights must be >= 0 and not all zero",
        )
    else:
        arrivals = w.setdefault("arrivals", [])
        _require(isinstance(arrivals, list) and arrivals,
                 "trace workload needs a non-empty arrivals list")
        for a in arrivals:
            _require(
                a.get("config") in CONFIG_KINDS,
                f"trace arrival config {a.get('config')!r}",
            )
            _require(float(a.get("t", -1)) >= 0, "trace arrival needs t >= 0")
    life = w.setdefault("lifetime_s", {"dist": "exp", "mean": 15.0})
    _require(
        life.get("dist", "exp") in ("exp", "fixed"),
        f"lifetime_s.dist {life.get('dist')!r}",
    )
    _require(float(life.get("mean", 0)) > 0, "lifetime_s.mean must be > 0")
    w.setdefault("gang_size", 8)
    w.setdefault("replicas", 4)
    # capacity-recovery workload shaping (docs/defrag.md). Defaults keep
    # every existing scenario's jobs — and digests — byte-identical.
    overrides = w.setdefault("lifetime_overrides", {})
    _require(
        isinstance(overrides, dict)
        and all(k in CONFIG_KINDS for k in overrides),
        f"workload.lifetime_overrides keys must be among {CONFIG_KINDS}",
    )
    for key, spec in overrides.items():
        _require(
            isinstance(spec, dict)
            and spec.get("dist", "exp") in ("exp", "fixed")
            and float(spec.get("mean", 0)) > 0,
            f"workload.lifetime_overrides[{key!r}] needs dist exp|fixed "
            "and mean > 0",
        )
    priorities = w.setdefault("priorities", {})
    _require(
        isinstance(priorities, dict)
        and all(k in CONFIG_KINDS for k in priorities),
        f"workload.priorities keys must be among {CONFIG_KINDS}",
    )
    gang_percent = int(w.setdefault("gang_percent", 200))
    _require(
        gang_percent > 0
        and (gang_percent < 100 or gang_percent % 100 == 0),
        "workload.gang_percent must be a valid per-member chip demand",
    )
    spread_percent = int(w.setdefault("spread_percent", 100))
    _require(
        spread_percent > 0
        and (spread_percent < 100 or spread_percent % 100 == 0),
        "workload.spread_percent must be a valid per-replica chip demand",
    )
    # job semantics: departures fire lifetime_s after the job STARTS
    # (non-gang: first pod bound; gang: fully bound) instead of after
    # arrival — waiting delays service instead of destroying it, so a
    # recovery-induced delay shifts work later rather than erasing
    # chip-seconds (the occupancy-equality basis of the defrag
    # certification, docs/defrag.md). Default False == the historical
    # window semantics, byte-identical digests.
    w.setdefault("lifetime_from_bind", False)
    # all-or-nothing gang admission: members bind only when the WHOLE
    # gang can place at once (no partial holds — the sim-level analogue
    # of the dealer's strict barrier, which a single-threaded driver
    # cannot park; docs/defrag.md "Strict gangs in the sim")
    w.setdefault("gang_strict", False)

    f = dict(raw.get("faults") or {})
    for key in ("node_flap", "bind_failure", "drop_event", "dup_event",
                "metric_sync", "agent_restart", "overload", "api_brownout",
                "scheduler_crash", "network_partition", "clock_skew",
                "lease_thrash", "gray_degradation"):
        f.setdefault(key, {})
    for key in ("bind_failure", "drop_event", "dup_event"):
        prob = float(f[key].get("prob", 0.0))
        _require(0.0 <= prob <= 1.0, f"faults.{key}.prob must be in [0, 1]")
    _require(
        float(f["overload"].get("rate_multiplier", 4.0)) >= 1.0,
        "faults.overload.rate_multiplier must be >= 1",
    )
    _require(
        float(f["api_brownout"].get("duration_s", 0) or 0) >= 0,
        "faults.api_brownout.duration_s must be >= 0",
    )
    windows = f["network_partition"].get("windows") or []
    _require(isinstance(windows, list), "network_partition.windows")
    last_end = -1.0
    for win in windows:
        _require(
            isinstance(win, dict)
            and float(win.get("duration_s", 0)) > 0
            and float(win.get("at_s", -1)) >= 0
            and win.get("scope", "api") in ("api", "stream", "full"),
            "network_partition windows need at_s >= 0, duration_s > 0, "
            "scope in api|stream|full",
        )
        _require(
            float(win["at_s"]) >= last_end,
            "network_partition windows must be sorted and non-overlapping",
        )
        last_end = float(win["at_s"]) + float(win["duration_s"])
    for key in ("lease_thrash", "gray_degradation"):
        prob = float(f[key].get("fail_prob", 0.5))
        _require(0.0 <= prob <= 1.0,
                 f"faults.{key}.fail_prob must be in [0, 1]")
        duration = float(f[key].get("duration_s", 0) or 0)
        _require(duration >= 0, f"faults.{key}.duration_s must be >= 0")
        # windows toggle one shared flag, so an overlap would let the
        # FIRST window's end event silently disarm the second — same
        # rule network_partition validates
        starts = sorted(float(t) for t in f[key].get("at_s", []))
        _require(
            all(
                b - a >= duration
                for a, b in zip(starts, starts[1:])
            ),
            f"faults.{key}.at_s windows must not overlap "
            "(spacing >= duration_s)",
        )
    shards = raw.get("shards", 1)
    _require(
        shards in (1, "auto"),
        f"shards must be 1 or 'auto', got {shards!r}",
    )
    pipeline = raw.get("pipeline", 1)
    _require(
        isinstance(pipeline, int) and not isinstance(pipeline, bool)
        and pipeline >= 1,
        f"pipeline must be an int >= 1, got {pipeline!r}",
    )
    tel = dict(raw.get("telemetry") or {})
    try:
        from nanotpu.metrics.slo import parse_objectives

        slo = parse_objectives(tel.get("slo") or [])
    except ValueError as e:
        raise ValueError(f"bad scenario: telemetry.slo: {e}") from e
    telemetry = {
        "enabled": bool(tel.get("enabled", False)),
        "every_s": float(tel.get("every_s", 1.0)),
        "capacity": int(tel.get("capacity", 512)),
        "flight_ticks": int(tel.get("flight_ticks", 64)),
        "flight_path": str(tel.get("flight_path", "")),
        "slo": slo,
    }
    _require(
        not telemetry["enabled"] or telemetry["every_s"] > 0,
        "telemetry.every_s must be > 0 when telemetry is enabled",
    )
    _require(
        telemetry["capacity"] > 0 and telemetry["flight_ticks"] > 0,
        "telemetry.capacity and telemetry.flight_ticks must be > 0",
    )

    exp = dict(raw.get("export") or {})
    export = {
        # durable decision-record export (docs/observability.md
        # "Decision export format"): the exporter runs sink-less by
        # default (path "" = counters + digest only) so
        # --check-determinism certifies the stream bytes with no
        # tmp-file plumbing; a path writes the crc-framed JSONL file
        "enabled": bool(exp.get("enabled", False)),
        "path": str(exp.get("path", "")),
        "sample": int(exp.get("sample", 1)),
        "max_bytes": int(exp.get("max_bytes", 8 * 1024 * 1024)),
    }
    _require(
        export["sample"] >= 0, "export.sample must be >= 0",
    )
    _require(
        export["max_bytes"] > 0, "export.max_bytes must be > 0",
    )

    bat = dict(raw.get("batch") or {})
    batch = {
        "enabled": bool(bat.get("enabled", False)),
        "every_s": float(bat.get("every_s", 0.5)),
        "lookahead": int(bat.get("lookahead", 4)),
        "max_batch": int(bat.get("max_batch", 128)),
    }
    _require(
        not batch["enabled"] or batch["every_s"] > 0,
        "batch.every_s must be > 0 when batch admission is enabled",
    )
    _require(
        batch["lookahead"] >= 1 and batch["max_batch"] >= 1,
        "batch.lookahead and batch.max_batch must be >= 1",
    )

    srv = dict(raw.get("serving") or {})
    asc = dict(srv.get("autoscale") or {})
    diurnal = dict(srv.get("diurnal") or {})
    degraded = dict(srv.get("degraded") or {})
    serving = {
        "enabled": bool(srv.get("enabled", False)),
        "every_s": float(srv.get("every_s", 0.25)),
        "users": int(srv.get("users", 1_000_000)),
        "requests_per_user_h": float(srv.get("requests_per_user_h", 1.08)),
        "diurnal": {
            "period_s": float(diurnal.get("period_s", 120.0)),
            "trough_frac": float(diurnal.get("trough_frac", 0.2)),
        },
        "tokens_out_mean": float(srv.get("tokens_out_mean", 64.0)),
        "prefill_s": float(srv.get("prefill_s", 0.15)),
        "slots_per_replica": int(srv.get("slots_per_replica", 64)),
        "tok_s_per_chip": float(srv.get("tok_s_per_chip", 350.0)),
        "tok_s_per_request": float(srv.get("tok_s_per_request", 25.0)),
        "replica_percent": int(srv.get("replica_percent", 400)),
        "replica_priority": int(srv.get("replica_priority", 50)),
        "degraded": {
            "every": int(degraded.get("every", 0)),
            "derate": float(degraded.get("derate", 0.5)),
        },
        "feedback": bool(srv.get("feedback", True)),
        "static_replicas": int(srv.get("static_replicas", 0)),
        "autoscale": {
            "enabled": bool(asc.get("enabled", True)),
            "every_s": float(asc.get("every_s", 0.5)),
            "min": int(asc.get("min", 1)),
            "max": int(asc.get("max", 16)),
            "target_util": float(asc.get("target_util", 0.75)),
            "up_cooldown_s": float(asc.get("up_cooldown_s", 0.0)),
            "down_cooldown_s": float(asc.get("down_cooldown_s", 5.0)),
            "drain_deadline_s": float(asc.get("drain_deadline_s", 10.0)),
        },
    }
    if serving["enabled"]:
        _require(serving["every_s"] > 0,
                 "serving.every_s must be > 0 when serving is enabled")
        _require(
            serving["users"] > 0 and serving["requests_per_user_h"] > 0,
            "serving.users and serving.requests_per_user_h must be > 0",
        )
        _require(serving["diurnal"]["period_s"] > 0,
                 "serving.diurnal.period_s must be > 0")
        _require(0.0 <= serving["diurnal"]["trough_frac"] <= 1.0,
                 "serving.diurnal.trough_frac must be in [0, 1]")
        _require(
            serving["tokens_out_mean"] > 0
            and serving["tok_s_per_chip"] > 0
            and serving["tok_s_per_request"] > 0,
            "serving token rates must be > 0",
        )
        _require(
            serving["slots_per_replica"] >= 1,
            "serving.slots_per_replica must be >= 1",
        )
        pct = serving["replica_percent"]
        _require(
            pct > 0 and (pct < 100 or pct % 100 == 0),
            "serving.replica_percent must be a valid chip demand",
        )
        _require(
            0.0 <= serving["degraded"]["derate"] < 1.0,
            "serving.degraded.derate must be in [0, 1)",
        )
        a = serving["autoscale"]
        if a["enabled"]:
            _require(
                a["every_s"] > 0 and 0 <= a["min"] <= a["max"],
                "serving.autoscale needs every_s > 0 and 0 <= min <= max",
            )
            _require(
                0.0 < a["target_util"] <= 1.0,
                "serving.autoscale.target_util must be in (0, 1]",
            )
        else:
            _require(
                serving["static_replicas"] >= 1,
                "serving.static_replicas must be >= 1 when the "
                "autoscaler is off (a serving scenario needs a fleet)",
            )

    ha_raw = dict(raw.get("ha") or {})
    lease_raw = dict(ha_raw.get("lease") or {})
    shadow_raw = dict(ha_raw.get("shadow") or {})
    ha = {
        "enabled": bool(ha_raw.get("enabled", False)),
        "lag_events": int(ha_raw.get("lag_events", 8)),
        "followers": int(ha_raw.get("followers", 0)),
        "follower_lag_bound": int(ha_raw.get("follower_lag_bound", 256)),
        "lease": {
            "enabled": bool(lease_raw.get("enabled", False)),
            "ttl_s": float(lease_raw.get("ttl_s", 1.0)),
            "period_s": float(lease_raw.get("period_s", 0.25)),
            "steal_hysteresis": int(lease_raw.get("steal_hysteresis", 2)),
            "max_clock_skew_s": float(
                lease_raw.get("max_clock_skew_s", 0.0)
            ),
            "backoff_s": float(lease_raw.get("backoff_s", 0.0)),
        },
        "degraded_budget_s": float(ha_raw.get("degraded_budget_s", 0.0)),
        "promotion_bound": int(ha_raw.get("promotion_bound", 0)),
        # shadow-mode A/B (docs/policy-programs.md): audition a verified
        # policy program on the follower fleet, divergences ledgered and
        # reported in the deterministic `shadow` report section
        "shadow": {
            "enabled": bool(shadow_raw.get("enabled", False)),
            "program": str(shadow_raw.get("program", "binpack_q16")),
        },
    }
    _require(
        ha["lag_events"] >= 0,
        "ha.lag_events must be >= 0",
    )
    _require(
        ha["followers"] >= 0 and ha["follower_lag_bound"] >= 0,
        "ha.followers and ha.follower_lag_bound must be >= 0",
    )
    _require(
        ha["followers"] == 0 or ha["enabled"],
        "ha.followers requires ha.enabled (followers tail the "
        "leader's delta stream)",
    )
    lease = ha["lease"]
    if lease["enabled"]:
        _require(ha["enabled"], "ha.lease requires ha.enabled")
        _require(
            lease["ttl_s"] > 0 and lease["period_s"] > 0,
            "ha.lease.ttl_s and period_s must be > 0",
        )
        _require(
            0.0 <= lease["max_clock_skew_s"] < lease["ttl_s"],
            "ha.lease.max_clock_skew_s must be in [0, ttl)",
        )
        _require(
            lease["steal_hysteresis"] >= 1 and lease["backoff_s"] >= 0,
            "ha.lease.steal_hysteresis must be >= 1, backoff_s >= 0",
        )
    _require(
        ha["degraded_budget_s"] >= 0 and ha["promotion_bound"] >= 0,
        "ha.degraded_budget_s and ha.promotion_bound must be >= 0",
    )
    if ha["shadow"]["enabled"]:
        _require(
            ha["followers"] >= 1,
            "ha.shadow requires ha.followers >= 1 (candidates audition "
            "on the follower fleet, never the leader)",
        )
        _require(
            bool(ha["shadow"]["program"]),
            "ha.shadow.program must name a policy program",
        )
        # resolve NOW, same rule as the policy "program:" knob: an
        # unknown or unprovable candidate is a scenario error, not a
        # mid-run crash on the first shadow cycle
        from nanotpu.policy_ir import PolicyProgramError, load_program

        try:
            load_program(ha["shadow"]["program"])
        except (ValueError, PolicyProgramError) as e:
            _require(False, f"ha.shadow.program: {e}")
    _require(
        not f["scheduler_crash"].get("at_s") or ha["enabled"],
        "faults.scheduler_crash requires ha.enabled (there is no "
        "standby to promote otherwise)",
    )
    _require(
        not f["scheduler_crash"].get("at_s") or not lease["enabled"],
        "faults.scheduler_crash and ha.lease are mutually exclusive: "
        "the crash fault's adopt-and-rebuild promotion path assumes "
        "the sim owns leadership, the lease mode arbitrates it",
    )
    for key in ("network_partition", "clock_skew", "lease_thrash"):
        spec = f[key]
        armed = bool(
            spec.get("windows") or spec.get("at_s")
            or any(
                float(spec.get(k2, 0) or 0) != 0.0
                for k2 in ("active_offset_s", "standby_offset_s",
                           "active_drift_ppm", "standby_drift_ppm")
            )
        )
        _require(
            not armed or lease["enabled"],
            f"faults.{key} requires ha.lease.enabled (leadership must "
            "be lease-arbitrated for a non-fail-stop fault to contest)",
        )
    _require(
        not f["gray_degradation"].get("at_s") or ha["enabled"],
        "faults.gray_degradation requires ha.enabled",
    )

    rec = dict(raw.get("recovery") or {})
    recovery = {
        "enabled": bool(rec.get("enabled", False)),
        "every_s": float(rec.get("every_s", 0.5)),
        "eviction_budget": int(rec.get("eviction_budget", 8)),
        "migration_budget": int(rec.get("migration_budget", 4)),
        "sweep_budget": int(rec.get("sweep_budget", 2)),
        "backfill": bool(rec.get("backfill", True)),
        "lease_grace_s": float(rec.get("lease_grace_s", 0.5)),
        "gang_start_horizon_s": float(
            rec.get("gang_start_horizon_s", 5.0)
        ),
        "hole_ttl_s": float(rec.get("hole_ttl_s", 30.0)),
    }
    _require(
        not recovery["enabled"] or recovery["every_s"] > 0,
        "recovery.every_s must be > 0 when recovery is enabled",
    )
    _require(
        recovery["eviction_budget"] >= 0
        and recovery["migration_budget"] >= 0,
        "recovery budgets must be >= 0",
    )

    return {
        "name": raw.get("name", "unnamed"),
        "description": raw.get("description", ""),
        "fleet": fleet,
        "policy": policy,
        "horizon_s": horizon,
        "workload": w,
        "faults": f,
        "resync_every_s": float(raw.get("resync_every_s", 10.0)),
        "sample_every_s": float(raw.get("sample_every_s", 1.0)),
        "retry_every_s": float(raw.get("retry_every_s", 0.5)),
        "invariant_every_events": int(raw.get("invariant_every_events", 1)),
        "assume_ttl_s": float(raw.get("assume_ttl_s", 0.0)),
        "queue_max": int(raw.get("queue_max", 0)),
        "shards": shards,
        "pipeline": pipeline,
        "batch": batch,
        "ha": ha,
        "recovery": recovery,
        "telemetry": telemetry,
        "export": export,
        "serving": serving,
        "metric_from_allocation": bool(
            raw.get("metric_from_allocation", False)
        ),
        "throughput_report": bool(raw.get("throughput_report", False)),
        "lock_witness": bool(raw.get("lock_witness", False)),
        "trace": bool(raw.get("trace", True)),
    }


def load_scenario(path: str | Path) -> dict:
    with open(path) as fh:
        return normalize_scenario(json.load(fh))
