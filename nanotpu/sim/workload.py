"""Workload generators: jobs for all five BASELINE.json configs.

A *job* is the arrival unit — one pod (fractional, multi-container) or a
burst of pods created together (spread replicas, gang workers, Mixtral
experts). Pod specs mirror tests/test_baseline_configs.py and bench.py so
the sim exercises exactly the demand shapes the repo's headline metric is
defined over:

* ``fractional``       — 1 container, <100% of one chip (config 0)
* ``spread``           — N replicas of one whole chip each (config 1)
* ``multi_container``  — one pod, 2 containers x 1 chip, ICI-adjacent
  placement (config 2)
* ``gang_llama``       — gang of workers, 2 chips each, soft gang
  annotations (config 3; strict gangs need concurrent binds, which a
  deterministic single-threaded driver cannot park — see
  docs/simulation.md)
* ``mixtral``          — gang of 8 experts, 4 chips (one host) each
  (config 4)

Generators draw only from the ``random.Random`` they are handed, so the
arrival stream is a pure function of (scenario, seed).
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field

from nanotpu import types
from nanotpu.k8s.objects import Pod, make_container, make_pod
from nanotpu.sim.scenario import CONFIG_KINDS

#: Fractional chip-percent menu (config 0's gpu-percent=20 plus neighbors).
FRACTIONAL_PERCENTS = (10, 20, 25, 40, 50)


@dataclass
class Job:
    """One arrival unit and its lifecycle bookkeeping."""

    id: int
    config: str
    arrival_t: float
    lifetime_s: float
    gang: str | None  # gang name annotation value, None for non-gang jobs
    pods: list[Pod] = field(default_factory=list)
    #: pod name -> bind virtual time (absent == not bound yet)
    bound_t: dict[str, float] = field(default_factory=dict)
    departed: bool = False
    #: how many flap-kill resubmissions deep this job is (0 == original);
    #: the next resubmission gets incarnation + 1 so repeated kills of the
    #: same job id never reuse pod names or uids
    incarnation: int = 0
    #: True for overload-fault burst arrivals (their draws live on the
    #: sim's rng_overload stream, never rng_workload)
    burst: bool = False
    #: exactly-once latch for the gang-wait observation: recovery paths
    #: (a member migrated and re-learned, a preempted-then-rebound
    #: sibling) can re-trigger the fully_bound transition, and the wait
    #: metric must record each gang's first completion only
    wait_recorded: bool = False
    #: strict-gate memo for the current virtual time (one all-or-nothing
    #: placement check per gang per event, not one per member)
    gate_t: float = -1.0
    gate_ok: bool = False
    #: latch: the job's departure event has been scheduled (exactly once,
    #: whether at admission or — under lifetime_from_bind — at start)
    departure_scheduled: bool = False

    @property
    def size(self) -> int:
        return len(self.pods)

    def fully_bound(self) -> bool:
        return len(self.bound_t) == len(self.pods)


def _pod(name: str, uid: str, containers, annotations=None) -> Pod:
    return make_pod(
        name, uid=uid, containers=containers, annotations=annotations or {}
    )


def build_job(
    job_id: int,
    config: str,
    arrival_t: float,
    lifetime_s: float,
    rng: random.Random,
    uid_of,
    gang_size: int = 8,
    replicas: int = 4,
    incarnation: int = 0,
    priority: int | None = None,
    declared_runtime_s: float | None = None,
    gang_percent: int = 200,
    spread_percent: int = 100,
) -> Job:
    """Materialize a job's pods. ``uid_of(pod_name)`` must return a unique
    uid per call — K8s never reuses uids, and the dealer's released-uid
    tombstones rely on that (a resubmitted gang with recycled uids would
    silently leak chips).

    ``priority`` stamps the capacity-recovery priority class and
    ``declared_runtime_s`` the submitter's runtime ESTIMATE (the
    scenario's configured mean, not the drawn lifetime — backfill's
    lease contract is exercised exactly by pods that outlive their
    declaration); both default to absent so scenarios without a
    ``priorities`` section build byte-identical pods. ``gang_percent``
    shapes gang_llama members' per-member chip demand (default 200 ==
    the historical 2-chip trainer)."""
    if config not in CONFIG_KINDS:
        raise ValueError(f"unknown workload config {config!r}")
    tag = f"{config}-{job_id}" + (f"-r{incarnation}" if incarnation else "")
    extra: dict[str, str] = {}
    if priority is not None:
        extra[types.ANNOTATION_PRIORITY] = str(int(priority))
    if declared_runtime_s is not None:
        extra[types.ANNOTATION_EXPECTED_RUNTIME] = (
            f"{float(declared_runtime_s):g}"
        )
    gang = None
    pods: list[Pod] = []
    if config == "fractional":
        percent = rng.choice(FRACTIONAL_PERCENTS)
        pods.append(_pod(
            f"{tag}-0", uid_of(f"{tag}-0"),
            [make_container("main", {types.RESOURCE_TPU_PERCENT: percent})],
            annotations=dict(extra) if extra else None,
        ))
    elif config == "spread":
        for i in range(replicas):
            pods.append(_pod(
                f"{tag}-{i}", uid_of(f"{tag}-{i}"),
                [make_container(
                    "srv", {types.RESOURCE_TPU_PERCENT: spread_percent}
                )],
                annotations=dict(extra) if extra else None,
            ))
    elif config == "multi_container":
        pods.append(_pod(
            f"{tag}-0", uid_of(f"{tag}-0"),
            [
                make_container("actor", {types.RESOURCE_TPU_PERCENT: 100}),
                make_container("learner", {types.RESOURCE_TPU_PERCENT: 100}),
            ],
            annotations=dict(extra) if extra else None,
        ))
    elif config == "gang_llama":
        gang = f"llama3-{job_id}"
        for i in range(gang_size):
            pods.append(_pod(
                f"{tag}-{i}", uid_of(f"{tag}-{i}"),
                [make_container(
                    "trainer", {types.RESOURCE_TPU_PERCENT: gang_percent}
                )],
                annotations={
                    types.ANNOTATION_GANG_NAME: gang,
                    types.ANNOTATION_GANG_SIZE: str(gang_size),
                    **extra,
                },
            ))
    elif config == "mixtral":
        gang = f"mixtral-{job_id}"
        for i in range(8):
            pods.append(_pod(
                f"{tag}-{i}", uid_of(f"{tag}-{i}"),
                [make_container("expert", {types.RESOURCE_TPU_PERCENT: 400})],
                annotations={
                    types.ANNOTATION_GANG_NAME: gang,
                    types.ANNOTATION_GANG_SIZE: "8",
                    **extra,
                },
            ))
    return Job(
        id=job_id, config=config, arrival_t=arrival_t,
        lifetime_s=lifetime_s, gang=gang, pods=pods,
        incarnation=incarnation,
    )


def draw_lifetime(spec: dict, rng: random.Random) -> float:
    mean = float(spec.get("mean", 15.0))
    if spec.get("dist", "exp") == "fixed":
        return mean
    # floor keeps a job alive long enough to ever be observed by a sample
    return max(0.25, rng.expovariate(1.0 / mean))


def poisson_arrivals(workload: dict, horizon_s: float,
                     rng: random.Random) -> list[tuple[float, str]]:
    """(arrival time, config) stream over [0, horizon). Inter-arrival times
    are exponential; configs drawn from the mix weights."""
    mix = workload["mix"]
    kinds = [k for k in CONFIG_KINDS if mix.get(k, 0) > 0]
    weights = [float(mix[k]) for k in kinds]
    rate = float(workload["rate_per_s"])
    out: list[tuple[float, str]] = []
    t = 0.0
    while True:
        t += rng.expovariate(rate)
        if t >= horizon_s:
            return out
        out.append((t, rng.choices(kinds, weights=weights)[0]))


def trace_arrivals(workload: dict, horizon_s: float) -> list[tuple[float, str, dict]]:
    """Explicit trace entries, clipped to the horizon, sorted by time."""
    out = []
    for a in workload["arrivals"]:
        t = float(a["t"])
        if t < horizon_s and math.isfinite(t):
            out.append((t, a["config"], a))
    return sorted(out, key=lambda e: (e[0], e[1]))
