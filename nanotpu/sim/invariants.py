"""Safety invariants checked after every simulated event.

Three always-on families plus two convergence checks (only valid once the
event stream has settled and a resync has run — mid-run the dealer may
legitimately lag the cluster, e.g. a dropped DELETE not yet repaired):

always:
  * ``chip_oversubscribed``         — a NodeInfo chip's accounting left
    [0, total] (dealer's own view corrupted)
  * ``ground_truth_oversubscribed`` — live bound pods' annotations commit
    more than 100% (or more HBM than exists) on some chip: the scheduler
    double-booked, regardless of what the dealer thinks
  * ``orphaned_reservation``        — a strict-gang chip reservation parked
    with no bind in flight (single-threaded driver == always a leak)
  * ``codec_roundtrip``             — an assumed pod's annotations don't
    survive decode -> encode through :mod:`nanotpu.utils.pod`, or no Plan
    reconstructs from them (an agent restart would lose the placement)

converged:
  * ``tracked_vanished``     — the dealer tracks a pod the cluster no
    longer has
  * ``accounting_mismatch``  — dealer per-chip usage != usage recomputed
    from live pod annotations (the durable-checkpoint contract)
"""

from __future__ import annotations

from nanotpu import types
from nanotpu.allocator.core import ChipSet
from nanotpu.dealer.dealer import plan_from_pod
from nanotpu.utils import pod as podutil


def _violation(kind: str, detail: str) -> dict:
    return {"kind": kind, "detail": detail}


def _ground_truth_usage(client) -> tuple[dict[str, dict[int, int]], list[dict]]:
    """Per-node per-chip percent committed by live, bound, assumed,
    non-completed pods' annotations — the durable K8s view the dealer must
    agree with. Also returns codec violations found on the way."""
    usage: dict[str, dict[int, int]] = {}
    violations: list[dict] = []
    for pod in client.list_pods():
        if not podutil.is_assumed(pod) or not pod.node_name:
            continue
        if podutil.is_completed_pod(pod):
            continue
        chips = podutil.get_assigned_chips(pod)
        if chips is None:
            violations.append(_violation(
                "codec_roundtrip",
                f"pod {pod.key()} is assumed but its chip annotations do "
                "not decode",
            ))
            continue
        for cname, ids in chips.items():
            # ids was decoded from this very annotation, so comparing its
            # decode against ids would be vacuous; the real property is
            # that the stored form IS the canonical encoding of what it
            # decodes to — the dealer only ever writes encode_chips()
            # output, so any drift (unsorted, duplicated, alternate
            # sentinel spelling) means something else touched it and an
            # agent restart would rewrite the annotation it re-learns from
            stored = pod.annotations.get(
                types.ANNOTATION_CONTAINER_FMT.format(name=cname), ""
            )
            if podutil.encode_chips(ids) != stored:
                violations.append(_violation(
                    "codec_roundtrip",
                    f"pod {pod.key()} container {cname}: annotation "
                    f"{stored!r} is not the canonical encoding of its own "
                    f"decode {ids}",
                ))
        plan = plan_from_pod(pod)
        if plan is None:
            violations.append(_violation(
                "codec_roundtrip",
                f"pod {pod.key()}: no Plan reconstructs from annotations "
                "(an agent restart would lose this placement)",
            ))
            continue
        node_usage = usage.setdefault(pod.node_name, {})
        for i, chip_ids in enumerate(plan.assignments):
            if not chip_ids:
                continue
            split = ChipSet._per_chip_split(
                plan.demand.percents[i], len(chip_ids)
            )
            for chip_id, p in zip(chip_ids, split):
                node_usage[chip_id] = node_usage.get(chip_id, 0) + p
    return usage, violations


def ground_truth_occupancy(dealer, client) -> float:
    """Fleet occupancy recomputed purely from live pod annotations over
    the dealer's tracked chip capacity — what a dealer rebuilt from the
    cluster (``_warm_from_cluster``) must report EXACTLY. The in-memory
    dealer may legitimately lag this mid-run (a dropped DELETE event not
    yet repaired by resync), which is why the agent-restart check compares
    against this and not against the pre-restart dealer's view."""
    truth, _ = _ground_truth_usage(client)
    snap = dealer.debug_snapshot()
    used = sum(sum(chips.values()) for chips in truth.values())
    total = sum(
        chip.percent_total
        for info in snap["node_infos"].values()
        for chip in info.chips.chips
    )
    return used / total if total else 0.0


def check_invariants(dealer, client, converged: bool = False) -> list[dict]:
    """All violated invariants (empty list == healthy). ``converged`` adds
    the dealer-vs-cluster equality checks; only set it when no events are
    in flight and a resync has just completed."""
    violations: list[dict] = []
    snap = dealer.debug_snapshot()

    # dealer's own chip accounting stayed in range
    for name in sorted(snap["node_infos"]):
        info = snap["node_infos"][name]
        for i, chip in enumerate(info.chips.chips):
            if not 0 <= chip.percent_free <= chip.percent_total:
                violations.append(_violation(
                    "chip_oversubscribed",
                    f"node {name} chip {i}: {chip.percent_free}% free of "
                    f"{chip.percent_total}% total",
                ))
            if chip.hbm_total_mib and not (
                0 <= chip.hbm_free_mib <= chip.hbm_total_mib
            ):
                violations.append(_violation(
                    "chip_oversubscribed",
                    f"node {name} chip {i}: {chip.hbm_free_mib} MiB HBM "
                    f"free of {chip.hbm_total_mib}",
                ))

    # no reservation outlives its bind
    for uid in snap["reserved_uids"]:
        violations.append(_violation(
            "orphaned_reservation",
            f"pod uid {uid} holds a parked chip reservation with no bind "
            "in flight",
        ))

    # the durable K8s view: annotations decode, and never double-book
    truth, codec_violations = _ground_truth_usage(client)
    violations.extend(codec_violations)
    for node in sorted(truth):
        for chip_id in sorted(truth[node]):
            used = truth[node][chip_id]
            if used > 100:
                violations.append(_violation(
                    "ground_truth_oversubscribed",
                    f"node {node} chip {chip_id}: live pod annotations "
                    f"commit {used}%",
                ))

    if converged:
        live_uids = {p.uid for p in client.list_pods()}
        for uid in snap["tracked_uids"]:
            if uid not in live_uids:
                violations.append(_violation(
                    "tracked_vanished",
                    f"dealer tracks pod uid {uid} which the cluster no "
                    "longer has",
                ))
        for name in sorted(snap["node_infos"]):
            info = snap["node_infos"][name]
            node_truth = truth.get(name, {})
            for i, chip in enumerate(info.chips.chips):
                want = node_truth.get(i, 0)
                if chip.percent_used != want:
                    violations.append(_violation(
                        "accounting_mismatch",
                        f"node {name} chip {i}: dealer accounts "
                        f"{chip.percent_used}% used, annotations say {want}%",
                    ))
        # annotated usage on nodes the dealer no longer knows is also a
        # mismatch: those chips exist nowhere in the dealer's accounting
        for node in sorted(set(truth) - set(snap["node_infos"])):
            violations.append(_violation(
                "accounting_mismatch",
                f"live pods hold chips on node {node} which the dealer "
                "does not track",
            ))
    return violations
