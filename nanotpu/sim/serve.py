"""Virtual serving fleet: the diurnal million-user certification plant.

The sim cannot run the real JAX engine, but it CAN run everything the
scheduler↔serving loop is made of against a *fluid-model* replica fleet:
the REAL Dealer places the replica pods, the REAL batch admitter admits
scale-ups, the REAL recovery plane sweeps drain leases, the REAL
:class:`~nanotpu.serving.autoscale.ReplicaAutoscaler` decides fleet
size, and the REAL :class:`~nanotpu.serving.feedback.ServingTap` feeds
measured tokens/s into the REAL
:class:`~nanotpu.allocator.throughput.ThroughputModel`. Only the decode
arithmetic is virtual (docs/serving-loop.md "The trace model"):

* **Demand** — a diurnal cosine rate curve over ``users`` synthetic
  users (``rate(t) = peak x (trough_frac + (1-trough_frac) x
  (1 - cos(2πt/P))/2)``, starting at the trough). Arrivals aggregate
  into per-tick *cohorts* (one arrival timestamp, one drawn output
  length) on the dedicated ``rng_serve`` stream, so a million-user day
  costs O(ticks), not O(requests), while TTFT percentiles stay exact at
  cohort granularity.
* **Replicas** — one bound replica pod = ``slots`` decode slots at
  capacity ``tok_s_per_chip x chips x table(generation) x
  (1 - derate)``: the throughput table's value is what the scheduler
  KNOWS; ``derate`` (the degraded-host set) is what only measurement
  can discover — exactly the signal the serving tap closes the loop on.
* **Service** — per tick, each replica splits its capacity over its
  in-flight requests (per-request rate capped at ``tok_s_per_request``,
  the single-row decode ceiling); cohorts complete when their drawn
  output length is served. Admission fills free slots from the global
  FIFO queue; TTFT = queue wait + ``prefill_s``.

Determinism: every draw is on ``rng_serve``, every timestamp is virtual
time, all floats round at the edge — the per-tick journal line makes
the serving trajectory part of the run digest.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from nanotpu.allocator.throughput import ThroughputModel
from nanotpu.serving.feedback import ReplicaSample

#: ttft samples retained for the rolling ext.serving.ttft_p99_ms gauge
#: (cohort entries, not requests — the SLO window, not the report stat)
_TTFT_WINDOW = 512


def weighted_percentile(pairs, p: float) -> float | None:
    """Exact weighted nearest-rank percentile over ``(value, weight)``
    pairs — the cohort-granular analogue of
    :func:`nanotpu.metrics.stats.percentile` (same convention: smallest
    value whose cumulative weight reaches ``ceil(p x total)``)."""
    if not pairs:
        return None
    total = sum(w for _, w in pairs)
    if total <= 0:
        return None
    rank = max(1, math.ceil(p * total))
    cum = 0
    for value, weight in sorted(pairs):
        cum += weight
        if cum >= rank:
            return value
    return sorted(pairs)[-1][0]


@dataclass
class _Cohort:
    """Requests that arrived in one tick and share one drawn length."""

    arrival_t: float
    n: int
    tokens_per_req: float
    #: remaining decode tokens per request (in-flight cohorts only)
    remaining: float = 0.0
    #: TTFT already recorded (a requeued in-flight cohort must not
    #: re-record at its second admission)
    ttft_recorded: bool = False


@dataclass
class _Replica:
    name: str
    state: str = "pending"  # pending -> active -> draining
    node: str = ""
    chips: int = 0
    #: the card indices the dealer actually assigned (the pod's
    #: container annotation) — the tap's write targets. Empty falls
    #: back to 0..chips-1 (a sub-host replica sharing a host with a
    #: sibling MUST carry real ids or its shortfall would reprice the
    #: co-resident's cards)
    chip_ids: tuple = ()
    #: uncontended capacity the MODEL expects (table x per-chip rate)
    expected_tok_s: float = 0.0
    #: true capacity (expected x (1 - hidden derate))
    capacity_tok_s: float = 0.0
    slots: int = 0
    active: list = field(default_factory=list)  # in-flight _Cohorts
    #: tokens actually decoded last tick / dt (0.0 when idle)
    measured_tok_s: float = 0.0

    def inflight(self) -> int:
        return sum(c.n for c in self.active)


class ServeSim:
    """See module docstring. Driven by the simulator's ``serving_tick``
    events; exposes the provider/signal surfaces the REAL feedback
    source and autoscaler consume."""

    def __init__(self, spec: dict, client, rng, tap=None):
        self.spec = spec
        self.client = client
        self.rng = rng
        #: the REAL ServingTap (None when scenario feedback is off)
        self.tap = tap
        self.replicas: dict[str, _Replica] = {}
        self.queue: list[_Cohort] = []
        self._carry = 0.0
        #: ground truth the scheduler cannot see: node -> serving derate
        self.derate_by_node = self._degraded_map()
        #: the table the scheduler DOES see (generation factors) — one
        #: fixed default model, the same convention as throughput_report
        self._table = ThroughputModel()
        # trajectory accounting (the report's serving section)
        self.arrived = 0
        self.admitted = 0
        self.completed = 0
        self.tokens_served = 0.0
        self.chip_seconds = 0.0
        self.ttft_samples: list[tuple[float, int]] = []
        self._ttft_window: list[tuple[float, int]] = []
        self.replica_peak = 0
        self.replica_min = -1
        self.ticks = 0
        self._last_tok_s = 0.0

    # -- fleet ground truth ------------------------------------------------
    def _degraded_map(self) -> dict[str, float]:
        """Every ``degraded.every``-th host (sorted names, from index 0)
        serves at ``1 - degraded.derate`` of its modeled rate — the
        hidden hardware/noisy-neighbor loss only measurement finds.
        Computed once at boot; deterministic."""
        deg = self.spec["degraded"]
        every = int(deg.get("every", 0))
        if every <= 0:
            return {}
        names = sorted(n.name for n in self.client.list_nodes())
        return {
            name: float(deg["derate"])
            for i, name in enumerate(names) if i % every == 0
        }

    # -- replica lifecycle (sim hooks) -------------------------------------
    def knows(self, name: str) -> bool:
        return name in self.replicas

    def register_pending(self, name: str) -> None:
        if name not in self.replicas:
            self.replicas[name] = _Replica(name=name)

    def replica_bound(self, name: str, node: str,
                      chips: tuple = ()) -> None:
        rep = self.replicas.get(name)
        if rep is None or rep.state != "pending":
            return
        rep.chip_ids = tuple(chips)
        from nanotpu import types

        chips = max(1, int(self.spec["replica_percent"]) // 100)
        try:
            node_obj = self.client.get_node(node)
            generation = node_obj.labels.get(
                types.LABEL_TPU_GENERATION, ""
            )
        except Exception:
            generation = ""
        if not generation:
            # fleet nodes name themselves "<gen>-host-N"; fall back to
            # the leading token
            generation = node.split("-", 1)[0]
        factor = self._table.base_fraction("*", generation)
        rate = float(self.spec["tok_s_per_chip"]) * chips
        rep.state = "active"
        rep.node = node
        rep.chips = chips
        rep.slots = int(self.spec["slots_per_replica"])
        rep.expected_tok_s = rate * factor
        rep.capacity_tok_s = rep.expected_tok_s * (
            1.0 - self.derate_by_node.get(node, 0.0)
        )

    def drain(self, name: str) -> None:
        rep = self.replicas.get(name)
        if rep is not None and rep.state == "active":
            rep.state = "draining"

    def replica_gone(self, name: str) -> None:
        """Pod deleted (drain complete, drain kill, flap): requeue its
        in-flight cohorts at their ORIGINAL arrival time (the client
        retries; TTFT was recorded at first admission and is not
        re-recorded)."""
        rep = self.replicas.pop(name, None)
        if rep is None:
            return
        for cohort in rep.active:
            self.queue.append(_Cohort(
                arrival_t=cohort.arrival_t, n=cohort.n,
                tokens_per_req=cohort.remaining,
                ttft_recorded=True,
            ))

    # -- demand ------------------------------------------------------------
    def rate(self, now: float) -> float:
        """Diurnal arrival rate (requests/s) at virtual time ``now``."""
        peak = (
            float(self.spec["users"])
            * float(self.spec["requests_per_user_h"]) / 3600.0
        )
        d = self.spec["diurnal"]
        period = float(d["period_s"])
        trough = float(d["trough_frac"])
        wave = 0.5 * (1.0 - math.cos(2.0 * math.pi * now / period))
        return peak * (trough + (1.0 - trough) * wave)

    def _arrivals(self, now: float, dt: float) -> int:
        lam = self.rate(now) * dt
        # +-10% multiplicative noise from the dedicated stream: enough
        # jitter to be a trace, still byte-stable under the seed
        noisy = lam * (0.9 + 0.2 * self.rng.random()) + self._carry
        count = int(noisy)
        self._carry = noisy - count
        if count <= 0:
            return 0
        tokens = float(self.spec["tokens_out_mean"]) * (
            0.5 + self.rng.random()
        )
        self.queue.append(_Cohort(
            arrival_t=now, n=count, tokens_per_req=tokens,
        ))
        self.arrived += count
        return count

    # -- one tick ----------------------------------------------------------
    def tick(self, now: float, dt: float) -> dict:
        """Advance the fleet by ``dt``: arrivals -> decode -> completions
        -> admissions -> accounting -> feedback. Returns the journal
        summary (rounded — it feeds the run digest)."""
        self.ticks += 1
        arrivals = self._arrivals(now, dt)
        cap = float(self.spec["tok_s_per_request"])
        prefill = float(self.spec["prefill_s"])
        served_tokens = 0.0
        completed = 0
        samples = []
        chips_now = 0
        for name in sorted(self.replicas):
            rep = self.replicas[name]
            if rep.state == "pending":
                continue
            chips_now += rep.chips
            inflight = rep.inflight()
            if inflight > 0:
                per_req = min(cap, rep.capacity_tok_s / inflight) * dt
                still: list[_Cohort] = []
                for cohort in rep.active:
                    chunk = min(cohort.remaining, per_req)
                    served_tokens += chunk * cohort.n
                    cohort.remaining -= chunk
                    if cohort.remaining <= 1e-9:
                        completed += cohort.n
                    else:
                        still.append(cohort)
                rep.active = still
                # the engine's measured decode rate: extrapolated full
                # rate while decoding (what the bandit EWMA converges to)
                rep.measured_tok_s = round(rep.capacity_tok_s, 4)
                if self.tap is not None and rep.node:
                    samples.append(ReplicaSample(
                        node=rep.node,
                        chips=rep.chip_ids or tuple(range(rep.chips)),
                        measured_tok_s=rep.capacity_tok_s,
                        expected_tok_s=rep.expected_tok_s,
                    ))
            else:
                rep.measured_tok_s = 0.0
        # admissions: fill free slots from the global FIFO queue
        # (draining replicas take nothing new — the drain contract)
        for name in sorted(self.replicas):
            rep = self.replicas[name]
            if rep.state != "active":
                continue
            free = rep.slots - rep.inflight()
            while free > 0 and self.queue:
                head = self.queue[0]
                take = min(free, head.n)
                if not head.ttft_recorded:
                    # first admission: TTFT = queue wait + prefill
                    ttft = round(now - head.arrival_t + prefill, 6)
                    self.ttft_samples.append((ttft, take))
                    self._ttft_window.append((ttft, take))
                    if len(self._ttft_window) > _TTFT_WINDOW:
                        del self._ttft_window[
                            : len(self._ttft_window) - _TTFT_WINDOW
                        ]
                rep.active.append(_Cohort(
                    arrival_t=head.arrival_t, n=take,
                    tokens_per_req=head.tokens_per_req,
                    remaining=head.tokens_per_req,
                ))
                self.admitted += take
                free -= take
                if take == head.n:
                    self.queue.pop(0)
                else:
                    head.n -= take
        self.completed += completed
        self.tokens_served += served_tokens
        self.chip_seconds += chips_now * dt
        self._last_tok_s = round(served_tokens / dt, 4) if dt else 0.0
        live = sum(
            1 for r in self.replicas.values() if r.state != "pending"
        )
        self.replica_peak = max(self.replica_peak, live)
        if self.replica_min < 0 or live < self.replica_min:
            self.replica_min = live
        if self.tap is not None and samples:
            self.tap.ingest(samples, now=now)
        queued = sum(c.n for c in self.queue)
        return {
            "arrivals": arrivals,
            "queued": queued,
            "active": sum(
                r.inflight() for r in self.replicas.values()
            ),
            "replicas": live,
            "tokens": round(served_tokens, 2),
            "completed": completed,
        }

    # -- provider / signal surfaces ----------------------------------------
    def metrics(self) -> dict:
        """The serving-provider contract (same key set as
        ``Engine.metrics()``, pinned by tests) — what the REAL
        ``ServingMetricsSource`` samples into ``ext.serving.*``."""
        active = 0
        slots = 0
        chips = 0
        for rep in self.replicas.values():
            if rep.state == "pending":
                continue
            active += rep.inflight()
            slots += rep.slots
            chips += rep.chips
        p99 = weighted_percentile(self._ttft_window, 0.99)
        return {
            "tok_s": self._last_tok_s,
            "queue_depth": float(sum(c.n for c in self.queue)),
            "active": float(active),
            "slots": float(slots),
            "kv_occupancy": round(active / slots, 6) if slots else 0.0,
            "chips": float(chips),
            "ttft_p99_ms": (
                round(p99 * 1e3, 2) if p99 is not None else 0.0
            ),
        }

    def signal(self):
        """The autoscaler's demand snapshot."""
        from nanotpu.serving.autoscale import ServingSignal

        return ServingSignal(
            queued=sum(c.n for c in self.queue),
            replicas={
                name: {
                    "active": rep.inflight(),
                    "tok_s": rep.measured_tok_s,
                }
                for name, rep in sorted(self.replicas.items())
                if rep.state != "pending"
            },
        )

    def bound_replicas(self) -> int:
        return sum(
            1 for r in self.replicas.values() if r.state != "pending"
        )

    # -- final report section ----------------------------------------------
    def summary(self) -> dict:
        ttft = self.ttft_samples
        tok_per_chip = (
            self.tokens_served / self.chip_seconds
            if self.chip_seconds else 0.0
        )

        def pct(p: float):
            v = weighted_percentile(ttft, p)
            return round(v * 1e3, 2) if v is not None else None

        return {
            "requests": {
                "arrived": self.arrived,
                "admitted": self.admitted,
                "completed": self.completed,
                "queued_final": sum(c.n for c in self.queue),
                "inflight_final": sum(
                    r.inflight() for r in self.replicas.values()
                ),
            },
            "tokens_served": round(self.tokens_served, 2),
            "chip_seconds": round(self.chip_seconds, 2),
            "tok_s_per_chip": round(tok_per_chip, 4),
            "ttft_ms": {
                "p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99),
            },
            "replicas": {
                "final": self.bound_replicas(),
                "peak": self.replica_peak,
                "min": max(self.replica_min, 0),
            },
            "feedback": {
                "samples": (
                    self.tap.samples_ingested if self.tap else 0
                ),
                "cards": self.tap.cards_observed if self.tap else 0,
            },
            "ticks": self.ticks,
        }
