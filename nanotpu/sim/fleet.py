"""Synthetic fleet builders: TPU node pools for the simulator and mocks.

One source of truth for "what does a v5p pool look like as K8s nodes",
shared by the simulator, ``nanotpu.cmd.main --mock`` and bench.py (which
previously each hand-rolled node grids). A pool is hosts of one TPU
generation partitioned into ICI slices; each slice lays its hosts on a
square-ish grid (the ``tpu.io/slice-coords`` convention the gang scorer
consumes, see :mod:`nanotpu.topology`).

Sizes are expressed in HOSTS; chips per host default to the generation's
host topology (v4/v5p: 4 chips as 2x2x1, v5e/v6e: 8 as 2x4x1). A v5p-512
pool is therefore ``hosts=128`` (512 chips), e.g. 8 slices of 16 hosts
(eight v5p-64 ICI domains).
"""

from __future__ import annotations

from nanotpu import types
from nanotpu.k8s.client import FakeClientset
from nanotpu.k8s.objects import Node, make_node
from nanotpu.topology import DEFAULT_HOST_TOPOLOGY, HOST_CHIPS


def pool_nodes(
    hosts: int,
    generation: str = "v5p",
    chips_per_host: int | None = None,
    slice_hosts: int | None = None,
    prefix: str | None = None,
    slice_prefix: str = "slice",
) -> list[Node]:
    """Nodes of one pool: ``hosts`` hosts split into slices of
    ``slice_hosts`` (default: one slice holds the whole pool). Host coords
    inside a slice go on a ``side x ceil(n/side)`` grid with
    ``side = int(sqrt(slice_hosts))`` — the same layout
    ``cmd.main.make_mock_cluster`` always used, kept so existing mock
    clusters and benches are bit-identical."""
    if hosts < 1:
        raise ValueError(f"pool needs at least 1 host, got {hosts}")
    chips = chips_per_host or HOST_CHIPS.get(generation, 4)
    topo = DEFAULT_HOST_TOPOLOGY.get(generation, "2x2x1")
    per_slice = slice_hosts or hosts
    if per_slice < 1:
        raise ValueError(f"slice_hosts must be >= 1, got {slice_hosts}")
    name_prefix = prefix or f"{generation}-host"
    side = max(1, int(per_slice ** 0.5))
    out: list[Node] = []
    for i in range(hosts):
        s, j = divmod(i, per_slice)
        hx, hy = j % side, j // side
        out.append(
            make_node(
                f"{name_prefix}-{i}",
                {types.RESOURCE_TPU_PERCENT: chips * types.PERCENT_PER_CHIP},
                labels={
                    types.LABEL_TPU_GENERATION: generation,
                    types.LABEL_TPU_TOPOLOGY: topo,
                    types.LABEL_TPU_SLICE: f"{slice_prefix}-{s}",
                    types.LABEL_TPU_SLICE_COORDS: f"{hx},{hy},0",
                    types.LABEL_TPU_ENABLE: types.LABEL_TPU_ENABLE_VALUE,
                },
            )
        )
    return out


def make_fleet(spec: dict, client: FakeClientset | None = None) -> FakeClientset:
    """Build a FakeClientset from a fleet spec (scenario ``fleet`` section)::

        {"pools": [
            {"generation": "v5p", "hosts": 128, "slice_hosts": 16},
            {"generation": "v4", "hosts": 2, "prefix": "v4-host"},
        ]}

    Pools are created in listed order; node names must not collide across
    pools (give each pool a distinct ``prefix``).

    ``count: N`` replicates a pool entry N times with distinct name and
    slice prefixes (``<prefix>-p<i>`` / ``<slice_prefix>-p<i>``) — the
    shorthand that makes 4096-host multi-pool fleets one line::

        {"pools": [{"generation": "v5p", "hosts": 1024, "slice_hosts": 64,
                    "prefix": "v5p-pool", "count": 4}]}

    Each replica is its own slice family, so a sharded dealer
    (``shards: "auto"``, docs/sharding.md) gives each replica its own
    snapshot shard. ``count`` 1 (the default) leaves names byte-identical
    to what this factory always produced.
    """
    client = client or FakeClientset()
    pools = spec.get("pools")
    if not pools:
        raise ValueError("fleet spec needs a non-empty 'pools' list")
    seen: set[str] = set()
    for p, pool in enumerate(pools):
        count = int(pool.get("count", 1))
        if count < 1:
            raise ValueError(f"pool {p}: count must be >= 1, got {count}")
        base_prefix = pool.get("prefix")
        base_slice_prefix = pool.get(
            "slice_prefix", f"slice{p}" if p else "slice"
        )
        for rep in range(count):
            prefix = base_prefix
            slice_prefix = base_slice_prefix
            if count > 1:
                prefix = (
                    f"{base_prefix or pool.get('generation', 'v5p') + '-host'}"
                    f"-p{rep}"
                )
                slice_prefix = f"{base_slice_prefix}-p{rep}"
            nodes = pool_nodes(
                hosts=int(pool.get("hosts", 1)),
                generation=pool.get("generation", "v5p"),
                chips_per_host=pool.get("chips_per_host"),
                slice_hosts=pool.get("slice_hosts"),
                prefix=prefix,
                slice_prefix=slice_prefix,
            )
            for node in nodes:
                if node.name in seen:
                    raise ValueError(
                        f"fleet node name collision: {node.name!r} (give "
                        f"pool {p} a distinct 'prefix')"
                    )
                seen.add(node.name)
                client.create_node(node)
    return client


def fleet_summary(client: FakeClientset) -> dict:
    """Deterministic fleet digest for the report header."""
    nodes = client.list_nodes()
    chips = sum(
        n.capacity(types.RESOURCE_TPU_PERCENT) // types.PERCENT_PER_CHIP
        for n in nodes
    )
    slices = sorted(
        {n.labels.get(types.LABEL_TPU_SLICE, "") for n in nodes} - {""}
    )
    return {"nodes": len(nodes), "chips": chips, "slices": len(slices)}
