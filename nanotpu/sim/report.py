"""Structured sim report: metric aggregation + the determinism contract.

The report splits in two:

* the **deterministic section** — everything derived from virtual time,
  chip counts, and seeded draws. Byte-identical across runs of the same
  (scenario, seed); ``render()`` serializes it with sorted keys, and
  ``digest`` is a sha256 over the per-event journal so even two reports
  that happen to aggregate equal can be told apart from two identical
  RUNS.
* the **timing section** — wall-clock Filter/Prioritize/Bind latency
  percentiles through the real verb objects. Real time is not
  reproducible, so this section is opt-in (``--timing`` / include_timing)
  and never feeds the digest.

Fragmentation is the two-level fleet ICI metric from
:mod:`nanotpu.dealer.frag` (shared with the timeline's production tap —
see that module's docstring for the math); ``fragmentation_of`` is
re-exported here for the sim's callers.
"""

from __future__ import annotations

import hashlib
import json

from nanotpu.dealer.frag import fragmentation_of  # noqa: F401  (re-export)
from nanotpu.metrics.stats import summarize


class ReportBuilder:
    """Accumulates sim observations; ``build()`` emits the final dict."""

    def __init__(self, scenario: dict, seed: int):
        self.scenario = scenario
        self.seed = seed
        self._journal = hashlib.sha256()
        self._journal_lines = 0
        self.events_processed = 0
        self.pods = {
            "arrived": 0, "bound": 0, "departed": 0, "evicted": 0,
            "bind_errors": 0, "schedule_retries": 0, "pending_final": 0,
        }
        self.per_config: dict[str, dict[str, int]] = {}
        self.gang_waits_s: list[float] = []
        self.occupancy_samples: list[float] = []
        self.fragmentation_samples: list[float] = []
        self.verb_counts = {"filter": 0, "prioritize": 0, "bind": 0}
        self.verb_wall_s: dict[str, list[float]] = {
            "filter": [], "prioritize": [], "bind": [],
        }
        self.invariant_checks = 0
        self.violations: list[dict] = []
        self.fault_counts: dict[str, int] = {}
        #: deterministic slice of the resilience-counter snapshot (core.py
        #: filters out the background-thread Event counters): attribution
        #: for every shed/coalesced/dropped/expired/fast-failed action
        self.resilience: dict = {}
        #: trace/decision-audit summary (Observability.digest_summary):
        #: counts plus a sha256 over every retained trace and decision
        #: record — virtual-clock timestamps make it byte-reproducible
        self.traces: dict = {}
        #: modeled aggregate throughput vs oracle at settle (the
        #: het-throughput certification metric, docs/scoring.md);
        #: empty == scenario did not enable throughput_report
        self.throughput: dict = {}
        #: capacity-recovery counters + final hole/lease state
        #: (docs/defrag.md); empty == recovery disabled, keeping
        #: existing scenario reports (and digests) byte-identical
        self.recovery: dict = {}
        #: telemetry-timeline summary (tick count + ring digest, SLO
        #: breach counts, flight-bundle count + newest bundle digest —
        #: docs/observability.md); empty == telemetry disabled, same
        #: opt-in digest rule as throughput/recovery
        self.timeline: dict = {}
        #: scheduler<->serving loop summary (requests, tokens/s-per-chip,
        #: TTFT percentiles, replica trajectory, feedback sample counts,
        #: autoscale action counters — docs/serving-loop.md); empty ==
        #: serving disabled, same opt-in digest rule as the sections above
        self.serving: dict = {}
        #: HA pair summary (crashes survived, promotions, deltas
        #: applied, reconcile-window sizes, standby-vs-truth drift —
        #: docs/ha.md); empty == ha disabled, same opt-in digest rule
        self.ha: dict = {}
        #: shadow-mode A/B summary (candidate program, cycles, rows,
        #: divergences, max_abs_delta, records digest —
        #: docs/policy-programs.md); empty == no shadow candidate, same
        #: opt-in digest rule as the sections above
        self.shadow: dict = {}
        #: durable decision-export summary (records, bytes, rotations,
        #: stream sha256 — docs/observability.md "Decision export
        #: format"); empty == export disabled, same opt-in digest rule.
        #: The stream digest inside joins --check-determinism: two runs
        #: of the same (scenario, seed) must frame identical bytes.
        self.export: dict = {}
        self.restart_occupancy_drift = 0.0
        self.final_occupancy = 0.0
        self.final_fragmentation = 0.0

    # -- journal: the determinism witness -----------------------------------
    def journal(self, t: float, what: str) -> None:
        """One line per consequential event outcome. The digest of this
        stream IS the determinism contract: any divergence in event order,
        placement decision, or fault draw changes it."""
        self._journal.update(f"{t:.6f} {what}\n".encode())
        self._journal_lines += 1

    def config_count(self, config: str, key: str, n: int = 1) -> None:
        entry = self.per_config.setdefault(
            config, {"arrived": 0, "bound": 0, "departed": 0}
        )
        entry[key] = entry.get(key, 0) + n

    def sample(self, occupancy: float, fragmentation: float) -> None:
        self.occupancy_samples.append(occupancy)
        self.fragmentation_samples.append(fragmentation)

    def observe_verb(self, verb: str, wall_s: float) -> None:
        self.verb_counts[verb] += 1
        self.verb_wall_s[verb].append(wall_s)

    # -- final assembly -----------------------------------------------------
    def build(self, include_timing: bool = False, wall_s: float = 0.0,
              fleet: dict | None = None) -> dict:
        occ = self.occupancy_samples
        frag = self.fragmentation_samples
        kinds: dict[str, int] = {}
        for v in self.violations:
            kinds[v["kind"]] = kinds.get(v["kind"], 0) + 1
        report = {
            "scenario": self.scenario["name"],
            "policy": self.scenario["policy"],
            "seed": self.seed,
            "horizon_s": self.scenario["horizon_s"],
            "fleet": fleet or {},
            "events_processed": self.events_processed,
            "pods": dict(self.pods),
            "configs": {k: self.per_config[k] for k in sorted(self.per_config)},
            "gangs": {
                "jobs": len(self.gang_waits_s),
                "wait_s": summarize(self.gang_waits_s),
            },
            "occupancy_pct": {
                "mean": round(100 * sum(occ) / len(occ), 2) if occ else 0.0,
                "peak": round(100 * max(occ), 2) if occ else 0.0,
                "final": round(100 * self.final_occupancy, 2),
            },
            "fragmentation": {
                "mean": round(sum(frag) / len(frag), 4) if frag else 0.0,
                "peak": round(max(frag), 4) if frag else 0.0,
                "final": self.final_fragmentation,
            },
            "verbs": dict(self.verb_counts),
            "faults": dict(sorted(self.fault_counts.items())),
            "resilience": {k: self.resilience[k]
                           for k in sorted(self.resilience)},
            "traces": {k: self.traces[k] for k in sorted(self.traces)},
            "restart_occupancy_drift_pct": round(
                100 * self.restart_occupancy_drift, 6
            ),
            "invariants": {
                "checks": self.invariant_checks,
                "violations": len(self.violations),
                "violation_kinds": dict(sorted(kinds.items())),
                # first few, so a red run names its failure without logs
                "first": self.violations[:5],
            },
            "digest": "sha256:" + self._journal.hexdigest(),
            "journal_lines": self._journal_lines,
        }
        if self.throughput:
            # present only when the scenario opts in: existing scenarios'
            # reports (and digests) stay byte-identical
            report["throughput"] = {
                k: self.throughput[k] for k in sorted(self.throughput)
            }
        if self.recovery:
            # same opt-in rule as the throughput section
            rec: dict = {}
            for k in sorted(self.recovery):
                v = self.recovery[k]
                rec[k] = (
                    {kk: v[kk] for kk in sorted(v)}
                    if isinstance(v, dict) else v
                )
            report["recovery"] = rec
        if self.timeline:
            # same opt-in rule again (docs/observability.md)
            tl: dict = {}
            for k in sorted(self.timeline):
                v = self.timeline[k]
                tl[k] = (
                    {kk: v[kk] for kk in sorted(v)}
                    if isinstance(v, dict) else v
                )
            report["timeline"] = tl
        if self.serving:
            # same opt-in rule (docs/serving-loop.md); render() sorts
            # keys globally, so nested sections need no manual ordering
            report["serving"] = self.serving
        if self.ha:
            # same opt-in rule (docs/ha.md)
            report["ha"] = {k: self.ha[k] for k in sorted(self.ha)}
        if self.shadow:
            # same opt-in rule (docs/policy-programs.md)
            report["shadow"] = {
                k: self.shadow[k] for k in sorted(self.shadow)
            }
        if self.export:
            # same opt-in rule (docs/observability.md)
            report["export"] = {
                k: self.export[k] for k in sorted(self.export)
            }
        if include_timing:
            report["timing"] = {
                "note": "wall-clock; excluded from the determinism contract",
                "wall_s": round(wall_s, 3),
                "latency_ms": {
                    verb: summarize(samples, scale=1e3)
                    for verb, samples in self.verb_wall_s.items()
                },
            }
        return report


def render(report: dict) -> str:
    """Canonical serialization: sorted keys, no float repr surprises
    (every float in the report is pre-rounded)."""
    return json.dumps(report, sort_keys=True, separators=(",", ":"))


def strip_timing(report: dict) -> dict:
    out = dict(report)
    out.pop("timing", None)
    return out
