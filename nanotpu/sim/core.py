"""The discrete-event simulator: virtual clock over the REAL extender stack.

One thread, one seeded RNG tree, one event heap. Every component under test
is the production object — :class:`~nanotpu.dealer.Dealer`,
:class:`~nanotpu.scheduler.verbs.Predicate`/``Prioritize``/``Bind``, and
:class:`~nanotpu.controller.controller.Controller` (driven through its
deterministic stepping surface ``handle_pod_event`` / ``handle_node_event``
/ ``drain_sync`` instead of its threads). The simulator owns only what a
real cluster would: the virtual clock, pod arrivals/departures, the
informer tap (where drop/duplicate faults live), and the fault schedule.

Scheduling cycles replicate kube-scheduler's loop: Filter over every live
node, Prioritize, pick the best score (ties broken by node name — the one
place kube-scheduler randomizes and a deterministic sim must not), then
Bind. Infeasible or failed pods go to a pending queue retried every
``retry_every_s``.

Determinism contract: two runs of (scenario, seed) produce byte-identical
deterministic reports — see docs/simulation.md. Wall-clock verb latencies
are collected on the side and surface only in the opt-in timing section.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import random
import time

from nanotpu import types
from nanotpu.allocator.rater import make_rater
from nanotpu.analysis import witness as lock_witness
from nanotpu.controller.controller import Controller
from nanotpu.dealer import Dealer
from nanotpu.k8s.objects import Node, Pod, plain_copy
from nanotpu.k8s.resilience import ResilientClientset
from nanotpu.metrics.resilience import ResilienceCounters
from nanotpu.obs import Observability, set_current
from nanotpu.scheduler.verbs import Bind, Predicate, Prioritize
from nanotpu.sim.faults import BrownoutClient, FaultPlan
from nanotpu.sim.fleet import fleet_summary, make_fleet
from nanotpu.sim.invariants import check_invariants, ground_truth_occupancy
from nanotpu.sim.report import ReportBuilder, fragmentation_of
from nanotpu.sim.scenario import normalize_scenario
from nanotpu.sim.workload import (
    Job,
    build_job,
    draw_lifetime,
    poisson_arrivals,
    trace_arrivals,
)

log = logging.getLogger("nanotpu.sim")

#: delay before a gang killed by a node flap is resubmitted (a real job
#: controller backs off before recreating workers)
GANG_RESUBMIT_DELAY_S = 1.0

#: bind retries within one arrival before the pod parks in pending
BIND_RETRIES_PER_CYCLE = 2


class _StandbyStack:
    """One replica's process-equivalent inside the sim: its own dealer,
    controller, informer watches, and coordinator. In crash mode it is
    only ever the warm standby; in lease mode (docs/ha.md "Split brain
    and fencing") the SAME shape also carries the per-process fault tap,
    leader lease, epoch fence, and degraded monitor, because leadership
    moves between two live stacks and the cut/clock/fence state must
    follow the PROCESS, not the role."""

    __slots__ = ("dealer", "controller", "coordinator", "pod_watch",
                 "node_watch", "lease", "fence", "tap", "monitor")

    def __init__(self, dealer, controller, coordinator, pod_watch,
                 node_watch, lease=None, fence=None, tap=None,
                 monitor=None):
        self.dealer = dealer
        self.controller = controller
        self.coordinator = coordinator
        self.pod_watch = pod_watch
        self.node_watch = node_watch
        self.lease = lease
        self.fence = fence
        self.tap = tap
        self.monitor = monitor


class Simulator:
    def __init__(self, scenario: dict, seed: int = 0):
        self.scenario = normalize_scenario(scenario)
        self.seed = seed
        # must precede the stack build (dealer, fleet, queue locks): the
        # witness factories decide plain-vs-instrumented at creation
        # time. Locks built at IMPORT time (nodeinfo._state_gen_lock,
        # native._lock) are already constructed by now — full coverage
        # needs NANOTPU_LOCK_WITNESS=1 in the environment, which `make
        # chaos-soak` and tests/conftest.py both set; this enable() is
        # the in-process arm for ad-hoc Simulator use. Sticky by design:
        # a lock order is a process-wide discipline.
        if self.scenario["lock_witness"] and not lock_witness.opted_out():
            lock_witness.enable()
        # independent seeded streams so e.g. adding a fault cannot shift
        # the arrival sequence out from under a regression bisect:
        # rng_workload is consumed ONLY by the fixed arrival sequence;
        # draws whose count depends on fault timing (departure-completion
        # coins, gang resubmissions) live on rng_lifecycle so toggling a
        # fault never changes WHICH jobs arrive or their shapes
        base = seed * 1_000_003
        self.rng_workload = random.Random(base + 1)
        self.rng_fault = random.Random(base + 2)
        self.rng_metric = random.Random(base + 3)
        self.rng_lifecycle = random.Random(base + 4)
        # overload-burst arrivals live on their own stream (same isolation
        # rule as rng_lifecycle: toggling the fault must not shift the base
        # arrival sequence); rng_retry feeds only the resilient client's
        # backoff jitter, whose sleeps are no-ops under virtual time
        self.rng_overload = random.Random(base + 5)
        self.rng_retry = random.Random(base + 6)
        # the capacity-recovery plane's reserved stream: the plane itself
        # draws nothing today (victim/target choice is a total order —
        # nanotpu.recovery.plane), but the stream is allocated so any
        # future recovery draw lives here and toggling `recovery.enabled`
        # can never shift a sibling stream (same isolation rule as
        # rng_overload; pinned by the defrag toggle test in test_sim.py)
        self.rng_defrag = random.Random(base + 7)
        # the serving plane's dedicated stream (docs/serving-loop.md):
        # diurnal arrival-count jitter + per-cohort output-length draws
        # live here exclusively, so toggling `serving.enabled` (or the
        # autoscaler/feedback inside it) can never shift the base
        # workload's arrival or lifetime draws (same isolation rule)
        self.rng_serve = random.Random(base + 8)
        # the HA plane's reserved stream (docs/ha.md): the pair itself
        # draws nothing today (crash times are scheduled, the delta
        # stream and promotion are total orders), but the stream is
        # allocated so any future HA draw lives here and toggling
        # `ha.enabled` can never shift a sibling stream (same isolation
        # rule as rng_defrag; pinned by the crash toggle test)
        self.rng_crash = random.Random(base + 9)
        # the non-fail-stop fault suite (docs/ha.md "Split brain and
        # fencing"), one reserved stream each — the same isolation rule:
        # toggling any of partition/skew/thrash/gray can never shift a
        # sibling stream (pinned by the toggle tests). partition and
        # skew schedule everything up front and draw nothing today; the
        # streams are allocated so any future draw has a home.
        self.rng_partition = random.Random(base + 10)
        self.rng_skew = random.Random(base + 11)
        # per-call coins: lease-API flaps and gray write timeouts
        self.rng_thrash = random.Random(base + 12)
        self.rng_gray = random.Random(base + 13)
        # the lease dance's jittered steal backoff draws on the HA
        # plane's reserved stream (exactly what it was allocated for)
        self.rng_lease = self.rng_crash

        self.client = make_fleet(self.scenario["fleet"])
        self.faults = FaultPlan(
            self.scenario["faults"], self.rng_fault,
            rng_thrash=self.rng_thrash, rng_gray=self.rng_gray,
        )
        self._bind_hook = self.faults.make_bind_hook()
        #: the degradation ledger, shared across agent restarts (it is the
        #: run's measurement, not the dealer's state) and snapshotted into
        #: the deterministic report
        self.resilience = ResilienceCounters()
        self.now = 0.0  # before _build_stack: the wrapper's clock reads it
        #: sampling=all tracing + decision audit on the VIRTUAL clock —
        #: every event timestamp is `self.now`, so the trace set (and the
        #: report's `traces` digest) is byte-reproducible. Like the
        #: resilience ledger it survives agent restarts: it is the run's
        #: measurement, not the dealer's state.
        self.obs = Observability(
            sample=1 if self.scenario["trace"] else 0,
            trace_capacity=131072,
            decision_capacity=65536,
            clock=lambda: self.now,
        )
        self._build_stack()
        # the capacity-recovery plane (docs/defrag.md): priority
        # preemption + defragmentation + gang backfill, stepped through
        # scheduled "recovery_cycle" events on the virtual clock. Like
        # the controller it survives agent restarts (holes/leases are
        # control-plane intent, not dealer state) — _build_stack rewires
        # its dealer. None when the scenario leaves it disabled, and
        # every hook below gates on that, so default-path digests are
        # byte-identical.
        rec = self.scenario["recovery"]
        if rec["enabled"]:
            from nanotpu.recovery import RecoveryConfig, RecoveryPlane

            self.plane = RecoveryPlane(
                self.dealer,
                controller=self.controller,
                obs=self.obs,
                config=RecoveryConfig(
                    eviction_budget=rec["eviction_budget"],
                    migration_budget=rec["migration_budget"],
                    sweep_budget=rec["sweep_budget"],
                    backfill=rec["backfill"],
                    lease_grace_s=rec["lease_grace_s"],
                    gang_start_horizon_s=rec["gang_start_horizon_s"],
                    hole_ttl_s=rec["hole_ttl_s"],
                ),
                clock=lambda: self.now,
            )
            self.dealer.recovery = self.plane
        else:
            self.plane = None
        # telemetry plane (docs/observability.md): timeline ticks as
        # virtual-time events, SLO watchdog over the ring, flight
        # recorder dumping on breach / dealer death. Deterministic mode
        # filters wall-clock-bred series, so the report's `timeline`
        # section (tick digest + bundle digest) joins the determinism
        # contract. Like the obs bundle it survives agent restarts (the
        # run's measurement, not the dealer's state) — _build_stack
        # rewires the dealer refs.
        tel = self.scenario["telemetry"]
        if tel["enabled"]:
            from nanotpu.metrics.slo import SLOWatchdog
            from nanotpu.obs.flight import FlightRecorder
            from nanotpu.obs.timeline import Timeline

            self.timeline = Timeline(
                dealer=self.dealer,
                resilience=self.resilience,
                recovery=self.plane,
                model=getattr(self.dealer.rater, "model", None),
                capacity=tel["capacity"],
                clock=lambda: self.now, deterministic=True,
            )
            # HA scenarios: every tick gains the `ha` section (role,
            # stream seq/lag, promotions) — absent otherwise, so
            # existing tick digests stay byte-identical (docs/ha.md)
            self.timeline.ha = self.ha_active
            self.watchdog = SLOWatchdog(
                self.timeline, obs=self.obs, clock=lambda: self.now
            )
            self.watchdog.configure(tel["slo"])
            self.flight = FlightRecorder(
                path=tel["flight_path"], timeline=self.timeline,
                obs=self.obs, dealer=self.dealer,
                resilience=self.resilience,
                config={"scenario": self.scenario["name"], "seed": seed},
                ticks=tel["flight_ticks"],
                clock=lambda: self.now, deterministic=True,
            )
        else:
            self.timeline = self.watchdog = self.flight = None
        # durable decision export (docs/observability.md "Decision
        # export format"): sampled finalized cycles + timeline ticks
        # framed as crc-checked canonical JSONL, on the VIRTUAL clock —
        # the exporter is clock-free (records carry their own t), so the
        # stream sha256 joins the determinism contract. Sink-less by
        # default (path "") so the digest pin needs no filesystem. None
        # when disabled; the ledger/timeline attach points are plain
        # attribute stores, so default-path digests are byte-identical.
        exp = self.scenario["export"]
        if exp["enabled"]:
            from nanotpu.obs.export import DecisionExporter

            self.exporter = DecisionExporter(
                path=exp["path"], sample=exp["sample"],
                max_bytes=exp["max_bytes"],
            )
            self.obs.ledger.exporter = self.exporter
            if self.timeline is not None:
                self.timeline.exporter = self.exporter
        else:
            self.exporter = None
        # scheduler<->serving loop (docs/serving-loop.md): a virtual
        # replica fleet served on the diurnal trace, with the REAL
        # autoscaler deciding fleet size and the REAL serving tap
        # feeding measured tok/s into the throughput model. Like the
        # recovery plane it survives agent restarts (replicas/queue are
        # workload state, not dealer state) — _build_stack rewires the
        # tap's dealer. None when disabled; every hook gates on that,
        # so default-path digests are byte-identical.
        srv = self.scenario["serving"]
        if srv["enabled"]:
            from nanotpu.serving.feedback import (
                ServingMetricsSource,
                ServingTap,
            )
            from nanotpu.sim.serve import ServeSim

            tap = ServingTap(self.dealer) if srv["feedback"] else None
            self.serve = ServeSim(
                srv, self.client, self.rng_serve, tap=tap
            )
            if srv["autoscale"]["enabled"]:
                from nanotpu.serving.autoscale import ReplicaAutoscaler

                self.autoscaler = ReplicaAutoscaler(
                    self.client, self._autoscale_config(),
                    plane=self.plane, clock=lambda: self.now,
                    uid_of=self._uid,
                )
            else:
                self.autoscaler = None
            self.serve_source = ServingMetricsSource(
                self.serve,
                replicas=(
                    self.autoscaler.replica_count
                    if self.autoscaler is not None
                    else self.serve.bound_replicas
                ),
            )
            if self.timeline is not None:
                # the PR-11 TimelineSource registration: serving series
                # land under ext.serving.* and are SLO-addressable with
                # zero timeline changes
                self.timeline.register_source(self.serve_source)
        else:
            self.serve = self.autoscaler = self.serve_source = None
        # the informer tap: the sim owns the watches and feeds the REAL
        # controller handlers, with the fault layer in between
        self._pod_watch = self.client.watch_pods()
        self._node_watch = self.client.watch_nodes()
        # the warm standby (docs/ha.md): built AFTER the active's watches
        # so both informer taps see the same event stream from here on
        self._ha_promotions = 0
        self._ha_reconciled = 0
        self.standby = None
        #: non-fail-stop fault state (docs/ha.md "Split brain"): the
        #: open partition window's scope+tap, the standby-tail cut, and
        #: the gray window's afflicted tap
        self._partition_state: dict | None = None
        self._stream_cut = False
        self._gray_tap = None
        #: lease-mode double-bind guard: pod name -> node for every
        #: CURRENTLY bound pod; a second successful bind without a
        #: removal in between is the split-brain violation the fencing
        #: exists to prevent (guard armed only in lease mode so the
        #: recovery plane's legitimate strip-and-rebind flows — absent
        #: there — can never false-positive)
        self._bound_nodes: dict[str, str] = {}
        #: read-plane follower stacks (docs/read-plane.md) + the run's
        #: read-availability ledger: each sample event asks every
        #: follower "would a Filter/Prioritize answer right now?" —
        #: ready_to_serve() counts ok, past-bound counts refused
        #: (NotSynced), never silently stale
        self.followers: list[_StandbyStack] = []
        self._follower_reads_ok = 0
        self._follower_reads_refused = 0
        #: per-follower shadow scorers (docs/policy-programs.md),
        #: index-aligned with ``followers``; empty == no candidate ==
        #: every existing digest byte-identical
        self.shadows: list = []
        if self.scenario["ha"]["enabled"]:
            self._build_standby()
            for _ in range(self.scenario["ha"]["followers"]):
                self._build_follower()

        self.report = ReportBuilder(self.scenario, seed)
        self._heap: list[tuple[float, int, object, object]] = []
        self._seq = itertools.count()
        self._uid_seq = itertools.count()
        self.jobs: list[Job] = []
        self._pod_job: dict[str, Job] = {}
        self._pending: list[str] = []  # pod names awaiting re-schedule
        #: lock-order edges the witness held at teardown (lock_witness
        #: scenarios only; tests assert the witness actually observed)
        self.lock_witness_edges = 0

    # -- construction --------------------------------------------------------
    def _side_clock(self, offset_s: float, drift_ppm: float):
        """A per-process lease/fence clock: virtual time plus this
        process's NTP error (the clock_skew fault, docs/ha.md). Offset 0
        / drift 0 reads exactly ``self.now``."""
        if offset_s == 0.0 and drift_ppm == 0.0:
            return lambda: self.now
        return lambda: (
            self.now + offset_s + drift_ppm * 1e-6 * self.now
        )

    def _build_side(self, holder: str, offset_s: float, drift_ppm: float,
                    api_client) -> tuple:
        """(lease, fence, monitor) for one process-equivalent in lease
        mode, wired into its resilient client. None-tuple when lease
        mode is off (the crash-fault promotion path stays
        byte-identical)."""
        from nanotpu.ha.degraded import DegradedMonitor
        from nanotpu.ha.fence import EpochFence
        from nanotpu.ha.lease import LeaderLease

        cfg = self.scenario["ha"]["lease"]
        clock = self._side_clock(offset_s, drift_ppm)
        fence = EpochFence(clock=clock)
        api_client.fence = fence
        lease = LeaderLease(
            api_client, holder, ttl_s=cfg["ttl_s"], clock=clock,
            max_clock_skew_s=cfg["max_clock_skew_s"],
            steal_hysteresis=cfg["steal_hysteresis"],
            steal_backoff_s=cfg["backoff_s"],
            rng=self.rng_lease, fence=fence,
        )
        monitor = None
        budget = self.scenario["ha"]["degraded_budget_s"]
        if budget > 0:
            monitor = DegradedMonitor(
                budget_s=budget, clock=lambda: self.now,
                on_enter=lambda h=holder: self.report.journal(
                    self.now, f"degraded-enter {h}"
                ),
                on_exit=lambda h=holder: self.report.journal(
                    self.now, f"degraded-exit {h}"
                ),
            )
            api_client.degraded = monitor
        return lease, fence, monitor

    def _build_stack(self) -> None:
        """(Re)build dealer + verbs — boot and the agent-restart fault.

        The dealer talks to the cluster through the REAL resilient write
        path (retry + breaker, on virtual clock / no-op sleep) over the
        brownout tap — so a chaos run exercises exactly the production
        degradation code. The wrapper is rebuilt with the dealer: breaker
        and budget state die with the process they model, while the
        counters (the run's measurement) persist."""
        tap = BrownoutClient(self.client, self.faults)
        api_client = ResilientClientset(
            tap,
            counters=self.resilience,
            clock=lambda: self.now,
            sleep=lambda s: None,
            rng=self.rng_retry,
        )
        #: this side's fault tap + (lease mode) lease/fence/monitor —
        #: the partition/gray window events flip flags on the tap of
        #: whichever side is active at window open
        self._active_tap = tap
        self._active_lease = None
        self._active_fence = None
        self._active_monitor = None
        self.dealer = Dealer(
            api_client, make_rater(self.scenario["policy"]), assume_workers=2,
            obs=self.obs, shards=self.scenario["shards"],
            pipeline_depth=self.scenario["pipeline"],
        )
        if self.scenario["ha"]["enabled"]:
            # the HA pair (docs/ha.md): the active emits its delta
            # stream; an agent restart mints a fresh log and the
            # standing standby re-tails it from the start (its state is
            # already consistent with the durable annotations, and
            # overlapping records apply idempotently)
            from nanotpu.ha import DeltaLog, HACoordinator

            self.dealer.ha = DeltaLog(clock=lambda: self.now)
            fence = None
            lease_client = None
            if self.scenario["ha"]["lease"]["enabled"]:
                skew = self.scenario["faults"]["clock_skew"]
                lease, fence, monitor = self._build_side(
                    "rep-0",
                    float(skew.get("active_offset_s", 0) or 0),
                    float(skew.get("active_drift_ppm", 0) or 0),
                    api_client,
                )
                self._active_lease = lease
                self._active_fence = fence
                self._active_monitor = monitor
                # boot-time election: rep-0 races first and wins the
                # empty lease (deterministic — rep-1 probes only from
                # its first ha_tick)
                lease.try_acquire(now=lease.clock())
                self.dealer.ha.epoch = lease.epoch
                lease_client = self.client
            self.ha_active = HACoordinator(
                self.dealer, role="active", log_=self.dealer.ha,
                clock=lambda: self.now, lease=self._active_lease,
                fence=fence, client=lease_client,
            )
            sb = getattr(self, "standby", None)
            if sb is not None:
                sb.coordinator.rebase(self.dealer.ha)
            for fl in getattr(self, "followers", []):
                # an agent restart mints a fresh log; the follower fleet
                # re-tails it exactly like the standby does
                fl.coordinator.rebase(self.dealer.ha)
        else:
            self.ha_active = None
        self._wire_dealer()
        if self.ha_active is not None and self.ha_active.controller is None:
            # lease mode can demote this side into a standby later; the
            # coordinator needs its controller for the dirty-window
            # machinery then (crash mode never demotes the active)
            self.ha_active.controller = self.controller

    def _wire_dealer(self) -> None:
        """Point every stack component at ``self.dealer`` — boot, the
        agent-restart rebuild, and a scheduler-crash promotion all share
        this one rewiring (the promotion adopts the standby's dealer
        instead of building one, docs/ha.md)."""
        self.predicate = Predicate(self.dealer, obs=self.obs)
        self.prioritize = Prioritize(self.dealer, obs=self.obs)
        self.bind_verb = Bind(self.dealer, obs=self.obs)
        bat = self.scenario["batch"]
        if bat["enabled"]:
            # joint batch admission (docs/batch-admission.md), stepped
            # through virtual-time "batch_admit" events; rebuilt with the
            # dealer on agent restart like the verbs (its state is knobs
            # + counters the dealer's PerfCounters carry). cycle_base
            # keeps cycle ids monotonic across the restart: the ledger
            # survives it, and a reused id would merge two unrelated
            # joint solves in a batch_cycle join
            from nanotpu.dealer.admit import BatchAdmitter

            prev = getattr(self, "admitter", None)
            self.admitter = BatchAdmitter(
                self.dealer, lookahead=bat["lookahead"],
                max_batch=bat["max_batch"], obs=self.obs,
                cycle_base=prev.cycles if prev is not None else 0,
            )
            self.dealer.batch = self.admitter
        else:
            self.admitter = None
        self.client.before_bind = self._bind_hook
        plane = getattr(self, "plane", None)
        if plane is not None:
            # agent restart: the plane keeps its holes/leases (recovery
            # intent, not dealer state) and points at the fresh dealer.
            # A promotion also moves its requeue target — the dead
            # active's workqueue drains nowhere (docs/ha.md)
            plane.dealer = self.dealer
            self.dealer.recovery = plane
            if getattr(self, "controller", None) is not None:
                plane.controller = self.controller
        serve = getattr(self, "serve", None)
        if serve is not None and serve.tap is not None:
            # agent restart: the serving tap writes through the fresh
            # dealer (the fleet/queue state is the run's workload)
            serve.tap.dealer = self.dealer
        timeline = getattr(self, "timeline", None)
        if timeline is not None:
            # agent restart: telemetry is the run's measurement — the
            # ring and SLO state persist, only the dealer refs move
            # (rewire_dealer also resets the perf-delta baseline: the
            # fresh dealer's counters restart at zero)
            timeline.rewire_dealer(
                self.dealer, getattr(self.dealer.rater, "model", None)
            )
            timeline.ha = self.ha_active
            self.flight.dealer = self.dealer
        if hasattr(self, "controller"):
            self.controller.dealer = self.dealer
        else:
            # never start()ed: the sim steps it deterministically (the
            # assume sweeper runs through scheduled "assume_sweep" events,
            # not the controller's own thread)
            self.controller = Controller(
                self.client, self.dealer, resync_period_s=0,
                queue_max=self.scenario["queue_max"],
                assume_ttl_s=0,
                resilience=self.resilience,
                obs=self.obs,
            )

    def _build_standby(self) -> None:
        """A fresh warm standby behind the CURRENT active — at boot and
        after every promotion (production restarts the dead replica,
        which comes back as the new standby). Its dealer warm-boots from
        the durable annotations, then tails the active's delta log from
        the seq that boot covered (overlap applies idempotently); its
        controller runs in standby mode (cache + dirty window only).
        Its informer watches are fault-free — the faults under test
        live on the ACTIVE's tap."""
        from nanotpu.ha import HACoordinator

        start_seq = self.dealer.ha.seq
        tap = BrownoutClient(self.client, self.faults)
        api_client = ResilientClientset(
            tap,
            counters=self.resilience,
            clock=lambda: self.now,
            sleep=lambda s: None,
            rng=self.rng_retry,
        )
        sd = Dealer(
            api_client, make_rater(self.scenario["policy"]),
            assume_workers=2, obs=self.obs,
            shards=self.scenario["shards"],
            pipeline_depth=self.scenario["pipeline"],
        )
        sc = Controller(
            self.client, sd, resync_period_s=0,
            queue_max=self.scenario["queue_max"], assume_ttl_s=0,
            resilience=self.resilience, obs=self.obs,
        )
        sc.enter_standby()
        sc.resync_once()  # standby mode: cache prime + synced() gate
        lease = fence = monitor = None
        lease_client = None
        if self.scenario["ha"]["lease"]["enabled"]:
            skew = self.scenario["faults"]["clock_skew"]
            lease, fence, monitor = self._build_side(
                "rep-1",
                float(skew.get("standby_offset_s", 0) or 0),
                float(skew.get("standby_drift_ppm", 0) or 0),
                api_client,
            )
            lease_client = self.client
        coordinator = HACoordinator(
            sd, role="standby", source=self.dealer.ha, controller=sc,
            lag_events=self.scenario["ha"]["lag_events"],
            clock=lambda: self.now, lease=lease, fence=fence,
            client=lease_client,
        )
        coordinator.applied_seq = start_seq
        self.standby = _StandbyStack(
            sd, sc, coordinator,
            self.client.watch_pods(), self.client.watch_nodes(),
            lease=lease, fence=fence, tap=tap, monitor=monitor,
        )

    def _build_follower(self) -> None:
        """One read-plane follower stack (docs/read-plane.md): its own
        dealer + RCU snapshots tailing the CURRENT active's delta log
        within the same lag window the standby models, standby-mode
        controller for the informer cache, and a coordinator that never
        leases and never leads. Reuses ``_StandbyStack`` (lease-less) —
        the cut/tap state follows the process shape exactly like the
        standby's. Followers draw nothing from any rng stream, so
        ``ha.followers`` can never shift a sibling stream (the same
        isolation rule every fault toggle lives under)."""
        from nanotpu.ha import HACoordinator

        start_seq = self.dealer.ha.seq
        tap = BrownoutClient(self.client, self.faults)
        api_client = ResilientClientset(
            tap,
            counters=self.resilience,
            clock=lambda: self.now,
            sleep=lambda s: None,
            rng=self.rng_retry,
        )
        fd = Dealer(
            api_client, make_rater(self.scenario["policy"]),
            assume_workers=2, obs=self.obs,
            shards=self.scenario["shards"],
            pipeline_depth=self.scenario["pipeline"],
        )
        fc = Controller(
            self.client, fd, resync_period_s=0,
            queue_max=self.scenario["queue_max"], assume_ttl_s=0,
            resilience=self.resilience, obs=self.obs,
        )
        fc.enter_standby()
        fc.resync_once()  # standby mode: cache prime + synced() gate
        coordinator = HACoordinator(
            fd, role="follower", source=self.dealer.ha, controller=fc,
            lag_events=self.scenario["ha"]["lag_events"],
            clock=lambda: self.now,
        )
        coordinator.applied_seq = start_seq
        coordinator.read_lag_bound = self.scenario["ha"][
            "follower_lag_bound"
        ]
        self.followers.append(_StandbyStack(
            fd, fc, coordinator,
            self.client.watch_pods(), self.client.watch_nodes(),
            tap=tap,
        ))
        shadow = self.scenario["ha"]["shadow"]
        if shadow["enabled"]:
            # shadow-mode A/B (docs/policy-programs.md): this follower
            # also scores every sampled cycle with a verified candidate
            # program against its own RCU snapshot. The virtual clock
            # keeps the divergence records (and hence the shadow
            # section's digest) byte-reproducible.
            from nanotpu.policy_ir import load_program
            from nanotpu.policy_ir.shadow import ShadowScorer

            self.shadows.append(ShadowScorer(
                fd, load_program(shadow["program"]),
                clock=lambda: self.now,
            ))

    def _push(self, t: float, kind: str, payload=None) -> None:
        heapq.heappush(self._heap, (t, next(self._seq), kind, payload))

    def _uid(self) -> str:
        return f"simuid-{next(self._uid_seq)}"

    # -- the run loop --------------------------------------------------------
    def run(self, include_timing: bool = False) -> dict:
        wall0 = time.perf_counter()
        horizon = self.scenario["horizon_s"]
        self._schedule_static_events(horizon)
        n_since_check = 0
        every = max(1, self.scenario["invariant_every_events"])
        while self._heap:
            t, _, kind, payload = heapq.heappop(self._heap)
            if t >= horizon:
                break
            self.now = t
            self._dispatch(kind, payload)
            self._pump_informers()
            self.report.events_processed += 1
            n_since_check += 1
            if n_since_check >= every:
                n_since_check = 0
                self._check(converged=False)
        self._settle(horizon)
        self.report.fault_counts = dict(self.faults.counts)
        if self.faults.nfs_armed:
            # the non-fail-stop counter block appears exactly when one
            # of partition/skew/thrash/gray is configured — existing
            # scenarios' reports (and pinned digests) stay byte-identical
            self.report.fault_counts.update(self.faults.counts_nfs)
        self.report.pods["pending_final"] = len(self._pending)
        self.report.resilience = self._deterministic_resilience()
        # every trace/decision timestamp is virtual time and every event
        # fires on the sim thread, so this digest is part of the
        # determinism contract — a replayable causal narrative per pod
        self.report.traces = self.obs.digest_summary()
        if self.scenario["lock_witness"]:
            # teardown assert: any two code paths that disagreed about
            # lock order during the run fail the soak HERE, with the
            # witness stack of every edge in the cycle. The edge set
            # itself stays out of the report: it depends on wall-clock
            # thread interleaving (recorder/assume-pool threads), and the
            # digest must remain byte-reproducible with the witness on.
            self.lock_witness_edges = (
                lock_witness.global_witness().edge_count()
            )
            lock_witness.global_witness().assert_acyclic()
        return self.report.build(
            include_timing=include_timing,
            wall_s=time.perf_counter() - wall0,
            fleet=fleet_summary(self.client),
        )

    def _schedule_static_events(self, horizon: float) -> None:
        w = self.scenario["workload"]
        if w["kind"] == "poisson":
            for t, config in poisson_arrivals(w, horizon, self.rng_workload):
                self._push(t, "arrival", {"config": config})
        else:
            for t, config, entry in trace_arrivals(w, horizon):
                self._push(t, "arrival", {"config": config, "trace": entry})
        for t, config in self.faults.overload_arrivals(
            w, horizon, self.rng_overload
        ):
            self._push(t, "arrival", {"config": config, "burst": True})
        for t in self.faults.flap_times(horizon):
            self._push(t, "flap", None)
        for t in self.faults.restart_times(horizon):
            self._push(t, "agent_restart", None)
        for t in self.faults.crash_times(horizon):
            self._push(t, "scheduler_crash", None)
        for start, end in self.faults.brownout_windows(horizon):
            self._push(start, "brownout", True)
            self._push(end, "brownout", False)
        for start, end, scope in self.faults.partition_windows(horizon):
            self._push(start, "partition", {"on": True, "scope": scope})
            self._push(end, "partition", {"on": False})
        for start, end in self.faults.thrash_windows(horizon):
            self._push(start, "lease_thrash", True)
            self._push(end, "lease_thrash", False)
        for start, end in self.faults.gray_windows(horizon):
            self._push(start, "gray", True)
            self._push(end, "gray", False)
        lease_cfg = self.scenario["ha"]["lease"]
        if lease_cfg["enabled"]:
            t = lease_cfg["period_s"]
            while t < horizon:
                self._push(t, "ha_tick", None)
                t += lease_cfg["period_s"]
        ttl = self.scenario["assume_ttl_s"]
        if ttl > 0:
            t = ttl / 2
            while t < horizon:
                self._push(t, "assume_sweep", None)
                t += ttl / 2
        rec = self.scenario["recovery"]
        if rec["enabled"] and rec["every_s"] > 0:
            t = rec["every_s"]
            while t < horizon:
                self._push(t, "recovery_cycle", None)
                t += rec["every_s"]
        tel = self.scenario["telemetry"]
        if tel["enabled"]:
            t = tel["every_s"]
            while t < horizon:
                self._push(t, "telemetry_tick", None)
                t += tel["every_s"]
        bat = self.scenario["batch"]
        if bat["enabled"] and bat["every_s"] > 0:
            t = bat["every_s"]
            while t < horizon:
                self._push(t, "batch_admit", None)
                t += bat["every_s"]
        srv = self.scenario["serving"]
        if srv["enabled"]:
            t = srv["every_s"]
            while t < horizon:
                self._push(t, "serving_tick", None)
                t += srv["every_s"]
            if srv["autoscale"]["enabled"]:
                # cycle 0 at t=0 bootstraps min_replicas before the
                # first serving tick — the same cold start the static
                # fleet's t=0 bootstrap gets, so an ON-vs-OFF A/B
                # compares ramps, not boot order
                t = 0.0
                while t < horizon:
                    self._push(t, "autoscale_cycle", None)
                    t += srv["autoscale"]["every_s"]
            else:
                self._push(0.0, "serve_bootstrap", None)
        metric_every, metric_delay = self.faults.metric_cadence()
        if metric_every > 0:
            t = metric_every
            while t < horizon:
                self._push(t, "metric_sync", {"delay": metric_delay})
                t += metric_every
        for name, every in (
            ("resync", self.scenario["resync_every_s"]),
            ("sample", self.scenario["sample_every_s"]),
            ("retry", self.scenario["retry_every_s"]),
        ):
            if every > 0:
                t = every
                while t < horizon:
                    self._push(t, name, None)
                    t += every

    def _dispatch(self, kind: str, payload) -> None:
        if kind == "arrival":
            self._on_arrival(payload)
        elif kind == "departure":
            self._on_departure(payload)
        elif kind == "flap":
            self._on_flap()
        elif kind == "flap_restore":
            self._on_flap_restore(payload)
        elif kind == "agent_restart":
            self._on_agent_restart()
        elif kind == "scheduler_crash":
            self._on_scheduler_crash()
        elif kind == "metric_sync":
            self._on_metric_sync(payload)
        elif kind == "metric_apply":
            self._on_metric_apply(payload)
        elif kind == "resync":
            self._on_resync()
        elif kind == "sample":
            self._on_sample()
        elif kind == "retry":
            self._on_retry()
        elif kind == "gang_resubmit":
            self._on_gang_resubmit(payload)
        elif kind == "brownout":
            self._on_brownout(payload)
        elif kind == "partition":
            self._on_partition(payload)
        elif kind == "lease_thrash":
            self._on_lease_thrash(payload)
        elif kind == "gray":
            self._on_gray(payload)
        elif kind == "ha_tick":
            self._on_ha_tick()
        elif kind == "assume_sweep":
            self._on_assume_sweep()
        elif kind == "recovery_cycle":
            self._on_recovery()
        elif kind == "telemetry_tick":
            self._on_telemetry()
        elif kind == "batch_admit":
            self._on_batch_admit()
        elif kind == "serving_tick":
            self._on_serving_tick()
        elif kind == "autoscale_cycle":
            self._on_autoscale()
        elif kind == "serve_bootstrap":
            self._on_serve_bootstrap()
        else:  # pragma: no cover - event kinds are closed within this file
            raise AssertionError(f"unknown event kind {kind}")

    # -- informer tap --------------------------------------------------------
    def _pump_informers(self) -> None:
        """Deliver queued watch events to the real controller handlers,
        applying drop/duplicate faults, then drain the sync workqueue.
        A side whose apiserver link is partitioned polls nothing — its
        events buffer in the watch and deliver in order at heal (the
        informer backlog of a real reconnect)."""
        delivered = not (
            self._active_tap is not None and self._active_tap.partitioned
        )
        while delivered:
            delivered = False
            for watch, handler in (
                (self._node_watch, self.controller.handle_node_event),
                (self._pod_watch, self.controller.handle_pod_event),
            ):
                while True:
                    event = watch.poll(timeout=0.0)
                    if event is None:
                        break
                    delivered = True
                    if self.faults.drop_event():
                        self.report.journal(
                            self.now,
                            f"drop {event.type} {event.obj.name}",
                        )
                        continue
                    handler(event)
                    if self.faults.duplicate_event():
                        self.report.journal(
                            self.now, f"dup {event.type} {event.obj.name}"
                        )
                        handler(event)
        self.controller.drain_sync()
        self._pump_standby()

    def _pump_standby(self) -> None:
        """Deliver the standby's and every follower's informer events
        (fault-free: the faults under test live on the active's tap)
        and tail the delta stream within the configured lag — each
        replica's event loop, stepped deterministically on the sim
        thread."""
        for sb in ([self.standby] if self.standby is not None else []) \
                + self.followers:
            if not (sb.tap is not None and sb.tap.partitioned):
                for watch, handler in (
                    (sb.node_watch, sb.controller.handle_node_event),
                    (sb.pod_watch, sb.controller.handle_pod_event),
                ):
                    while True:
                        event = watch.poll(timeout=0.0)
                        if event is None:
                            break
                        handler(event)
            # the stream-cut fault severs every replica tailing the
            # active — standby and follower fleet alike
            if not self._stream_cut:
                sb.coordinator.tail_once()

    # -- scheduling cycle ----------------------------------------------------
    def _live_node_names(self) -> list[str]:
        return sorted(n.name for n in self.client.list_nodes())

    def _run_verb(self, verb_obj, args, uid: str):
        """One verb call, traced on the virtual clock when the scenario
        enables tracing — the sim-side mirror of the route layer's
        sampled path (one trace per request, thread-local current set
        so the resilient client's retry/breaker events land in it)."""
        if not self.obs.tracer.sample:
            return verb_obj.handle(args)
        trace = self.obs.tracer.begin(verb_obj.name, uid)
        if trace is None:  # a future 1-in-N scenario knob must not crash
            return verb_obj.handle(args)
        set_current(trace)
        try:
            return verb_obj.handle(args, trace=trace)
        finally:
            set_current(None)
            self.obs.tracer.commit(trace)

    def _gang_can_place(self, job: Job) -> bool:
        """All-or-nothing placement check for a strict gang: virtually
        place every UNBOUND member on scratch copies of the live chip
        state (hole-filtered, same rule a real Filter sees). True iff
        the whole remainder fits at once — the sim-level analogue of
        the dealer's strict barrier, whose park a single-threaded
        driver cannot express. The placement logic itself lives in
        :func:`nanotpu.recovery.plane.demands_fit`, shared with the
        plane's clearing pass so gate and plane can never drift
        (docs/defrag.md)."""
        from nanotpu.allocator.core import Demand
        from nanotpu.recovery.plane import demands_fit

        infos = self.dealer.debug_snapshot()["node_infos"]
        names = sorted(infos)
        unbound = [
            p for p in job.pods
            if p.name not in job.bound_t and p.name in self._pod_job
        ]
        if not unbound:
            return True
        # every member of one gang sees the same candidate filter (same
        # annotations), so compute it once
        allowed = names
        if self.plane is not None:
            allowed = self.plane.filter_candidates(
                unbound[0], names, now=self.now
            )
        return demands_fit(
            infos, allowed,
            [Demand.from_pod(p) for p in unbound],
            self.dealer.rater,
        )

    def _strict_gate(self, job: Job) -> bool:
        """True when ``job`` may attempt member binds now (memoized per
        virtual time so a 16-member retry costs one placement check)."""
        if not (
            job.gang and self.scenario["workload"]["gang_strict"]
        ):
            return True
        if job.gate_t != self.now:
            job.gate_ok = self._gang_can_place(job)
            job.gate_t = self.now
        return job.gate_ok

    def _try_schedule(self, job: Job, pod: Pod) -> bool:
        if not self._strict_gate(job):
            return False
        node_names = self._live_node_names()
        if self.plane is not None:
            # hole-aware candidate filtering (docs/defrag.md): nodes
            # earmarked for other gangs are withheld unless this pod
            # qualifies for a backfill lease
            node_names = self.plane.filter_candidates(
                pod, node_names, now=self.now
            )
        if not node_names:
            return False
        args = {"Pod": pod.raw, "NodeNames": node_names}
        t0 = time.perf_counter()
        filt = self._run_verb(self.predicate, args, pod.uid)
        self.report.observe_verb("filter", time.perf_counter() - t0)
        feasible = set(filt["NodeNames"])
        if not feasible:
            return False
        t0 = time.perf_counter()
        scored = self._run_verb(self.prioritize, args, pod.uid)
        self.report.observe_verb("prioritize", time.perf_counter() - t0)
        ranked = sorted(
            ((name, score) for name, score in scored if name in feasible),
            key=lambda ns: (-ns[1], ns[0]),
        )
        for attempt, (best, _) in enumerate(ranked):
            if attempt > BIND_RETRIES_PER_CYCLE:
                break
            t0 = time.perf_counter()
            result = self._run_verb(self.bind_verb, {
                "PodName": pod.name,
                "PodNamespace": pod.namespace,
                "PodUID": pod.uid,
                "Node": best,
            }, pod.uid)
            self.report.observe_verb("bind", time.perf_counter() - t0)
            if not result["Error"]:
                self._note_bound(job, pod, best)
                return True
            self.report.pods["bind_errors"] += 1
            self.report.journal(
                self.now, f"bind-error {pod.name} @ {best}"
            )
        return False

    def _note_bound(self, job: Job, pod: Pod, best: str) -> None:
        """Post-bind bookkeeping shared by the pod-at-a-time cycle and
        the batch-admission cycle (one copy: departure scheduling, gang
        completion, and the recovery plane's lease hook must not drift
        between the two admission paths)."""
        if self.scenario["ha"]["lease"]["enabled"]:
            # the split-brain certification's sharpest check: a second
            # successful bind of a still-bound pod means TWO dealers
            # each believed they committed it — exactly what the epoch
            # fence exists to prevent (docs/ha.md)
            prev = self._bound_nodes.get(pod.name)
            if prev is not None:
                self.report.violations.append({
                    "kind": "double_bind",
                    "detail": (
                        f"pod {pod.name} bound to {best} while still "
                        f"bound to {prev} (split-brain write)"
                    ),
                })
            self._bound_nodes[pod.name] = best
        job.bound_t[pod.name] = self.now
        self.report.pods["bound"] += 1
        self.report.config_count(job.config, "bound")
        self.report.journal(self.now, f"bind {pod.name} -> {best}")
        if self.plane is not None:
            leased = self.plane.note_bound(
                pod, best, now=self.now
            )
            if leased is not None:
                self.report.journal(
                    self.now,
                    f"backfill {pod.name} @ {best} for {leased}",
                )
        if (
            self.scenario["workload"]["lifetime_from_bind"]
            and not job.gang
            and not job.departure_scheduled
        ):
            job.departure_scheduled = True
            self._push(
                self.now + job.lifetime_s, "departure", job
            )
        if job.gang and job.fully_bound() and \
                not job.wait_recorded:
            # exactly-once: recovery paths can re-trigger the
            # fully_bound transition (a migrated member re-binds
            # through the replay path); the gang's wait is its
            # FIRST completion only
            job.wait_recorded = True
            self.report.gang_waits_s.append(
                round(self.now - job.arrival_t, 6)
            )
            self.report.journal(
                self.now, f"gang-complete {job.gang}"
            )
            if self.plane is not None:
                self.plane.gang_bound(
                    f"{pod.namespace}/{job.gang}"
                )
            if (
                self.scenario["workload"]["lifetime_from_bind"]
                and not job.departure_scheduled
            ):
                # training holds its slice for lifetime_s FROM
                # START (full bind), not from submission — the
                # departure is scheduled here instead of at
                # admission (scenario knob; docs/defrag.md)
                job.departure_scheduled = True
                self._push(
                    self.now + job.lifetime_s, "departure", job
                )

    # -- event handlers ------------------------------------------------------
    def _admit_job(self, job: Job) -> None:
        self.jobs.append(job)
        created: list[Pod] = []
        for pod in job.pods:
            created.append(self.client.create_pod(pod))
            self._pod_job[pod.name] = job
        job.pods = created  # keep the server-side copies (resourceVersion)
        self.report.pods["arrived"] += job.size
        self.report.config_count(job.config, "arrived", job.size)
        self.report.journal(
            self.now, f"arrive {job.config}-{job.id} x{job.size}"
        )
        for pod in job.pods:
            if not self._try_schedule(job, pod):
                self._pending.append(pod.name)
        if not self.scenario["workload"]["lifetime_from_bind"]:
            job.departure_scheduled = True
            self._push(self.now + job.lifetime_s, "departure", job)
        # else: the departure is scheduled by the STARTING bind in
        # _try_schedule (a job holds capacity lifetime_s from start:
        # non-gang jobs start at their first bound pod, gangs at full
        # bind); a job that never starts simply parks until the horizon

    def _on_arrival(self, payload: dict) -> None:
        w = self.scenario["workload"]
        trace = payload.get("trace") or {}
        # overload-burst arrivals draw their lifetime/shape from the
        # dedicated rng_overload stream, end to end: the isolation rule is
        # that toggling the fault changes NOTHING about the base jobs —
        # not their arrival times (pinned at schedule time) and not their
        # shapes (drawn here, in arrival order, from rng_workload only)
        burst = bool(payload.get("burst"))
        rng = self.rng_overload if burst else self.rng_workload
        config = payload["config"]
        # per-config lifetime override (capacity-recovery scenarios give
        # training gangs their own duration); absent == the shared spec,
        # so existing scenarios draw byte-identically
        life_spec = w["lifetime_overrides"].get(config) or w["lifetime_s"]
        # explicit trace overrides win even when falsy (lifetime_s: 0 ==
        # depart immediately); only absence falls back to the scenario
        life = trace.get("lifetime_s")
        if life is None:
            life = draw_lifetime(life_spec, rng)
        gang_size = trace.get("gang_size")
        replicas = trace.get("replicas")
        prio = w["priorities"].get(config)
        job = build_job(
            job_id=len(self.jobs),
            config=config,
            arrival_t=self.now,
            lifetime_s=float(life),
            rng=rng,
            uid_of=lambda name: self._uid(),
            gang_size=int(w["gang_size"] if gang_size is None else gang_size),
            replicas=int(w["replicas"] if replicas is None else replicas),
            priority=prio,
            # the DECLARED runtime is the config's mean, not the draw —
            # the submitter's estimate, which the exp tail then exceeds
            # (exactly what exercises the backfill lease contract)
            declared_runtime_s=(
                float(life_spec.get("mean", 15.0))
                if prio is not None else None
            ),
            gang_percent=int(w["gang_percent"]),
            spread_percent=int(w["spread_percent"]),
        )
        job.burst = burst
        self._admit_job(job)

    def _remove_pod(self, pod: Pod, complete_first: bool) -> None:
        """Take one pod out of the cluster, optionally through the
        Succeeded phase first (exercises release-on-completion as well as
        release-on-delete)."""
        if complete_first:
            try:
                fresh = self.client.get_pod(pod.namespace, pod.name)
            except Exception:
                return
            fresh.raw.setdefault("status", {})["phase"] = "Succeeded"
            self.client.update_pod(fresh)
        try:
            self.client.delete_pod(pod.namespace, pod.name)
        except Exception:
            return
        if pod.name in self._pending:
            self._pending.remove(pod.name)
        self._pod_job.pop(pod.name, None)
        self._bound_nodes.pop(pod.name, None)
        if self.plane is not None:
            self.plane.pod_gone(pod.uid)

    def _on_departure(self, job: Job) -> None:
        if job.departed:
            return
        job.departed = True
        n = 0
        for pod in job.pods:
            if pod.name in self._pod_job:
                self._remove_pod(
                    pod, complete_first=self.rng_lifecycle.random() < 0.5
                )
                n += 1
        self.report.pods["departed"] += n
        self.report.config_count(job.config, "departed", n)
        self.report.journal(self.now, f"depart {job.config}-{job.id} x{n}")
        if job.gang and self.plane is not None:
            self.plane.gang_gone(f"default/{job.gang}")

    def _on_flap(self) -> None:
        names = self._live_node_names()
        if not names:
            return
        victim = self.rng_fault.choice(names)
        raw = plain_copy(self.client.get_node(victim).raw)
        self.faults.counts["node_flaps"] += 1
        self.report.journal(self.now, f"flap {victim}")
        self.client.delete_node(victim)
        # evict the victim's pods; a gang that lost a member dies whole
        # (a JAX job cannot run short) and is resubmitted
        gangs_killed: list[Job] = []
        for pod in self.client.list_pods():
            if pod.node_name != victim or pod.name not in self._pod_job:
                continue
            job = self._pod_job[pod.name]
            if job.gang and not job.departed and job not in gangs_killed:
                gangs_killed.append(job)
                continue
            self._remove_pod(pod, complete_first=False)
            self.faults.counts["pods_evicted"] += 1
            self.report.pods["evicted"] += 1
        for job in gangs_killed:
            self._kill_gang(job)
        self._push(self.now + self.faults.flap_down_s, "flap_restore", raw)

    def _kill_gang(self, job: Job) -> None:
        job.departed = True
        for pod in job.pods:
            if pod.name in self._pod_job:
                self._remove_pod(pod, complete_first=False)
                self.faults.counts["pods_evicted"] += 1
                self.report.pods["evicted"] += 1
        self.faults.counts["gangs_killed"] += 1
        self.report.journal(self.now, f"gang-killed {job.gang}")
        if self.plane is not None:
            self.plane.gang_gone(f"default/{job.gang}")
        self._push(
            self.now + GANG_RESUBMIT_DELAY_S, "gang_resubmit",
            {"job": job, "incarnation": job.incarnation + 1},
        )

    def _on_gang_resubmit(self, payload: dict) -> None:
        old: Job = payload["job"]
        incarnation = payload.get("incarnation", 1)
        w = self.scenario["workload"]
        life_spec = (
            w["lifetime_overrides"].get(old.config) or w["lifetime_s"]
        )
        prio = w["priorities"].get(old.config)
        job = build_job(
            job_id=old.id,
            config=old.config,
            arrival_t=self.now,
            lifetime_s=draw_lifetime(life_spec, self.rng_lifecycle),
            rng=self.rng_lifecycle,
            uid_of=lambda name: self._uid(),
            gang_size=old.size,
            incarnation=incarnation,
            priority=prio,
            declared_runtime_s=(
                float(life_spec.get("mean", 15.0))
                if prio is not None else None
            ),
            gang_percent=int(w["gang_percent"]),
            spread_percent=int(w["spread_percent"]),
        )
        self._admit_job(job)

    def _on_flap_restore(self, raw: dict) -> None:
        name = (raw.get("metadata") or {}).get("name", "")
        try:
            self.client.get_node(name)
            return  # already back (double restore cannot happen, defensive)
        except Exception:
            pass
        self.client.create_node(Node(plain_copy(raw)))
        self.report.journal(self.now, f"restore {name}")

    def _on_agent_restart(self) -> None:
        occ_before = self.dealer.occupancy()
        self.dealer.close()
        if self.flight is not None:
            # post-mortem against the DEAD dealer, before the rebuild:
            # the bundle must come out complete even though the process
            # it describes is gone (the acceptance drill for real
            # crash-time dumps; every tap degrades, never raises)
            self.flight.dump("dealer_death", now=self.now)
            self.report.journal(self.now, "flight-dump dealer_death")
        self._build_stack()
        occ_after = self.dealer.occupancy()
        # the rebuilt dealer must agree with the DURABLE state (live pod
        # annotations), not with the old dealer's in-memory view — which
        # may legitimately be stale mid-run (e.g. a dropped DELETE event
        # the next resync would have repaired)
        occ_truth = ground_truth_occupancy(self.dealer, self.client)
        drift = abs(occ_after - occ_truth)
        self.faults.counts["agent_restarts"] += 1
        self.report.restart_occupancy_drift = max(
            self.report.restart_occupancy_drift, drift
        )
        self.report.journal(
            self.now,
            f"agent-restart occ {occ_before:.6f} -> {occ_after:.6f} "
            f"(truth {occ_truth:.6f})",
        )
        if drift > 1e-9:
            self.report.violations.append({
                "kind": "restart_occupancy_drift",
                "detail": (
                    f"annotation-replay restart rebuilt occupancy "
                    f"{occ_after:.6f} but live annotations say "
                    f"{occ_truth:.6f}"
                ),
            })

    def _on_scheduler_crash(self) -> None:
        """Kill the ACTIVE dealer mid-run (docs/ha.md): its delta stream
        stops where the standby's applied_seq stands — records past it
        died with the process — the warm standby promotes in ONE step
        (O(lag-window) reconcile against its informer dirty keys), the
        sim adopts the standby's stack as the active, and a FRESH
        standby boots behind the new leader. Convergence is judged
        against the durable annotations exactly like the agent-restart
        fault — the promoted dealer must agree with ground truth."""
        sb = self.standby
        if sb is None:
            return
        occ_before = self.dealer.occupancy()
        if self.flight is not None:
            # post-mortem of the dying active, exactly like the
            # agent-restart drill: the bundle must come out complete
            self.flight.dump("dealer_death", now=self.now)
            self.report.journal(self.now, "flight-dump dealer_death")
        self.dealer.close()
        # the dead active's informer watches die with it
        self._pod_watch.stop()
        self._node_watch.stop()
        result = sb.coordinator.promote(now=self.now)
        self.faults.counts["scheduler_crashes"] += 1
        self._ha_promotions += 1
        self._ha_reconciled += max(result["reconciled"], 0)
        # adopt the standby's stack as the active's
        self.dealer = sb.dealer
        self.controller = sb.controller
        self._pod_watch = sb.pod_watch
        self._node_watch = sb.node_watch
        self.ha_active = sb.coordinator
        self._wire_dealer()
        # anything the reconcile requeued drains now, on the sim thread
        self.controller.drain_sync()
        occ_after = self.dealer.occupancy()
        occ_truth = ground_truth_occupancy(self.dealer, self.client)
        drift = abs(occ_after - occ_truth)
        self.report.restart_occupancy_drift = max(
            self.report.restart_occupancy_drift, drift
        )
        self.report.journal(
            self.now,
            f"scheduler-crash occ {occ_before:.6f} -> {occ_after:.6f} "
            f"(truth {occ_truth:.6f}) reconciled={result['reconciled']}",
        )
        if drift > 1e-9:
            self.report.violations.append({
                "kind": "failover_occupancy_drift",
                "detail": (
                    f"promoted standby holds occupancy {occ_after:.6f} "
                    f"but live annotations say {occ_truth:.6f}"
                ),
            })
        # the follower fleet re-anchors its tails onto the promoted
        # leader's fresh delta log (docs/read-plane.md) — each
        # follower's own warm state keeps serving throughout, and the
        # availability ledger witnesses it: a follower that came out of
        # the re-anchor unable to serve counts a refused read
        for fl in self.followers:
            fl.coordinator.rebase(self.dealer.ha)
            if fl.coordinator.ready_to_serve(now=self.now):
                self._follower_reads_ok += 1
            else:
                self._follower_reads_refused += 1
                fl.coordinator.reads_refused += 1
        # pending pods retry against the new leader immediately — the
        # sim analogue of kube-scheduler's retry landing on the freshly
        # ready replica
        self._on_retry()
        # production restarts the dead replica; it returns as the new
        # standby behind the promoted leader
        self._build_standby()

    def _on_brownout(self, active: bool) -> None:
        self.faults.brownout_active = active
        if active:
            self.faults.counts["brownouts"] += 1
        self.report.journal(
            self.now, "brownout-start" if active else "brownout-end"
        )

    # -- non-fail-stop faults (docs/ha.md "Split brain and fencing") ---------
    def _on_partition(self, payload: dict) -> None:
        """Cut (or heal) the CURRENT active's links. Both processes stay
        alive and keep trying — the whole point: the deposed side's
        writes must die on its fence, not on its absence."""
        if payload["on"]:
            scope = payload["scope"]
            self.faults.counts_nfs["partitions"] += 1
            state = {"scope": scope, "tap": self._active_tap}
            if scope in ("api", "full") and self._active_tap is not None:
                self._active_tap.partitioned = True
            if scope in ("stream", "full"):
                self._stream_cut = True
            self._partition_state = state
            self.report.journal(self.now, f"partition-start scope={scope}")
        else:
            state = self._partition_state or {}
            tap = state.get("tap")
            if tap is not None:
                tap.partitioned = False
            self._stream_cut = False
            self._partition_state = None
            self.report.journal(
                self.now, f"partition-end scope={state.get('scope', '?')}"
            )

    def _on_lease_thrash(self, active: bool) -> None:
        self.faults.thrash_active = active
        if active:
            self.faults.counts_nfs["lease_thrash_windows"] += 1
        self.report.journal(
            self.now,
            "lease-thrash-start" if active else "lease-thrash-end",
        )

    def _on_gray(self, active: bool) -> None:
        """Gray degradation afflicts the side that is active at window
        open; like the partition, the affliction follows the process."""
        if active:
            self.faults.counts_nfs["gray_windows"] += 1
            self._gray_tap = self._active_tap
            if self._gray_tap is not None:
                self._gray_tap.gray = True
            self.report.journal(self.now, "gray-start")
        else:
            if self._gray_tap is not None:
                self._gray_tap.gray = False
            self._gray_tap = None
            self.report.journal(self.now, "gray-end")

    def _on_ha_tick(self) -> None:
        """One lease-dance cycle for BOTH processes on virtual time —
        the sim-side HALoop body. Active side first (renew or demote),
        then the standby's steal probe; promotion swaps the sim's
        serving pointer between two LIVE stacks."""
        co_a = self.ha_active
        lease_a = self._active_lease
        if co_a is not None and lease_a is not None:
            if co_a.role == "active":
                if co_a.log is not None and co_a.log.epoch != lease_a.epoch:
                    co_a.log.epoch = lease_a.epoch
                now_a = lease_a.clock()
                if not (
                    lease_a.renew(now=now_a)
                    or lease_a.try_acquire(now=now_a)
                ):
                    # leadership lost (or unprovable): demote IN PLACE.
                    # The stack stays alive and keeps answering reads;
                    # its fence already closed, so writes die typed.
                    co_a.role = "standby"
                    self.report.journal(
                        self.now, f"ha-demote {lease_a.holder}"
                    )
            elif lease_a.try_acquire(now=lease_a.clock()):
                # a deposed-in-place leader re-won (lease API healed
                # before the peer stole): flip back — same process,
                # new epoch term, no swap needed
                result = co_a.promote(now=self.now)
                self._ha_promotions += 1
                self._ha_reconciled += max(result["reconciled"], 0)
                self.report.journal(
                    self.now,
                    f"ha-repromote {lease_a.holder} "
                    f"epoch={lease_a.epoch} "
                    f"reconciled={result['reconciled']}",
                )
                self._on_retry()
        sb = self.standby
        if (
            sb is not None and sb.lease is not None
            and sb.coordinator.role == "standby"
            and sb.lease.try_acquire(now=sb.lease.clock())
        ):
            result = sb.coordinator.promote(now=self.now)
            self._ha_promotions += 1
            self._ha_reconciled += max(result["reconciled"], 0)
            verify = result.get("verify") or {}
            self.report.journal(
                self.now,
                f"ha-promote {sb.lease.holder} epoch={sb.lease.epoch} "
                f"reconciled={result['reconciled']} "
                f"verify={verify.get('match', 'skipped')}",
            )
            self._swap_leader(sb)

    def _swap_leader(self, sb) -> None:
        """Adopt the freshly-promoted standby as the serving stack and
        demote the old active INTO the standby slot — both processes
        stay alive (the split-brain drill). The old side re-tails the
        new leader's stream anchored at its present seq: its own state
        is consistent with everything it committed (fenced writes
        rolled back), and the new leader's future commits stream to it
        like to any standby."""
        old = _StandbyStack(
            self.dealer, self.controller, self.ha_active,
            self._pod_watch, self._node_watch,
            lease=self._active_lease, fence=self._active_fence,
            tap=self._active_tap, monitor=self._active_monitor,
        )
        old.coordinator.role = "standby"
        old.controller.enter_standby()
        old.coordinator.source = sb.dealer.ha
        old.coordinator.applied_seq = sb.dealer.ha.seq
        old.coordinator.lag_events = self.scenario["ha"]["lag_events"]
        old.coordinator.stale = False
        # adopt the new leader
        self.dealer = sb.dealer
        self.controller = sb.controller
        self._pod_watch = sb.pod_watch
        self._node_watch = sb.node_watch
        self.ha_active = sb.coordinator
        self._active_lease = sb.lease
        self._active_fence = sb.fence
        self._active_tap = sb.tap
        self._active_monitor = sb.monitor
        self._wire_dealer()
        self.controller.drain_sync()
        self.standby = old
        # the follower fleet re-tails the new leader's stream, serving
        # throughout — same re-anchor + availability accounting as the
        # crash path (docs/read-plane.md)
        for fl in self.followers:
            fl.coordinator.rebase(self.dealer.ha)
            if fl.coordinator.ready_to_serve(now=self.now):
                self._follower_reads_ok += 1
            else:
                self._follower_reads_refused += 1
                fl.coordinator.reads_refused += 1
        # pending pods retry against the new leader immediately — the
        # sim analogue of kube-scheduler's retry landing on the freshly
        # ready replica
        self._on_retry()

    def _on_recovery(self) -> None:
        """One capacity-recovery cycle on virtual time: hand the plane
        the pending GANG pods (the sim's view of a parked gang — the
        single-threaded driver cannot park strict barriers, so pending
        members stand in for parked reservations), journal every action
        (the digest witnesses each preempt/migrate/lease decision), and
        requeue evicted pods into the pending list — the sim-side half
        of preempt-and-requeue (the coalescing-queue half runs inside
        the plane via Controller.requeue)."""
        if self._degraded_skip("recovery"):
            return
        parked = []
        for name in self._pending:
            job = self._pod_job.get(name)
            if job is None or job.departed or not job.gang:
                continue
            try:
                parked.append(self.client.get_pod("default", name))
            except Exception:
                continue
        result = self.plane.run_once(self.now, parked)
        for kind, detail in result["actions"]:
            self.report.journal(self.now, f"{kind} {detail}")
        for name in result["evicted"]:
            job = self._pod_job.get(name)
            if job is not None and not job.departed and \
                    name not in self._pending:
                self._pending.append(name)
        if result["actions"]:
            # a cycle that acted nudges an immediate retry — the sim
            # analogue of the plane's force=True requeue through the
            # coalescing queue: cleared capacity must not idle until the
            # next retry tick (that idle is exactly the reserved-capacity
            # waste the backfill half exists to recoup)
            self._on_retry()

    def _on_telemetry(self) -> None:
        """One telemetry tick on virtual time: snapshot the timeline,
        run the SLO watchdog's two-window burn evaluation, journal the
        tick and every breach/clear transition (digest-witnessed), and
        hand breach transitions to the flight recorder — exactly the
        production TelemetryLoop body, driven deterministically."""
        tick = self.timeline.tick(now=self.now)
        self.report.journal(
            self.now,
            f"telemetry tick={tick['tick']} "
            f"occ={tick['fleet']['occupancy']:.6f} "
            f"frag={tick['fleet']['fragmentation']:.4f} "
            f"whole_free={tick['fleet']['whole_free_chips']}",
        )
        for tr in self.watchdog.evaluate(now=self.now):
            self.report.journal(
                self.now,
                f"slo-{tr['event']} {tr['name']} "
                f"burn_long={tr['burn_long']:.6f} "
                f"burn_short={tr['burn_short']:.6f}",
            )
            if tr["event"] == "breach":
                self.flight.dump(f"slo:{tr['name']}", now=self.now)

    def _on_batch_admit(self) -> None:
        """One joint batch-admission cycle on virtual time
        (docs/batch-admission.md): drain the pending queue — the sim's
        analogue of the controller's coalescing queue — into ONE fused
        native solve, commit winners INLINE through the real
        ``Dealer.bind`` (the sim is single-threaded, so the inline
        committer is the deterministic stand-in for the production
        commit fan-out), journal every action (digest-witnessed), and
        leave losers pending for the pod-at-a-time retry path
        untouched."""
        if self._degraded_skip("batch"):
            return
        if not self._pending:
            return
        offered: list = []
        by_name: dict[str, object] = {}
        for name in self._pending:
            job = self._pod_job.get(name)
            if job is None or job.departed:
                continue
            if not self._strict_gate(job):
                # all-or-nothing gangs wait for the sim-level gate just
                # as they do on the pod-at-a-time path
                continue
            try:
                pod = self.client.get_pod("default", name)
            except Exception:
                continue
            offered.append(pod)
            by_name[name] = job
            if len(offered) >= self.scenario["batch"]["max_batch"]:
                break
        if not offered:
            return
        result = self.admitter.admit(
            offered, self._live_node_names(),
            bind=lambda node, pod: self.dealer.bind(node, pod),
        )
        self.report.journal(
            self.now,
            f"batch-admit cycle={result.cycle} offered={len(offered)} "
            f"bound={len(result.bound)} failed={len(result.failed)} "
            f"unplaced={len(result.unplaced)}"
            + (" fellback" if result.fell_back else ""),
        )
        for pod, node, _score in result.bound:
            self._pending.remove(pod.name)
            self._note_bound(by_name[pod.name], pod, node)
        for pod, _err in result.failed:
            self.report.pods["bind_errors"] += 1
            self.report.journal(
                self.now, f"batch-bind-error {pod.name}"
            )

    # -- the scheduler<->serving loop (docs/serving-loop.md) -----------------
    def _autoscale_config(self):
        from nanotpu.serving.autoscale import AutoscaleConfig

        srv = self.scenario["serving"]
        a = srv["autoscale"]
        return AutoscaleConfig(
            min_replicas=a["min"], max_replicas=a["max"],
            slots_per_replica=srv["slots_per_replica"],
            target_utilization=a["target_util"],
            up_cooldown_s=a["up_cooldown_s"],
            down_cooldown_s=a["down_cooldown_s"],
            drain_deadline_s=a["drain_deadline_s"],
            replica_percent=srv["replica_percent"],
            priority=srv["replica_priority"],
        )

    def _sync_replicas(self) -> None:
        """Mirror the cluster's replica-pod state into the virtual fleet:
        a bind activates the replica (capacity from its node's
        generation), a vanished pod (drain complete, drain-lease kill,
        flap eviction) requeues its in-flight cohorts. The cluster is
        the source of truth — the same contract the autoscaler's
        reconcile lives under — so the fluid model can never serve on a
        placement the scheduler does not hold."""
        for name in sorted(self.serve.replicas):
            try:
                pod = self.client.get_pod("default", name)
            except Exception:
                self.serve.replica_gone(name)
                self._pod_job.pop(name, None)
                if name in self._pending:
                    self._pending.remove(name)
                continue
            if pod.node_name:
                # the dealer's per-container assignment annotation names
                # the ACTUAL cards the replica holds — the tap must
                # reprice those, not a fabricated 0..n-1 (a sub-host
                # replica sharing a host with a sibling would otherwise
                # write its shortfall onto the co-resident's cards)
                ann = pod.annotations.get(
                    types.ANNOTATION_CONTAINER_FMT.format(name="decode"),
                    "",
                )
                chips = tuple(
                    int(c) for c in ann.split(",") if c.strip().isdigit()
                )
                self.serve.replica_bound(name, pod.node_name, chips)

    def _admit_replica_pod(self, pod: Pod) -> None:
        """Admission for a replica pod the autoscaler (or the static
        bootstrap) already created in the cluster: it enters the normal
        scheduling path as a single-pod job with no departure — the
        replica's lifetime belongs to the autoscaler, not the workload.
        With the batch admitter on, scale-ups park in the pending queue
        and the next batch_admit cycle places the whole step in ONE
        joint native solve (docs/batch-admission.md); without it they
        schedule pod-at-a-time inline."""
        job = Job(
            id=len(self.jobs), config="serve", arrival_t=self.now,
            lifetime_s=0.0, gang=None, pods=[pod],
            departure_scheduled=True,
        )
        self.jobs.append(job)
        self._pod_job[pod.name] = job
        self.report.pods["arrived"] += 1
        self.report.config_count("serve", "arrived")
        self.report.journal(self.now, f"serve-replica {pod.name}")
        if self.admitter is not None:
            self._pending.append(pod.name)
        elif not self._try_schedule(job, pod):
            self._pending.append(pod.name)

    def _on_serving_tick(self) -> None:
        """Advance the virtual serving fleet by one tick: sync replica
        state from the cluster, then arrivals -> decode -> completions ->
        admissions on the fluid model — which also feeds the serving tap
        (measured tok/s into the ThroughputModel) when feedback is on.
        The tick summary is journaled, so the whole serving trajectory
        is part of the determinism digest."""
        self._sync_replicas()
        s = self.serve.tick(self.now, self.scenario["serving"]["every_s"])
        self.report.journal(
            self.now,
            f"serve arrivals={s['arrivals']} queued={s['queued']} "
            f"active={s['active']} replicas={s['replicas']} "
            f"tokens={s['tokens']} completed={s['completed']}",
        )

    def _on_autoscale(self) -> None:
        """One autoscale cycle on virtual time: the REAL
        ReplicaAutoscaler decides against the fleet's demand snapshot;
        the sim routes its pod writes back through the event loop —
        scale-ups into the admission path, drains into the virtual
        fleet's no-new-work state, deletes into cohort requeue."""
        if self._degraded_skip("autoscale"):
            return
        self._sync_replicas()
        result = self.autoscaler.run_once(self.now, self.serve.signal())
        for kind, detail in result["actions"]:
            self.report.journal(self.now, f"{kind} {detail}")
        for name in result["draining"]:
            self.serve.drain(name)
        for name, _uid in result["deleted"]:
            self.serve.replica_gone(name)
            self._pod_job.pop(name, None)
            if name in self._pending:
                self._pending.remove(name)
        for pod in result["created"]:
            self.serve.register_pending(pod.name)
            self._admit_replica_pod(pod)

    def _on_serve_bootstrap(self) -> None:
        """Static fleet (autoscaler OFF — the A/B control): submit
        ``static_replicas`` replica pods once at t=0, byte-identical
        specs to the autoscaler's (shared make_replica_pod), so the
        ON-vs-OFF comparison is pure policy, not pod shape."""
        from nanotpu.serving.autoscale import make_replica_pod

        cfg = self._autoscale_config()
        for i in range(1, self.scenario["serving"]["static_replicas"] + 1):
            name = f"{cfg.pod_prefix}-{i}"
            pod = self.client.create_pod(
                make_replica_pod(name, cfg, uid=self._uid())
            )
            self.serve.register_pending(name)
            self._admit_replica_pod(pod)

    def _api_cut(self) -> bool:
        """True while the active's apiserver link is partitioned — the
        list-driven loops (resync, sweeper) cannot run then, exactly as
        a real partitioned process could not list."""
        return (
            self._active_tap is not None and self._active_tap.partitioned
        )

    def _degraded_skip(self, what: str) -> bool:
        """True (journaled) when the active's degraded monitor has the
        write loops paused — the sim-side analogue of the production
        loops' gate (docs/ha.md 'Degraded mode')."""
        monitor = self._active_monitor
        if monitor is not None and not monitor.allow_writes():
            self.report.journal(self.now, f"degraded-skip {what}")
            return True
        return False

    def _on_assume_sweep(self) -> None:
        if self._api_cut():
            return
        fence = self._active_fence
        expired = self.controller.sweep_assumed_once(
            self.scenario["assume_ttl_s"], now=self.now,
            epoch=(fence.epoch if fence is not None else None),
        )
        if expired:
            self.report.journal(self.now, f"assume-expire {expired}")

    def _on_metric_sync(self, payload: dict) -> None:
        self.faults.counts["metric_syncs"] += 1
        samples = []
        infos = self.dealer.debug_snapshot()["node_infos"]
        if self.scenario["metric_from_allocation"]:
            # usage mirrors the REAL per-card allocation (used fraction)
            # instead of seeded noise: the signal that calibrates the
            # throughput rater's contention EWMA end to end — a card two
            # fractional pods share reads hot, an idle card reads cold
            # (docs/scoring.md). Deterministic: derived from accounting,
            # no rng draw. Nodes the dealer does not track yet have no
            # known allocation and simply skip the tick.
            for name in self._live_node_names():
                info = infos.get(name)
                if info is None:
                    continue
                for chip, c in enumerate(info.chips.chips):
                    frac = (
                        c.percent_used / c.percent_total
                        if c.percent_total else 0.0
                    )
                    samples.append((name, chip, round(frac, 4)))
            delay = float(payload["delay"])
            if delay > 0:
                self.faults.counts["metric_samples_delayed"] += len(samples)
            self._push(self.now + delay, "metric_apply", samples)
            return
        for name in self._live_node_names():
            info = infos.get(name)
            if info is not None:
                n_chips = len(info.chips.chips)
            else:
                # dealer doesn't know the node yet (e.g. its ADDED event
                # was dropped): derive the chip count from capacity — a
                # constant would undersample 8-chip generations (v5e/v6e)
                node = self.client.get_node(name)
                n_chips = (
                    node.capacity(types.RESOURCE_TPU_PERCENT)
                    // types.PERCENT_PER_CHIP
                )
            for chip in range(n_chips):
                samples.append(
                    (name, chip, round(self.rng_metric.random() * 0.9, 4))
                )
        delay = float(payload["delay"])
        if delay > 0:
            self.faults.counts["metric_samples_delayed"] += len(samples)
        self._push(self.now + delay, "metric_apply", samples)

    def _on_metric_apply(self, samples: list) -> None:
        touched: set[str] = set()
        for node, chip, core in samples:
            # publish deferred: one snapshot publish per metric event, not
            # one full view clone per chip sample (same batching as
            # controller/metricsync.sync_once)
            self.dealer.update_chip_usage(
                node, chip, core=core, now=self.now, publish=False
            )
            touched.add(node)
        if touched:
            self.dealer.publish_usage(tuple(sorted(touched)))

    def _on_resync(self) -> None:
        if self._api_cut():
            return  # a partitioned process cannot list
        self.controller.resync_once()
        self.controller.drain_sync()

    def _on_sample(self) -> None:
        occ = self.dealer.occupancy()
        frag = fragmentation_of(self.dealer)
        self.report.sample(occ, frag)
        self.report.journal(
            self.now, f"sample occ={occ:.6f} frag={frag:.4f}"
        )
        # read-availability ledger (docs/read-plane.md): each sample is
        # a virtual client asking every follower for a read — within
        # the staleness bound answers, past it refuses (NotSynced).
        # Counters only; the journal line above stays byte-identical
        # with followers off.
        for fl in self.followers:
            if fl.coordinator.ready_to_serve(now=self.now):
                self._follower_reads_ok += 1
            else:
                self._follower_reads_refused += 1
                fl.coordinator.reads_refused += 1
        if self.shadows:
            # shadow-mode A/B (docs/policy-programs.md): each sampled
            # cycle the candidate scores the follower's own snapshot
            # against the serving policy's wire scores. The journal line
            # folds the divergence count into the determinism digest —
            # shadow-off scenarios skip the block and stay byte-identical.
            from nanotpu.allocator.core import Demand

            probe = Demand(
                percents=(25,), container_names=("shadow-probe",)
            )
            sampled = diverged = 0
            for i, ss in enumerate(self.shadows):
                if not self.followers[i].coordinator.ready_to_serve(
                    now=self.now
                ):
                    continue  # an unserving follower audits nothing
                out = ss.sample(probe)
                sampled += out["rows"]
                diverged += out["diverged"]
            self.report.journal(
                self.now, f"shadow rows={sampled} diverged={diverged}"
            )

    def _on_retry(self) -> None:
        if not self._pending:
            return
        still: list[str] = []
        for name in self._pending:
            job = self._pod_job.get(name)
            if job is None or job.departed:
                continue  # departed before it ever placed
            try:
                pod = self.client.get_pod("default", name)
            except Exception:
                continue
            self.report.pods["schedule_retries"] += 1
            if not self._try_schedule(job, pod):
                still.append(name)
        self._pending = still

    # -- invariants + settle -------------------------------------------------
    def _check(self, converged: bool) -> None:
        violations = check_invariants(
            self.dealer, self.client, converged=converged
        )
        self.report.invariant_checks += 1
        if violations:
            self.report.violations.extend(violations)
            self.report.journal(
                self.now,
                f"VIOLATIONS {len(violations)} "
                + ",".join(sorted({v['kind'] for v in violations})),
            )
            if self.flight is not None:
                # the flight recorder's third trigger: a broken
                # invariant IS the incident, and the bundle captures the
                # state that broke it (deterministic: violations are)
                self.flight.dump("invariant_violation", now=self.now)

    def _deterministic_resilience(self) -> dict:
        """The resilience-counter snapshot MINUS the Event recorder's
        share: Events post from a background thread whose interleaving is
        wall-clock, so their counters (events_* scalars, the "events"
        write target) stay off the deterministic report — everything else
        is bumped on the sim thread and is part of the contract."""
        out: dict = {}
        for key, val in self.resilience.snapshot().items():
            if key.startswith("events_"):
                continue
            if isinstance(val, dict):
                val = {t: c for t, c in sorted(val.items()) if t != "events"}
            out[key] = val
        return out

    def _settle(self, horizon: float) -> None:
        """Stop the fault tap, deliver everything in flight, reconcile,
        and run the convergence invariants + final sample."""
        self.now = horizon
        self.faults.armed = False
        self.faults.brownout_active = False  # windows are horizon-clipped
        self.faults.thrash_active = False
        # heal any window still open at the horizon: convergence is
        # only checkable with every link up
        for side_tap in (
            self._active_tap,
            self.standby.tap if self.standby is not None else None,
            *(fl.tap for fl in self.followers),
        ):
            if side_tap is not None:
                side_tap.partitioned = False
                side_tap.gray = False
        self._stream_cut = False
        self._partition_state = None
        self._gray_tap = None
        self._pump_informers()
        self.controller.resync_once()
        self.controller.drain_sync()
        self._pump_informers()
        self._check(converged=True)
        self.report.final_occupancy = self.dealer.occupancy()
        self.report.final_fragmentation = fragmentation_of(self.dealer)
        self.report.journal(
            horizon,
            f"settle occ={self.report.final_occupancy:.6f} "
            f"frag={self.report.final_fragmentation:.4f}",
        )
        if self.scenario["throughput_report"]:
            # modeled aggregate throughput of the pods still bound at the
            # horizon vs the oracle bound, ONE fixed default model for
            # every policy (so binpack-vs-throughput runs of the same
            # scenario compare on identical units — the het-throughput
            # certification, docs/scoring.md). Part of the journal, so
            # part of the determinism digest.
            from nanotpu.allocator.throughput import modeled_aggregate

            agg = modeled_aggregate(
                self.dealer.debug_snapshot()["node_infos"],
                self.dealer.tracked_pods(),
            )
            self.report.throughput = agg
            self.report.journal(
                horizon,
                f"throughput agg={agg['aggregate']:.4f} "
                f"oracle={agg['oracle']:.4f} "
                f"loss={agg['loss_vs_oracle_pct']:.2f}%",
            )
        if self.timeline is not None:
            # deterministic telemetry section: every tick is virtual-time
            # data sampled on the sim thread, so the ring digest AND the
            # newest flight bundle's byte digest join the determinism
            # contract (docs/observability.md)
            breaches = {
                name: state["breaches"]
                for name, state in self.watchdog.status().items()
            }
            self.report.timeline = {
                "ticks": self.timeline.latest_tick,
                "digest": self.timeline.digest(),
                "breaches": breaches,
                "bundles": self.flight.bundles,
                "bundle_digest": self.flight.digest(),
            }
            self.report.journal(
                horizon,
                f"telemetry ticks={self.timeline.latest_tick} "
                f"breaches={sum(breaches.values())} "
                f"bundles={self.flight.bundles}",
            )
        if self.exporter is not None:
            # deterministic export section: records are framed only on
            # the sim thread with virtual-time payloads, so the stream
            # sha256 is byte-reproducible and joins --check-determinism
            status = self.exporter.status()
            self.report.export = status
            self.report.journal(
                horizon,
                f"export records={status['records']} "
                f"bytes={status['bytes']} digest={status['digest']}",
            )
        if self.plane is not None:
            # deterministic recovery section: counters are bumped only on
            # the sim thread (run_once / note_bound), so they are part of
            # the determinism contract like the resilience slice
            status = self.plane.status()
            counters = self.plane.counters.snapshot()
            self.report.recovery = {
                "counters": counters,
                "holes_final": status["holes"],
                "leases_final": status["leases"],
            }
            self.report.journal(
                horizon,
                f"recovery preempted={counters['preempted_pods']} "
                f"migrated={counters['migrated_pods']} "
                f"backfilled={counters['backfill_leases']} "
                f"lease_expired={counters['backfill_lease_expiries']}",
            )
        if self.scenario["ha"]["enabled"]:
            # deterministic HA section (docs/ha.md): the standby drains
            # its remaining lag at settle and must then agree with the
            # durable annotations exactly — the "converged dealer-vs-
            # cluster equality" half of the failover certification, for
            # the replica that did NOT serve the traffic
            sb = self.standby
            sb_drift = 0.0
            if sb is not None:
                sb.coordinator.lag_events = 0
                self._pump_standby()
                if self.scenario["ha"]["lease"]["enabled"]:
                    # a deposed-in-place leader's dirty window holds
                    # events from the handover gap (no delta will ever
                    # cover them) — the standby-side reconcile drains
                    # them so the convergence check judges real state,
                    # not the gap (docs/ha.md "Split brain")
                    sb.coordinator.reconcile_dirty()
                sb_occ = sb.dealer.occupancy()
                sb_truth = ground_truth_occupancy(sb.dealer, self.client)
                sb_drift = abs(sb_occ - sb_truth)
                if sb_drift > 1e-9:
                    self.report.violations.append({
                        "kind": "standby_occupancy_drift",
                        "detail": (
                            f"settled standby holds occupancy "
                            f"{sb_occ:.6f} but live annotations say "
                            f"{sb_truth:.6f}"
                        ),
                    })
            self.report.ha = {
                "crashes": self.faults.counts["scheduler_crashes"],
                "promotions": self._ha_promotions,
                "reconciled_pods": self._ha_reconciled,
                "applied_deltas": (
                    sb.coordinator.applied_deltas if sb is not None else 0
                ),
                "emitted_deltas": (
                    self.dealer.ha.seq if self.dealer.ha is not None else 0
                ),
                "standby_drift_pct": round(100 * sb_drift, 6),
            }
            self.report.journal(
                horizon,
                f"ha crashes={self.report.ha['crashes']} "
                f"promotions={self._ha_promotions} "
                f"reconciled={self._ha_reconciled} "
                f"applied={self.report.ha['applied_deltas']} "
                f"standby_drift={sb_drift:.6f}",
            )
            if self.followers:
                # the read-plane certification (docs/read-plane.md):
                # every follower drains its remaining lag at settle and
                # must then agree with the durable annotations exactly —
                # byte-for-byte the same convergence bar the standby
                # meets, held by N replicas at once. Block and journal
                # line appear only with followers on, so every existing
                # digest stays byte-identical.
                fl_drift = 0.0
                for fl in self.followers:
                    fl.coordinator.lag_events = 0
                self._pump_standby()
                for i, fl in enumerate(self.followers):
                    fl_occ = fl.dealer.occupancy()
                    fl_truth = ground_truth_occupancy(
                        fl.dealer, self.client
                    )
                    drift_i = abs(fl_occ - fl_truth)
                    fl_drift = max(fl_drift, drift_i)
                    if drift_i > 1e-9:
                        self.report.violations.append({
                            "kind": "follower_occupancy_drift",
                            "detail": (
                                f"settled follower {i} holds occupancy "
                                f"{fl_occ:.6f} but live annotations "
                                f"say {fl_truth:.6f}"
                            ),
                        })
                self.report.ha["followers"] = {
                    "count": len(self.followers),
                    "applied_deltas": sum(
                        fl.coordinator.applied_deltas
                        for fl in self.followers
                    ),
                    "reads_ok": self._follower_reads_ok,
                    "reads_refused": self._follower_reads_refused,
                    "max_drift_pct": round(100 * fl_drift, 6),
                }
                self.report.journal(
                    horizon,
                    f"followers n={len(self.followers)} "
                    f"reads_ok={self._follower_reads_ok} "
                    f"reads_refused={self._follower_reads_refused} "
                    f"max_drift={fl_drift:.6f}",
                )
            if self.shadows:
                self._settle_shadow(horizon)
            if self.scenario["ha"]["lease"]["enabled"]:
                self._settle_lease(horizon)
        # deterministic serving section (docs/serving-loop.md)
        self._settle_serving(horizon)

    def _settle_shadow(self, horizon: float) -> None:
        """The shadow-mode certification block (docs/policy-programs.md):
        aggregate candidate-vs-serving divergence evidence across the
        follower fleet into the deterministic ``shadow`` report section.
        ``records_digest`` hashes every retained divergence record, so
        two runs that happen to agree on the counters but disagree on a
        single ledger byte still certify differently — the same witness
        discipline as the journal digest. Shadow-off scenarios never
        reach this and every existing section stays byte-identical."""
        import hashlib
        import json

        cycles = rows = divergences = 0
        max_delta = 0
        agg = hashlib.sha256()
        for ss in self.shadows:
            st = ss.status()
            cycles += st["cycles"]
            rows += st["rows"]
            divergences += st["divergences"]
            max_delta = max(max_delta, st["max_abs_delta"])
            for rec in ss.dump():
                agg.update(json.dumps(rec, sort_keys=True).encode())
        candidate = self.shadows[0].candidate
        self.report.shadow = {
            "program": candidate.program_name,
            "fingerprint": candidate.fingerprint,
            "followers": len(self.shadows),
            "cycles": cycles,
            "rows": rows,
            "divergences": divergences,
            "max_abs_delta": max_delta,
            "records_digest": "sha256:" + agg.hexdigest(),
        }
        self.report.journal(
            horizon,
            f"shadow settle program={candidate.program_name} "
            f"divergences={divergences} max_delta={max_delta}",
        )

    def _settle_lease(self, horizon: float) -> None:
        """The split-brain certification block (docs/ha.md): fencing,
        epoch, degraded-mode, and promotion-storm accounting for BOTH
        live sides, plus the promotion bound assert. Lease-mode
        scenarios only — crash-mode `ha` sections stay byte-identical."""
        sb = self.standby
        sides = [
            (self._active_lease, self._active_fence, self._active_monitor,
             self.ha_active, self.controller),
        ]
        if sb is not None:
            sides.append(
                (sb.lease, sb.fence, sb.monitor, sb.coordinator,
                 sb.controller)
            )
        fence_rejections = sum(
            f.rejections for _, f, _m, _c, _ct in sides if f is not None
        )
        steals = sum(
            le.steals for le, _f, _m, _c, _ct in sides if le is not None
        )
        epoch_final = max(
            (le.epoch for le, _f, _m, _c, _ct in sides
             if le is not None), default=0,
        )
        suspect = sum(c.suspect_deltas for _l, _f, _m, c, _ct in sides)
        heals = sum(ct.epoch_heals for _l, _f, _m, _c, ct in sides)
        verify_failures = sum(
            c.verify_failures for _l, _f, _m, c, _ct in sides
        )
        lease_block = {
            "epoch_final": epoch_final,
            "steals": steals,
            "fence_rejections": fence_rejections,
            "suspect_deltas": suspect,
            "epoch_heals": heals,
            "verify_failures": verify_failures,
        }
        monitors = [m for _l, _f, m, _c, _ct in sides if m is not None]
        if monitors:
            lease_block["degraded"] = {
                "entries": sum(m.entries for m in monitors),
                "exits": sum(m.exits for m in monitors),
            }
        self.report.ha["lease"] = lease_block
        bound = self.scenario["ha"]["promotion_bound"]
        if bound > 0 and self._ha_promotions > bound:
            self.report.violations.append({
                "kind": "promotion_storm",
                "detail": (
                    f"{self._ha_promotions} promotions exceed the "
                    f"scenario bound of {bound} (steal hysteresis / "
                    "backoff failed to contain the thrash)"
                ),
            })
        # post-promotion verifies run MID-RUN, where a dropped event
        # awaiting resync is a legitimate transient (verify_failures is
        # reported, not asserted). The CONVERGED verify here is the
        # certification: with everything healed and resynced, the deep
        # check must match to the byte.
        from nanotpu.ha.verify import verify_state

        final_verify = verify_state(self.dealer, self.client.list_pods())
        lease_block["final_verify_match"] = bool(final_verify["match"])
        if not final_verify["match"]:
            self.report.violations.append({
                "kind": "verify_state_mismatch",
                "detail": (
                    "converged verify_state found dealer-vs-truth "
                    f"divergence: {final_verify}"
                ),
            })
        self.report.journal(
            horizon,
            f"ha-lease epoch={epoch_final} steals={steals} "
            f"fenced={fence_rejections} suspect={suspect} "
            f"epoch_heals={heals} verify_failures={verify_failures}",
        )

    def _settle_serving(self, horizon: float) -> None:
        if self.serve is not None:
            # deterministic serving section (docs/serving-loop.md): the
            # certification metrics — tokens/s-per-chip, TTFT
            # percentiles, replica trajectory, feedback sample counts —
            # all derived from virtual time and the dedicated rng_serve
            # stream, so the section (and its journal line) joins the
            # determinism contract like recovery/timeline
            self._sync_replicas()
            summary = self.serve.summary()
            if self.autoscaler is not None:
                a = self.autoscaler.status()
                summary["autoscale"] = {
                    k: a[k] for k in (
                        "scale_ups", "scale_downs", "drains_started",
                        "drains_completed", "drain_kills",
                    )
                }
            self.report.serving = summary
            self.report.journal(
                horizon,
                f"serving tok_s_per_chip={summary['tok_s_per_chip']} "
                f"ttft_p99_ms={summary['ttft_ms']['p99']} "
                f"completed={summary['requests']['completed']} "
                f"replicas={summary['replicas']['final']} "
                f"feedback_samples={summary['feedback']['samples']}",
            )


def run_scenario(scenario: dict, seed: int = 0,
                 include_timing: bool = False) -> dict:
    """One fresh simulator run (the programmatic entry point)."""
    return Simulator(scenario, seed).run(include_timing=include_timing)
