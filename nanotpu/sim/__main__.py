"""CLI: ``python -m nanotpu.sim --scenario examples/sim/smoke.json --seed 0``.

stdout carries exactly one JSON report (canonical serialization, sorted
keys). Without ``--timing`` the report is byte-identical across runs of
the same (scenario, seed) — the determinism contract CI leans on;
``--timing`` adds wall-clock Filter/Prioritize/Bind percentiles (real
time, not reproducible). A human summary — including the wall-clock
p50/p99 either way — goes to stderr.

Exit codes: 0 healthy; 1 invariant violations (or determinism breach
under ``--check-determinism``); 2 bad usage/scenario.
"""

from __future__ import annotations

import argparse
import json
import sys

from nanotpu.sim.core import Simulator
from nanotpu.sim.report import render, strip_timing
from nanotpu.sim.scenario import load_scenario


def _summary_line(report: dict, timing: dict) -> str:
    occ = report["occupancy_pct"]
    frag = report["fragmentation"]
    inv = report["invariants"]
    lat = timing.get("latency_ms", {})

    def p(verb, q):
        s = lat.get(verb) or {}
        v = s.get(q)
        return f"{v:.3f}" if isinstance(v, (int, float)) else "n/a"

    return (
        f"sim {report['scenario']!r} seed={report['seed']}: "
        f"occupancy mean {occ['mean']}% peak {occ['peak']}% "
        f"final {occ['final']}%; fragmentation mean {frag['mean']}; "
        f"{report['pods']['bound']}/{report['pods']['arrived']} pods bound; "
        f"filter p50/p99 {p('filter', 'p50')}/{p('filter', 'p99')} ms, "
        f"bind p50/p99 {p('bind', 'p50')}/{p('bind', 'p99')} ms; "
        f"invariants: {inv['violations']} violations / {inv['checks']} checks"
    )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m nanotpu.sim",
        description="deterministic cluster simulator (docs/simulation.md)",
    )
    parser.add_argument("--scenario", required=True, help="scenario JSON path")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--timing", action="store_true",
        help="embed wall-clock verb latencies in the report "
        "(breaks byte-reproducibility of stdout, by design)",
    )
    parser.add_argument(
        "--check-determinism", action="store_true",
        help="run the scenario twice and fail unless the deterministic "
        "reports are byte-identical",
    )
    parser.add_argument(
        "--horizon-s", type=float, default=None,
        help="override the scenario horizon (shorter smoke runs)",
    )
    args = parser.parse_args(argv)

    try:
        scenario = load_scenario(args.scenario)
        if args.horizon_s is not None:
            if args.horizon_s <= 0:
                raise ValueError(
                    f"--horizon-s must be > 0, got {args.horizon_s}"
                )
            scenario["horizon_s"] = float(args.horizon_s)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    # timing is always COLLECTED (the stderr summary wants it); it lands
    # in stdout's JSON only behind --timing
    report = Simulator(scenario, args.seed).run(include_timing=True)
    timing = report.get("timing", {})
    out = report if args.timing else strip_timing(report)

    rc = 0
    if args.check_determinism:
        again = strip_timing(
            Simulator(scenario, args.seed).run(include_timing=False)
        )
        if render(strip_timing(report)) != render(again):
            print(
                "DETERMINISM BREACH: two runs of the same (scenario, seed) "
                "diverged — diff the digests:\n"
                f"  run 1: {report['digest']}\n  run 2: {again['digest']}",
                file=sys.stderr,
            )
            rc = 1
        else:
            print(
                f"determinism check passed ({report['digest']})",
                file=sys.stderr,
            )
    print(render(out))
    print(_summary_line(report, timing), file=sys.stderr)
    if report["invariants"]["violations"]:
        for v in report["invariants"]["first"]:
            print(f"violation[{v['kind']}]: {v['detail']}", file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
