"""Deterministic cluster simulator + fault-injection harness.

Placement-policy work lives or dies on trace-driven simulation (Tesserae,
arxiv 2508.04953; Gavel, arxiv 2008.09213 — both evaluate every policy in a
cluster simulator before touching hardware). This package is that substrate
for nanotpu: a seeded discrete-event simulator that drives the REAL
:class:`~nanotpu.dealer.Dealer`, the real scheduler verbs
(:mod:`nanotpu.scheduler.verbs`), and the real
:class:`~nanotpu.controller.controller.Controller` — no re-implementation of
allocation logic — against synthetic fleets (a single v4 host up to
v5p-512 torus pools), Poisson or trace-file pod arrivals covering all five
BASELINE configs, pod lifetimes/departures, and a fault-injection layer
(node flap, dropped/duplicate informer events, bind-API failures, delayed
metric sync, agent restart).

Everything is single-threaded and seeded: two runs of the same scenario and
seed produce byte-identical reports (see docs/simulation.md for the
determinism contract), so a policy regression reproduces from one JSON
trace. An invariant checker (no chip oversubscription, no orphaned
reservations, annotations round-trip through the :mod:`nanotpu.types`
codec) runs after every event.

Entry point::

    python -m nanotpu.sim --scenario examples/sim/smoke.json --seed 0
"""

from nanotpu.sim.core import Simulator, run_scenario
from nanotpu.sim.scenario import load_scenario

__all__ = ["Simulator", "run_scenario", "load_scenario"]
