"""HTTP front end for the serving engine (POST /v1/generate).

Reuses the scheduler's hand-rolled HTTP/1.1 handler (``routes/server.py``
``serve()`` takes any object with ``dispatch``); per-request handler
threads block on the engine future, the engine batches across them.

Run:  python -m nanotpu.serving.server --preset tiny --port 8100
      curl -d '{"tokens": [1,2,3], "max_new_tokens": 8}' localhost:8100/v1/generate
      curl -N -d '{"tokens": [1,2,3], "max_new_tokens": 64, "stream": true}' \
           localhost:8100/v1/generate     # SSE token streaming
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import threading
import traceback

from nanotpu.metrics.registry import Registry
from nanotpu.serving.engine import Engine

log = logging.getLogger("nanotpu.serving.http")

#: TTFT/latency buckets (seconds) tuned for decode: 5ms to 60s.
SERVE_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0
)


class ServingAPI:
    """dispatch() in the SchedulerAPI shape so routes.server.serve() and the
    tests' socketless dispatch both work."""

    def __init__(self, engine: Engine, registry: Registry | None = None,
                 request_timeout_s: float = 600.0):
        self.engine = engine
        self.registry = registry or Registry()
        self.request_timeout_s = request_timeout_s
        r = self.registry
        self.req_total = r.counter(
            "nanotpu_serve_requests_total", "Generation requests"
        )
        self.tok_total = r.counter(
            "nanotpu_serve_tokens_total", "Generated tokens"
        )
        self.ttft = r.histogram(
            "nanotpu_serve_ttft_seconds", "Time to first token",
            buckets=SERVE_BUCKETS,
        )
        self.latency = r.histogram(
            "nanotpu_serve_latency_seconds", "Whole-request latency",
            buckets=SERVE_BUCKETS,
        )
        self.active = r.gauge(
            "nanotpu_serve_active_slots", "Requests currently decoding"
        )
        self.active.set_function(
            lambda: sum(1 for x in engine._slot_req if x is not None)
        )
        self.moe_dropped = r.gauge(
            "nanotpu_serve_moe_prefill_dropped_tokens_total",
            "MoE tokens dropped by expert capacity during admission "
            "prefills (monotone; decode routes at full capacity and "
            "cannot drop)",
        )
        self.moe_dropped.set_function(
            lambda: engine.moe_prefill_dropped_total
        )

    def dispatch(self, method: str, path: str, body: bytes,
                 trace_ctx: str = "") -> tuple[int, str, str]:
        # trace_ctx (the X-Nanotpu-Trace header) is accepted for handler
        # parity with SchedulerAPI and ignored: serving requests are not
        # part of the scheduler's cross-process story
        try:
            if method == "POST" and path == "/v1/generate":
                return self._generate(body)
            if method == "GET" and path == "/v1/stats":
                return 200, "application/json", json.dumps(self.engine.stats())
            if method == "GET" and path == "/healthz":
                return 200, "text/plain", "ok"
            if method == "GET" and path == "/metrics":
                return 200, "text/plain; version=0.0.4", self.registry.render()
            return 404, "application/json", json.dumps(
                {"error": f"no route {path}"}
            )
        except Exception:
            log.exception("unhandled error on %s %s", method, path)
            return 500, "application/json", json.dumps(
                {"error": traceback.format_exc(limit=3)}
            )

    def _generate(self, body: bytes) -> tuple[int, str, str]:
        try:
            args = json.loads(body or b"{}")
        except json.JSONDecodeError as e:
            return 400, "application/json", json.dumps(
                {"error": f"malformed JSON: {e}"}
            )
        tokens = args.get("tokens")
        if not isinstance(tokens, list) or not all(
            isinstance(t, int) for t in tokens
        ):
            return 400, "application/json", json.dumps(
                {"error": "'tokens' must be a list of ints"}
            )
        max_new = args.get("max_new_tokens", 16)
        temperature = float(args.get("temperature", 0.0))
        if not isinstance(max_new, int) or max_new < 1:
            return 400, "application/json", json.dumps(
                {"error": "'max_new_tokens' must be a positive int"}
            )
        req = self.engine.submit(tokens, max_new, temperature)
        self.req_total.inc()
        if args.get("stream"):
            return 200, "text/event-stream", self._sse_events(req)
        if not req.wait(self.request_timeout_s):
            return 500, "application/json", json.dumps(
                {"error": "request timed out"}
            )
        if req.error:
            return 400, "application/json", json.dumps({"error": req.error})
        self.tok_total.inc(len(req.out))
        stats = self._completion_stats(req)
        stats["tokens"] = req.out
        return 200, "application/json", json.dumps(stats)

    def _completion_stats(self, req) -> dict:
        """Observe the latency histograms and build the shared completion
        fields (the JSON and SSE paths must not drift)."""
        if req.ttft_s is not None:
            self.ttft.observe(req.ttft_s)
        if req.latency_s is not None:
            self.latency.observe(req.latency_s)
        return {
            "id": req.id,
            "ttft_ms": (
                round(req.ttft_s * 1e3, 2) if req.ttft_s is not None else None
            ),
            "latency_ms": (
                round(req.latency_s * 1e3, 2)
                if req.latency_s is not None else None
            ),
        }

    def _sse_events(self, req):
        """SSE generator: one ``data:`` event per decode-chunk batch of
        tokens (the engine's natural streaming boundary), then a final
        event carrying completion stats — TTFT is user-visible because the
        first event leaves as soon as the prefill's token lands, not when
        the whole generation finishes. ({"stream": true} on /v1/generate.)"""
        try:
            for batch in req.stream(self.request_timeout_s):
                self.tok_total.inc(len(batch))
                yield f"data: {json.dumps({'id': req.id, 'tokens': batch})}\n\n"
        except TimeoutError:
            yield f"data: {json.dumps({'id': req.id, 'error': 'request timed out'})}\n\n"
            return
        if req.error:
            yield f"data: {json.dumps({'id': req.id, 'error': req.error})}\n\n"
            return
        stats = self._completion_stats(req)
        stats.update(done=True, n_tokens=len(req.out))
        yield f"data: {json.dumps(stats)}\n\n"


def build_engine(preset: str, slots: int, max_len: int, quantize: bool,
                 attn: str = "auto", eos_id: int = -1,
                 kv_int8: bool = False) -> Engine:
    import jax

    from nanotpu.models.llama import LlamaConfig, init_params

    if preset == "flagship":
        cfg = LlamaConfig(
            vocab_size=32768, dim=1024, n_layers=12, n_heads=16,
            n_kv_heads=8, ffn_dim=2816, max_seq_len=max_len,
            attn_impl=("flash" if attn == "auto" else attn),
        )
    elif preset == "tiny":
        import dataclasses

        cfg = dataclasses.replace(LlamaConfig.tiny(), max_seq_len=max_len)
    else:
        raise SystemExit(f"unknown preset {preset}")
    params = init_params(jax.random.PRNGKey(0), cfg)
    if quantize:
        from nanotpu.models.quant import quantize_params

        params = quantize_params(params)
    return Engine(params, cfg, slots=slots, max_len=max_len, eos_id=eos_id,
                  kv_int8=kv_int8)


def main(argv=None) -> None:
    p = argparse.ArgumentParser("nanotpu-serve")
    p.add_argument("--port", type=int, default=8100)
    p.add_argument("--preset", default="flagship")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--max-len", type=int, default=2048)
    p.add_argument("--int8", action="store_true", help="weight-only int8")
    p.add_argument("--kv-int8", action="store_true",
                   help="int8 KV cache (halves decode HBM reads)")
    p.add_argument("--eos-id", type=int, default=-1)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    engine = build_engine(
        args.preset, args.slots, args.max_len, args.int8, eos_id=args.eos_id,
        kv_int8=args.kv_int8,
    )
    api = ServingAPI(engine)
    from nanotpu.routes.server import serve

    server = serve(api, args.port)
    log.info("serving on :%d (%d slots, max_len %d)", args.port, args.slots,
             args.max_len)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.shutdown()
    engine.stop()


if __name__ == "__main__":
    main()
