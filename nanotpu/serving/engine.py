"""Continuous-batching serving engine over the KV-cache decode path.

The reference has no serving stack at all (it schedules pods; SURVEY §2
"absent in reference"), but BASELINE's fractional-inference story
(``examples/fractional-inference.yaml``) needs a server for the scheduled
pod to run — this is it, designed TPU-first:

* **Slot-based batch, static shapes.** The cache is [SLOTS, max_len] per
  layer, allocated once. A request is admitted into a free slot at prefill
  and evicted at eos/max-new; the decode step always runs the full slot
  batch (inactive rows compute garbage that is never read) so XLA compiles
  exactly one decode program for the lifetime of the engine.
* **Per-row cache lengths.** Unlike :class:`nanotpu.models.generate.KVCache`
  (one scalar ``length``), every slot has its own frontier: rope positions,
  cache writes, and attention masks are all per-row, which is what lets
  requests at different depths share one step (the continuous-batching
  core). Writes use a vmapped dynamic-slice (lowers to scatter at S=1).
* **Sampling on device.** The step samples inside the jit (per-row
  temperature; engine-wide top-k/top-p) and returns only the [SLOTS] token
  vector — one tiny transfer per step, no logits round-trip.
* **Prefill via the flash path.** Admission reuses
  :func:`nanotpu.models.generate.prefill` (cache-empty prefills route
  through the Pallas flash kernel when ``attn_impl="flash"``), padded to a
  small set of bucket lengths so compile count stays bounded; the row is
  then inserted into the slot cache with a donated dynamic-slice (no copy
  of the other slots).
* **int8 composes for free**: ``linear`` dispatches on QArray leaves, so an
  engine built from ``quantize_params(params)`` runs weight-only int8.

MoE serving routes decode steps at **full expert capacity** (C = SLOTS *
top_k — tiny at S=1): no token is ever dropped, so each slot's routing is
independent of its batch-mates at any ``capacity_factor``. Prefill keeps
Switch capacity semantics with C computed over the padded bucket length —
looser than an unpadded run (nearly drop-free for prompts much shorter
than their bucket), the memory-bounded choice for long prompts where a
drop-free dispatch tensor would be O(T^2).
"""

from __future__ import annotations

import itertools
import logging
import math
import threading
import time
from collections import deque
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from nanotpu.models.generate import (
    NEG_INF,
    apply_top_k,
    apply_top_p,
    prefill,
)
from nanotpu.models.llama import (
    apply_rope,
    embed_lookup,
    linear,
    mlp,
    rms_norm,
    rope_freqs,
)

log = logging.getLogger("nanotpu.serving")

#: Prompt lengths are padded up to one of these before prefill so the
#: number of compiled prefill programs is bounded (one per bucket).
DEFAULT_BUCKETS = (32, 64, 128, 256, 512, 1024, 2048)


class SlotCache(NamedTuple):
    """Per-layer k/v [SLOTS, max_len, KV, hd] + per-row valid lengths."""

    k: tuple
    v: tuple
    lengths: jax.Array  # [SLOTS] int32

    @staticmethod
    def create(cfg, slots: int, max_len: int, dtype=None) -> "SlotCache":
        shape = (slots, max_len, cfg.n_kv_heads, cfg.head_dim)
        dt = dtype or jnp.dtype(cfg.dtype)
        return SlotCache(
            k=tuple(jnp.zeros(shape, dt) for _ in range(cfg.n_layers)),
            v=tuple(jnp.zeros(shape, dt) for _ in range(cfg.n_layers)),
            lengths=jnp.zeros((slots,), jnp.int32),
        )


class SlotCache8(NamedTuple):
    """int8 variant of :class:`SlotCache`: k/v stored int8 with one f32
    scale per (row, position, kv-head). Decode is KV-cache-bandwidth
    bound, so halving the bytes per element (bf16 -> int8 + 1/hd scale)
    roughly doubles the attention-read ceiling at long context; the
    dequantize is an elementwise producer XLA fuses into the attention
    dot, so no bf16 copy of the cache ever materializes in HBM."""

    k: tuple  # per-layer int8 [SLOTS, max_len, KV, hd]
    v: tuple
    k_scale: tuple  # per-layer f32 [SLOTS, max_len, KV]
    v_scale: tuple
    lengths: jax.Array  # [SLOTS] int32

    @staticmethod
    def create(cfg, slots: int, max_len: int) -> "SlotCache8":
        shape = (slots, max_len, cfg.n_kv_heads, cfg.head_dim)
        sshape = shape[:-1]
        L = cfg.n_layers
        return SlotCache8(
            k=tuple(jnp.zeros(shape, jnp.int8) for _ in range(L)),
            v=tuple(jnp.zeros(shape, jnp.int8) for _ in range(L)),
            k_scale=tuple(jnp.zeros(sshape, jnp.float32) for _ in range(L)),
            v_scale=tuple(jnp.zeros(sshape, jnp.float32) for _ in range(L)),
            lengths=jnp.zeros((slots,), jnp.int32),
        )


def quantize_kv(x):
    """x [..., hd] -> (int8 values, f32 scale [...]); symmetric per-vector
    absmax quantization (the grain decode reads at: one (position, kv-head)
    vector per cache entry)."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale, dtype):
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def _attend_rows(q, k_cache, v_cache, base):
    """q [B,S,H,hd] against cache [B,T,KV,hd]; row b's s-th new token sits
    at position base[b]+s and attends positions <= itself (causal within
    the fed block, per-row frontier into the cache). GQA stays unexpanded
    (broadcast inside the einsum). S=1 is the plain decode step."""
    B, S, H, hd = q.shape
    KV, T = k_cache.shape[2], k_cache.shape[1]
    rep = H // KV
    qg = q.reshape(B, S, KV, rep, hd)
    logits = jnp.einsum("bsgrd,btgd->bgrst", qg, k_cache).astype(jnp.float32)
    logits = logits * (1.0 / math.sqrt(hd))
    frontier = base[:, None] + jnp.arange(S)[None, :] + 1  # [B, S]
    mask = jnp.arange(T)[None, None, :] < frontier[:, :, None]  # [B, S, T]
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bgrst,btgd->bsgrd", probs, v_cache)
    return out.reshape(B, S, H, hd)


def _write_rows(cache_arr, new, offsets):
    """Write new [B, 1, ...] into cache_arr [B, T, ...] at per-row offsets
    (vmapped dynamic-slice: each slot's frontier differs — the thing the
    single-scalar KVCache cannot express). Rank-generic: serves both the
    [T, KV, hd] value caches and the [T, KV] scale planes.

    INVARIANT (never-read-after-freeze): when a row's offset is within
    S-1 of max_len — only possible for FROZEN rows, since active rows are
    admitted with >= S positions of slack — dynamic_update_slice clamps
    the start backward and this write CORRUPTS the row's still-valid
    cache prefix. That is safe solely because frozen rows are evicted and
    never attended again. Any future prefix-reuse / slot-resume feature
    must mask frozen rows' writes instead of relying on the clamp."""

    def one(row, val, off):
        start = (off,) + (jnp.int32(0),) * (row.ndim - 1)
        return jax.lax.dynamic_update_slice(row, val.astype(row.dtype), start)

    return jax.vmap(one)(cache_arr, new, offsets)


def _cache_update_and_views(cache, i, k, v, lengths, dtype):
    """Write this step's k/v into layer i of either cache flavor; returns
    (storage leaves to carry, dequantized full-cache views to attend)."""
    if isinstance(cache, SlotCache8):
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        k_store = _write_rows(cache.k[i], kq, lengths)
        ks_store = _write_rows(cache.k_scale[i], ks, lengths)
        v_store = _write_rows(cache.v[i], vq, lengths)
        vs_store = _write_rows(cache.v_scale[i], vs, lengths)
        return (
            (k_store, v_store, ks_store, vs_store),
            dequantize_kv(k_store, ks_store, dtype),
            dequantize_kv(v_store, vs_store, dtype),
        )
    k_store = _write_rows(cache.k[i], k, lengths)
    v_store = _write_rows(cache.v[i], v, lengths)
    return (k_store, v_store, None, None), k_store, v_store


def _rows_forward(params, cfg, cache: "SlotCache | SlotCache8", tokens,
                  advance, head: bool = True):
    """Forward ``tokens [B, S]`` fed at each row's frontier; returns
    (logits [B, S, V] fp32, cache with per-row lengths advanced by
    ``advance [B]``). The shared body of the plain decode step (S=1,
    advance=active) and the speculative draft/verify steps (S=K+1,
    advance=per-row acceptance): k/v for all S positions are written at
    each row's current frontier regardless of ``advance`` — positions
    beyond the advanced length are stale and get overwritten by the next
    write at that row's length, exactly the speculative rollback
    semantics of nanotpu.models.speculative. Frozen rows (advance 0 via
    the caller's active mask) still WRITE S positions at their frontier —
    near max_len the write clamps backward over valid prefix; see the
    never-read-after-freeze invariant on _write_rows."""
    B, S = tokens.shape
    positions = cache.lengths[:, None] + jnp.arange(S)[None, :]  # [B,S]
    cos, sin = rope_freqs(cfg, positions)
    x = embed_lookup(params["embed"], tokens, jnp.dtype(cfg.dtype))
    H, KV, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    ks, vs, kss, vss = [], [], [], []
    for i, layer in enumerate(params["layers"]):
        attn = layer["attn"]
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = linear(h, attn["wq"]).reshape(B, S, H, hd)
        k = linear(h, attn["wk"]).reshape(B, S, KV, hd)
        v = linear(h, attn["wv"]).reshape(B, S, KV, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        stored, k_view, v_view = _cache_update_and_views(
            cache, i, k, v, cache.lengths, x.dtype
        )
        out = _attend_rows(q, k_view, v_view, cache.lengths)
        x = x + linear(out.reshape(B, S, H * hd), attn["wo"])
        if "moe" in layer:
            from nanotpu.models.mixtral import moe_block

            # full capacity at decode shapes: every (slot, position)
            # routes independently of its batch-mates (C = B*S*top_k is
            # tiny — S is 1 or the speculation depth K+1)
            ffn_out, _aux = moe_block(
                layer["moe"], rms_norm(x, layer["moe_norm"], cfg.norm_eps),
                cfg, full_capacity=True,
            )
        else:
            ffn_out = mlp(
                layer["mlp"], rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
            )
        x = x + ffn_out
        ks.append(stored[0])
        vs.append(stored[1])
        kss.append(stored[2])
        vss.append(stored[3])
    new_lengths = cache.lengths + advance.astype(jnp.int32)
    if isinstance(cache, SlotCache8):
        new_cache = SlotCache8(
            tuple(ks), tuple(vs), tuple(kss), tuple(vss), new_lengths
        )
    else:
        new_cache = SlotCache(tuple(ks), tuple(vs), new_lengths)
    if not head:
        # cache-write-only callers (the draft's extension step) skip the
        # full-vocab projection — with a tied head it costs more than the
        # shallow draft's layers
        return None, new_cache
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = linear(x, params["lm_head"]).astype(jnp.float32)  # [B,S,V]
    return logits, new_cache


def _warp_rows(logits, temps, top_k: int, top_p: float):
    """Per-row warped logits: temperature is per-row (greedy rows get a
    near-zero temperature floor only to keep the division defined — their
    tokens come from argmax, never from these logits)."""
    sl = logits / jnp.maximum(temps, 1e-6)[:, None]
    if top_k:
        sl = apply_top_k(sl, top_k)
    if top_p < 1.0:
        sl = apply_top_p(sl, top_p)
    return sl


def serving_step(params, cfg, cache: "SlotCache | SlotCache8", tokens,
                 active, temps, key,
                 top_k: int = 0, top_p: float = 1.0):
    """One decode step for the whole slot batch.

    tokens/active/temps: [SLOTS]; returns (next_tokens [SLOTS], cache with
    active rows advanced by one). Sampling happens on device: greedy where
    temps <= 0, temperature/top-k/top-p sampling elsewhere.
    """
    logits_all, new_cache = _rows_forward(
        params, cfg, cache, tokens[:, None], active.astype(jnp.int32)
    )
    logits = logits_all[:, -1]  # [B, V]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    sl = _warp_rows(logits, temps, top_k, top_p)
    sampled = jax.random.categorical(key, sl, axis=-1).astype(jnp.int32)
    nxt = jnp.where(temps > 0, sampled, greedy)
    return nxt, new_cache


def serving_chunk(params, cfg, cache: "SlotCache | SlotCache8", tokens,
                  done, temps,
                  remaining, key, n_steps: int, eos_id: int = -1,
                  top_k: int = 0, top_p: float = 1.0):
    """``n_steps`` decode steps in ONE device program (lax.scan).

    The single-step loop costs ~6 host<->device round trips per emitted
    token (uploads, dispatch, PRNG split, token fetch) — fatal when the
    chip sits behind a network tunnel and merely wasteful on PCIe. The
    chunk carries tokens/done/key on device and returns [n_steps, SLOTS]
    tokens in one fetch: round trips per token drop by n_steps x SLOTS.

    Per-row freezes stay on device so the cache never advances past a
    stop: ``done`` rows re-feed their token and don't advance ``lengths``;
    a row freezes when it emits ``eos_id`` or its ``remaining`` budget
    (tokens still owed) hits zero.

    Returns (cache, tokens, done, remaining, key, toks[n_steps, SLOTS]).
    """

    def body(carry, _):
        cache, tok, done, rem, key = carry
        key, sub = jax.random.split(key)
        active = ~done
        nxt, cache = serving_step(
            params, cfg, cache, tok, active, temps, sub,
            top_k=top_k, top_p=top_p,
        )
        nxt = jnp.where(done, tok, nxt)  # frozen rows hold their token
        rem = rem - active.astype(jnp.int32)
        done = done | (rem <= 0)
        if eos_id >= 0:
            done = done | (nxt == eos_id)
        return (cache, nxt, done, rem, key), nxt

    (cache, tokens, done, remaining, key), toks = lax.scan(
        body, (cache, tokens, done, remaining, key), None, length=n_steps
    )
    return cache, tokens, done, remaining, key, toks


def speculative_serving_cycle(
    params, draft_params, cfg, dcfg,
    cache: "SlotCache | SlotCache8", d_cache: "SlotCache | SlotCache8",
    tokens, active, temps, key, draft_tokens: int,
    top_k: int = 0, top_p: float = 1.0,
):
    """One speculative cycle for the whole slot batch, each row advancing
    by ITS OWN acceptance (VERDICT r3 missing #3: the standalone decoder
    advances by the minimum across rows, which wastes speculation at
    B > 1 — the slot cache's per-row frontiers are exactly the machinery
    per-row advance needs).

    The draft proposes K tokens per row (K+1 scan steps — the last one
    materializes the cache entry full-accept rows need, a position other
    rows simply overwrite next cycle); the target verifies all rows' K+1
    tokens in ONE forward with per-row frontiers; rejection sampling
    (temps > 0) or greedy matching (temps <= 0) decides each row's
    acceptance a_i independently; row i emits a_i+1 tokens and advances
    both caches by a_i+1. Emitted tokens are exactly the per-row warped
    target distribution (sampled rows) / the target's greedy tokens
    (greedy rows) — the same guarantees as the standalone decoder, row by
    row.

    tokens/active/temps: [SLOTS]. Returns (cache, d_cache, next_tokens
    [SLOTS], emit [SLOTS, K+1], counts [SLOTS]) — counts[i] of emit[i]
    are valid (0 for inactive rows).
    """
    from nanotpu.models.speculative import rejection_step

    B = tokens.shape[0]
    K = draft_tokens
    t_base = cache.lengths
    d_base = d_cache.lengths
    key, k_draft, k_accept, k_resample, k_bonus = jax.random.split(key, 5)

    # -- draft: K proposals per row + the cache-extension step ------------
    def draft_scan(carry, step_key):
        dc, tok = carry
        logits, dc = _rows_forward(
            draft_params, dcfg, dc, tok[:, None],
            jnp.ones((B,), jnp.int32),
        )
        q_warp = jax.nn.softmax(
            _warp_rows(logits[:, -1], temps, top_k, top_p), axis=-1
        )
        sampled = jax.random.categorical(
            step_key, jnp.log(jnp.maximum(q_warp, 1e-38)), axis=-1
        ).astype(jnp.int32)
        greedy = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        nxt = jnp.where(temps > 0, sampled, greedy)
        return (dc, nxt), (nxt, q_warp)

    (d_cache, last), (drafts, q_all) = lax.scan(
        draft_scan, (d_cache, tokens), jax.random.split(k_draft, K)
    )
    drafts = jnp.moveaxis(drafts, 0, 1)  # [B, K]
    q_probs = jnp.moveaxis(q_all, 0, 1)  # [B, K, V]
    # extension: materialize d_K's cache entry (valid only where a row
    # accepts everything; elsewhere it is stale and overwritten later)
    _, d_cache = _rows_forward(
        draft_params, dcfg, d_cache, last[:, None],
        jnp.zeros((B,), jnp.int32), head=False,
    )

    # -- target verifies cur + d1..dK in one per-row-frontier forward -----
    verify = jnp.concatenate([tokens[:, None], drafts], axis=1)  # [B, K+1]
    v_logits, cache = _rows_forward(
        params, cfg, cache, verify, jnp.zeros((B,), jnp.int32)
    )  # [B, K+1, V]
    greedy = jnp.argmax(v_logits, axis=-1).astype(jnp.int32)  # [B, K+1]

    # per-row acceptance: greedy rows match the target's own argmax;
    # sampled rows run batched rejection sampling on the warped dists
    flat = v_logits.reshape(B * (K + 1), -1)
    p_all = jax.nn.softmax(
        _warp_rows(flat, jnp.repeat(temps, K + 1), top_k, top_p), axis=-1
    ).reshape(B, K + 1, -1)
    accepted, resampled = rejection_step(
        p_all[:, :K], q_probs, drafts, k_accept, k_resample
    )
    a_sample = jnp.cumprod(accepted.astype(jnp.int32), axis=1).sum(axis=1)
    matches = drafts == greedy[:, :K]
    a_greedy = jnp.cumprod(matches.astype(jnp.int32), axis=1).sum(axis=1)
    a = jnp.where(temps > 0, a_sample, a_greedy)  # [B]

    # token at each row's emit position a: accepted-all -> bonus sample
    # from the K+1-th target distribution; rejected at a -> the residual
    # resample (sampled rows) / the target's greedy token (greedy rows)
    bonus = jax.random.categorical(
        k_bonus, jnp.log(jnp.maximum(p_all[:, K], 1e-38)), axis=-1
    ).astype(jnp.int32)
    res_pad = jnp.concatenate([resampled, resampled[:, -1:]], axis=1)
    res_a = jnp.take_along_axis(res_pad, a[:, None], axis=1)[:, 0]
    greedy_a = jnp.take_along_axis(greedy, a[:, None], axis=1)[:, 0]
    tok_a = jnp.where(
        temps > 0, jnp.where(a == K, bonus, res_a), greedy_a
    )
    # emit[i] = d1..d_{a_i}, tok_a_i, <junk beyond counts[i]>
    emit = jnp.concatenate([drafts, drafts[:, -1:]], axis=1)  # [B, K+1]
    emit = jnp.where(
        jnp.arange(K + 1)[None, :] == a[:, None], tok_a[:, None], emit
    )

    adv = jnp.where(active, a + 1, 0).astype(jnp.int32)
    cache = cache._replace(lengths=t_base + adv)
    d_cache = d_cache._replace(lengths=d_base + adv)
    counts = adv
    nxt = jnp.take_along_axis(
        emit, jnp.maximum(adv - 1, 0)[:, None], axis=1
    )[:, 0]
    nxt = jnp.where(active, nxt, tokens)
    return cache, d_cache, nxt, emit, counts


def speculative_serving_chunk(
    params, draft_params, cfg, dcfg, cache, d_cache, tokens, done, temps,
    remaining, key, n_cycles: int, draft_tokens: int, eos_id: int = -1,
    top_k: int = 0, top_p: float = 1.0,
):
    """``n_cycles`` speculative cycles in ONE device program (the
    speculative analogue of :func:`serving_chunk`; same freeze semantics,
    emitting up to K+1 tokens per row per cycle).

    Returns (cache, d_cache, tokens, done, remaining, key,
    emits [n_cycles, SLOTS, K+1], counts [n_cycles, SLOTS]). A row
    freezes when its VALID emitted prefix contains ``eos_id`` or its
    budget runs out; like serving_chunk, frozen rows compute garbage that
    is never read, and per-cycle ``counts`` may overshoot ``remaining``
    by up to K — the host replay trims to the budget (cache positions
    past the last needed token are stale-by-construction, exactly like
    rejected drafts)."""
    K = draft_tokens

    def body(carry, _):
        cache, d_cache, tok, done, rem, key = carry
        key, sub = jax.random.split(key)
        active = ~done
        cache, d_cache, tok, emit, counts = speculative_serving_cycle(
            params, draft_params, cfg, dcfg, cache, d_cache, tok, active,
            temps, sub, K, top_k=top_k, top_p=top_p,
        )
        rem = rem - counts
        done = done | (rem <= 0)
        if eos_id >= 0:
            valid = jnp.arange(K + 1)[None, :] < counts[:, None]
            done = done | (valid & (emit == eos_id)).any(axis=1)
        return (cache, d_cache, tok, done, rem, key), (emit, counts)

    (cache, d_cache, tokens, done, remaining, key), (emits, counts) = (
        lax.scan(
            body, (cache, d_cache, tokens, done, remaining, key), None,
            length=n_cycles,
        )
    )
    return cache, d_cache, tokens, done, remaining, key, emits, counts


def prefill_cache_only(params, cfg, prompt_padded, max_len, mesh=None):
    """Prefill that only primes cache rows — no sampling, no lm_head
    (the speculative draft's admission path: the discarded full-vocab
    logits over a padded prompt would cost more than the shallow draft's
    whole transformer). Accepts a [B, S] batch — the re-prime path pads
    all stale rows of one bucket into a single call. Returns (k rows,
    v rows) for insert_request (B=1) or insert_rows (batched)."""
    from nanotpu.models.generate import _run, KVCache

    cache = KVCache.create(cfg, prompt_padded.shape[0], max_len)
    if mesh is not None:
        from nanotpu.parallel.infer import constrain_cache

        cache = constrain_cache(cache, mesh)
    _, cache = _run(
        params, prompt_padded, cfg, cache, full_prefill=True, mesh=mesh,
        head=False,
    )
    return cache.k, cache.v


def prefill_request(params, cfg, prompt_padded, true_len, max_len,
                    temp, key, top_k: int = 0, top_p: float = 1.0,
                    mesh=None, count_drops: bool = False):
    """Prefill one request (B=1, padded prompt) and sample its first token.

    Returns (first_token scalar, k rows, v rows) where rows are per-layer
    [1, max_len, KV, hd] ready for :func:`insert_request`. The pad region's
    k/v are garbage but sit at positions >= true_len, beyond the row's
    frontier — never attended. ``mesh`` pins the fresh cache rows to the
    tp-over-kv-heads layout so insertion into the (sharded) slot cache is
    collective-free.

    ``count_drops`` (MoE models) appends a fourth return value: the total
    tokens dropped by expert-capacity pressure across all layers of this
    prefill — prefill routes at Switch capacity over the PADDED bucket
    length, so a long prompt near its bucket boundary can drop; this
    makes that observable on /metrics instead of theoretical (VERDICT r3
    weak #5)."""
    from nanotpu.models.generate import _run, KVCache

    cache = KVCache.create(cfg, 1, max_len)
    if mesh is not None:
        from nanotpu.parallel.infer import constrain_cache

        cache = constrain_cache(cache, mesh)
    drop_acc: list | None = [] if count_drops else None
    logits_all, cache = _run(
        params, prompt_padded, cfg, cache, full_prefill=True,
        return_all=True, mesh=mesh, drop_acc=drop_acc,
    )  # [1, S_pad, V]; drop_acc collects per-token [S_pad] vectors
    logits = jax.lax.dynamic_index_in_dim(
        logits_all, true_len - 1, axis=1, keepdims=False
    )  # [1, V]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    sl = logits / jnp.maximum(temp, 1e-6)
    if top_k:
        sl = apply_top_k(sl, top_k)
    if top_p < 1.0:
        sl = apply_top_p(sl, top_p)
    sampled = jax.random.categorical(key, sl, axis=-1).astype(jnp.int32)
    first = jnp.where(temp > 0, sampled, greedy)[0]
    if count_drops:
        if drop_acc:
            # count REAL tokens only: route_topk fills capacity in token
            # order, so trailing PAD positions lose their slots first —
            # unmasked, every short prompt in a long bucket would report
            # phantom drops no served token ever experienced
            real = jnp.arange(prompt_padded.shape[1]) < true_len
            drops = jnp.where(real, sum(drop_acc), 0).sum().astype(
                jnp.int32
            )
        else:
            drops = jnp.zeros((), jnp.int32)
        return first, cache.k, cache.v, drops
    return first, cache.k, cache.v


def insert_request(cache, ks, vs, slot, length):
    """Drop a prefilled row into ``slot``: per-layer dynamic-slice on axis 0
    (donated by the jit wrapper, so no copy of the other slots). For the
    int8 cache the row is quantized here, once, at admission; positions
    past the prompt quantize garbage that stays beyond the row frontier."""

    def put4(cache_arr, row):
        return jax.lax.dynamic_update_slice(
            cache_arr, row.astype(cache_arr.dtype), (slot, 0, 0, 0)
        )

    def put3(cache_arr, row):
        return jax.lax.dynamic_update_slice(
            cache_arr, row.astype(cache_arr.dtype), (slot, 0, 0)
        )

    lengths = cache.lengths.at[slot].set(length)
    if isinstance(cache, SlotCache8):
        kq = [quantize_kv(rk) for rk in ks]
        vq = [quantize_kv(rv) for rv in vs]
        return SlotCache8(
            tuple(put4(ck, q) for ck, (q, _) in zip(cache.k, kq)),
            tuple(put4(cv, q) for cv, (q, _) in zip(cache.v, vq)),
            tuple(put3(cs, s) for cs, (_, s) in zip(cache.k_scale, kq)),
            tuple(put3(cs, s) for cs, (_, s) in zip(cache.v_scale, vq)),
            lengths,
        )
    new_k = tuple(put4(ck, rk) for ck, rk in zip(cache.k, ks))
    new_v = tuple(put4(cv, rv) for cv, rv in zip(cache.v, vs))
    return SlotCache(new_k, new_v, lengths)


def insert_rows(cache, ks, vs, slots, lengths):
    """Batched :func:`insert_request`: scatter B prefilled rows into their
    slots in ONE call (the re-prime path's per-bucket device round trip).
    ``slots``/``lengths`` are [B]; padding rows carry an out-of-range slot
    index (== slot capacity) and are dropped by the scatter, which is what
    lets the caller pad every batch to one compiled shape."""

    def put(cache_arr, rows):
        return cache_arr.at[slots].set(
            rows.astype(cache_arr.dtype), mode="drop"
        )

    new_lengths = cache.lengths.at[slots].set(lengths, mode="drop")
    if isinstance(cache, SlotCache8):
        kq = [quantize_kv(rk) for rk in ks]
        vq = [quantize_kv(rv) for rv in vs]
        return SlotCache8(
            tuple(put(ck, q) for ck, (q, _) in zip(cache.k, kq)),
            tuple(put(cv, q) for cv, (q, _) in zip(cache.v, vq)),
            tuple(put(cs, s) for cs, (_, s) in zip(cache.k_scale, kq)),
            tuple(put(cs, s) for cs, (_, s) in zip(cache.v_scale, vq)),
            new_lengths,
        )
    return SlotCache(
        tuple(put(ck, rk) for ck, rk in zip(cache.k, ks)),
        tuple(put(cv, rv) for cv, rv in zip(cache.v, vs)),
        new_lengths,
    )


class Request:
    """One generation request; wait() blocks until completion."""

    _ids = itertools.count()

    def __init__(self, tokens: list[int], max_new_tokens: int,
                 temperature: float = 0.0):
        self.id = next(self._ids)
        self.prompt = list(tokens)
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.out: list[int] = []
        self.submitted_at = time.perf_counter()
        self.first_token_at: float | None = None
        self.done_at: float | None = None
        self.error: str | None = None
        self._done = threading.Event()
        #: signaled by the engine loop whenever new tokens landed in
        #: ``out`` (once per decode chunk per row, not per token) — the
        #: stream() consumers' wakeup
        self._progress = threading.Condition()

    # -- results -----------------------------------------------------------
    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def stream(self, timeout: float | None = None):
        """Yield lists of new tokens as the engine emits them (one batch
        per decode-chunk boundary), returning when the request completes.
        ``timeout`` bounds the wait for EACH batch; no progress within it
        raises TimeoutError. Check ``self.error`` after exhaustion."""
        cursor = 0
        while True:
            with self._progress:
                while cursor >= len(self.out) and not self._done.is_set():
                    if not self._progress.wait(timeout):
                        raise TimeoutError(
                            f"request {self.id}: no progress in {timeout}s"
                        )
                batch = list(self.out[cursor:])
            cursor += len(batch)
            if batch:
                yield batch
            if self._done.is_set() and cursor >= len(self.out):
                return

    def _notify_progress(self) -> None:
        with self._progress:
            self._progress.notify_all()

    @property
    def ttft_s(self) -> float | None:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at

    @property
    def latency_s(self) -> float | None:
        if self.done_at is None:
            return None
        return self.done_at - self.submitted_at

    def _finish(self, error: str | None = None) -> None:
        self.error = error
        self.done_at = time.perf_counter()
        self._done.set()
        self._notify_progress()


class Engine:
    """Continuous-batching engine: one background loop interleaves
    admission prefills with whole-batch decode steps.

    ``slots`` bounds concurrent requests; extras queue. ``eos_id >= 0``
    stops a row early. ``top_k``/``top_p`` apply engine-wide to sampled
    (temperature > 0) rows; temperature is per-request.
    """

    def __init__(self, params, cfg, slots: int = 8, max_len: int | None = None,
                 buckets: tuple = DEFAULT_BUCKETS, eos_id: int = -1,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 chunk_steps: int = 32, chunk_steps_max: int = 96,
                 kv_int8: bool = False, mesh=None,
                 draft_params=None, draft_cfg=None, draft_tokens: int = 4,
                 spec_policy="auto"):
        #: multi-chip serving (nanotpu.parallel.infer): params placed
        #: tp x fsdp, slot cache sharded tp-over-kv-heads, per-row control
        #: vectors replicated. mesh=None is the single-chip path unchanged.
        self.mesh = mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from nanotpu.parallel.infer import place_params

            params = place_params(params, cfg, mesh)
            self._repl = NamedSharding(mesh, PartitionSpec())
        else:
            self._repl = None
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.max_len = max_len or cfg.max_seq_len
        self.buckets = tuple(b for b in sorted(buckets) if b <= self.max_len)
        if not self.buckets or self.buckets[-1] < self.max_len:
            self.buckets = self.buckets + (self.max_len,)
        self.eos_id = eos_id
        self.top_k = top_k
        self.top_p = top_p
        #: device-program units per host round trip (see serving_chunk).
        #: Plain decode: one unit = one step = one token per row.
        #: Speculative decode: one unit = one CYCLE (one target verify —
        #: the dominant device cost — plus K cheap draft steps), which
        #: emits 1..draft_tokens+1 tokens per row; per sync a speculative
        #: engine therefore emits up to (1 + acceptance*K)x more than a
        #: plain one — on a high-latency link that multiplier IS the
        #: speedup, so the budget deliberately does NOT divide by K+1
        #: (equalizing per-sync emission was measured to neutralize
        #: speculation: 0.72x on the tunneled v5e at 0.90 acceptance).
        #: The small chunk keeps admission latency low while requests
        #: queue; the large one amortizes the link RTT when every row has
        #: a long runway.
        self.chunk_steps = max(1, chunk_steps)
        self.chunk_steps_max = max(self.chunk_steps, chunk_steps_max)

        #: int8 KV cache: half the HBM bytes per cache read — the decode
        #: bandwidth bottleneck — at ~0.4% per-element quantization error
        self.kv_int8 = kv_int8
        cache_cls = SlotCache8 if kv_int8 else SlotCache
        self._cache = cache_cls.create(cfg, slots, self.max_len)
        if mesh is not None:
            from nanotpu.parallel.infer import place_cache

            self._cache = place_cache(self._cache, mesh)

        #: per-row speculative decoding (VERDICT r3 #2): a draft model
        #: proposes draft_tokens per cycle, the target verifies the whole
        #: slot batch in one forward, each row advances by its own
        #: acceptance (speculative_serving_cycle). The draft keeps a plain
        #: bf16 SlotCache regardless of kv_int8 — at 1-2 layers its cache
        #: is a rounding error next to the target's.
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.draft_tokens = draft_tokens
        #: occupancy-adaptive speculation (VERDICT r4 missing #1). The r4
        #: v5e sweep measured the regime split: speculation pays clearly
        #: at small batch (1.36-1.52x at B=2), regresses around B=4
        #: (0.83-0.84x), and hovers at parity at B=8 — the plain batched
        #: step's weight reads amortize and verify cost wins. A fixed
        #: draft_tokens for every occupancy bakes that mistake in; the
        #: policy picks per SYNC, from the live active-slot count:
        #:   "auto"   -> speculate (K=draft_tokens) only at <=2 active
        #:               rows; plain chunks above (the measured default)
        #:   "always" -> speculate at every occupancy (the r4 behavior;
        #:               what the exactness tests pin)
        #:   "off"    -> plain chunks only (draft stays idle)
        #:   [(max_active, K), ...] -> explicit rules: first rule whose
        #:               max_active >= active rows decides K; no rule ->
        #:               plain. K must be <= draft_tokens (admission
        #:               slack reserves draft_tokens+1 positions).
        #: Plain phases leave the draft cache behind the target's
        #: frontier; on the next switch to speculation the engine
        #: re-primes stale rows through the existing bucketed draft
        #: prefill (one small forward per row, only on regime changes).
        #: "measured": pick plain-vs-speculative per sync from the
        #: engine's OWN observed tokens/s at the current occupancy bucket
        #: (r5: the r4-measured occupancy boundary turned out to be
        #: session-dependent — a later draft/chip state measured K=6
        #: winning at EVERY occupancy, where "auto"'s static <=2 rule
        #: left 24% at B=4. The regime boundary is a property of the
        #: (draft, target, link, chip) tuple, so measure it in place:
        #: each arm (plain / K) gets an EWMA of realized tokens/s per
        #: occupancy bucket; under-sampled arms are explored first, then
        #: the loser is re-probed every PROBE_EVERY syncs to track chip
        #: drift.) Greedy outputs are invariant across arms, so probing
        #: never perturbs emitted tokens.
        self._measured = spec_policy == "measured" and draft_params is not None
        if draft_params is None or spec_policy == "off":
            if draft_params is None and spec_policy != "off":
                # ADVICE r5: "measured" is the documented production
                # policy, and an operator who requests it but miswires the
                # draft would otherwise silently run plain-only decoding.
                # "auto" is the constructor DEFAULT, so a plain engine
                # built with no speculation settings at all logs at INFO
                # only — a WARNING there would be unconditional noise
                log.log(
                    logging.INFO if spec_policy == "auto" else logging.WARNING,
                    "spec_policy=%r requested but draft_params is None: "
                    "speculative decoding is DISABLED, falling back to "
                    "plain decoding (pass draft_params+draft_cfg, or "
                    "spec_policy='off' to silence this)",
                    spec_policy,
                )
            rules: list[tuple[int, int]] = []
        elif spec_policy == "measured":
            rules = [(slots, draft_tokens)]
        elif spec_policy == "always":
            rules = [(slots, draft_tokens)]
        elif spec_policy == "auto":
            rules = [(2, draft_tokens)]
        else:
            rules = sorted((int(m), int(k)) for m, k in spec_policy)
            for _, k in rules:
                if not 1 <= k <= draft_tokens:
                    raise ValueError(
                        f"spec_policy K={k} outside [1, draft_tokens="
                        f"{draft_tokens}]"
                    )
        self.spec_rules = rules
        #: measured-policy state: (occupancy bucket, chunk flavor) ->
        #: {k: EWMA tokens/s}, sample counts, and a per-cell sync counter
        #: for re-probes. Small- and large-chunk samples never share a
        #: cell: their per-sync overhead amortization differs ~chunk-size-
        #: fold, so mixing them penalizes whichever arm drew more small
        #: chunks (ADVICE r5).
        self._bandit_rate: dict[tuple[int, str], dict[int, float | None]] = {}
        self._bandit_n: dict[tuple[int, str], dict[int, int]] = {}
        self._bandit_t: dict[tuple[int, str], int] = {}
        #: (k, flavor) chunks that have executed at least once: the first
        #: execution's bandit sample is compile-contaminated and dropped
        self._chunk_seen: set[tuple[int, str]] = set()
        #: slots whose draft-cache row trails the target (plain chunks ran
        #: while they were active); re-primed before the next spec chunk
        self._draft_stale: set[int] = set()
        # speculation observability (stats()/operators): cycles run and
        # tokens they emitted — mean tokens/cycle - 1 is the realized
        # acceptance x K
        self.spec_cycles_total = 0
        self.spec_cycle_tokens_total = 0
        self._d_cache = None
        if draft_params is not None:
            if draft_cfg is None:
                raise ValueError("draft_params needs draft_cfg")
            if mesh is not None:
                from nanotpu.parallel.infer import (
                    place_cache as _pc,
                    place_params as _pp,
                )

                self.draft_params = _pp(draft_params, draft_cfg, mesh)
                self._d_cache = _pc(
                    SlotCache.create(draft_cfg, slots, self.max_len), mesh
                )
            else:
                self._d_cache = SlotCache.create(
                    draft_cfg, slots, self.max_len
                )
        self._slot_req: list[Request | None] = [None] * slots
        # host mirrors of per-row decode state; re-uploaded when _dirty
        self._tokens = np.zeros((slots,), np.int32)  # last token per slot
        self._temps = np.zeros((slots,), np.float32)
        self._done = np.ones((slots,), np.bool_)  # empty slots are frozen
        self._remaining = np.zeros((slots,), np.int32)
        self._dirty = True
        # device-resident copies, carried across chunks
        self._d_tokens = None
        self._d_temps = None
        self._d_done = None
        self._d_remaining = None
        self._d_key = jax.random.PRNGKey(seed)
        if self._repl is not None:
            self._d_key = jax.device_put(self._d_key, self._repl)
        self._queue: deque[Request] = deque()
        self._cv = threading.Condition()
        self._stop = False

        # stats (served by /metrics and /v1/stats)
        self.requests_total = 0
        self.tokens_total = 0
        #: realized decode tokens/s EWMA over timed chunks (cold/compile-
        #: contaminated chunks dropped, same rule as the bandit) — the
        #: feedback signal the scheduler's ThroughputModel consumes via
        #: the serving tap (docs/serving-loop.md). None until the first
        #: warm chunk; read/written under self._cv.
        self.tok_s_ewma: float | None = None
        #: MoE only: tokens dropped by expert-capacity pressure during
        #: admission prefills (decode routes at full capacity — only the
        #: padded-bucket prefill can drop; see prefill_request)
        self.moe_prefill_dropped_total = 0
        self._count_drops = hasattr(cfg, "n_experts")
        self.ttft_samples: deque[float] = deque(maxlen=4096)
        self.latency_samples: deque[float] = deque(maxlen=4096)

        # compiled chunks (small now, large lazily); cache donated so the
        # update is in place (HBM holds ONE slot cache, not two)
        # In mesh mode the chunk's carried outputs are PINNED (cache keeps
        # its layout, control vectors stay replicated): the chunk's outputs
        # feed back in as its next inputs, so without the pin GSPMD could
        # pick a different carried sharding than the committed inputs have
        # and the AOT-compiled large chunk would reject its own carry.
        if mesh is not None:
            from nanotpu.parallel.infer import slot_cache_specs
            from nanotpu.parallel.mesh import shardings_for

            cache_sh = shardings_for(mesh, slot_cache_specs(cfg, kv_int8))
            r = self._repl
            out_sh_plain = (cache_sh, r, r, r, r, r)
            if draft_params is not None:
                d_cache_sh = shardings_for(
                    mesh, slot_cache_specs(draft_cfg, False)
                )
                out_sh_spec = (cache_sh, d_cache_sh, r, r, r, r, r, r)
            else:
                out_sh_spec = None
        else:
            out_sh_plain = out_sh_spec = None
        dcfg = draft_cfg

        # draft params ride as a jit ARGUMENT (closure-captured big
        # trees break remote compiles over a tunneled chip)
        def make_chunk(n_units, k: int):
            """Compiled-chunk factory: k == 0 -> plain decode chunk of
            ``n_units`` steps; k > 0 -> speculative chunk of ``n_units``
            CYCLES proposing k tokens each (see the chunk_steps docstring
            for why speculative budgets count cycles, not tokens)."""
            if k == 0:
                return jax.jit(
                    lambda params, cache, tokens, done, temps, rem, key:
                    serving_chunk(
                        params, cfg, cache, tokens, done, temps, rem, key,
                        n_steps=n_units, eos_id=self.eos_id,
                        top_k=self.top_k, top_p=self.top_p,
                    ),
                    donate_argnums=(1,),
                    out_shardings=out_sh_plain,
                )
            return jax.jit(
                lambda params, dparams, cache, d_cache, tokens, done,
                temps, rem, key:
                speculative_serving_chunk(
                    params, dparams, cfg, dcfg, cache, d_cache, tokens,
                    done, temps, rem, key, n_cycles=n_units,
                    draft_tokens=k, eos_id=self.eos_id,
                    top_k=self.top_k, top_p=self.top_p,
                ),
                donate_argnums=(2, 3),
                out_shardings=out_sh_spec,
            )

        #: decode steps / speculative cycles per compiled chunk
        self._chunk_units = (self.chunk_steps, self.chunk_steps_max)
        #: the K variants the policy can select, plus 0 (plain) when any
        #: occupancy falls through the rules (or no draft at all)
        variant_ks = sorted({k for _, k in rules})
        if not rules or rules[-1][0] < slots or self._measured:
            # measured mode always needs the plain arm compiled: the
            # bandit chooses between plain and speculative chunks live
            variant_ks = [0] + variant_ks
        self._variant_ks = variant_ks
        self._chunk_small = {
            k: make_chunk(self._chunk_units[0], k) for k in variant_ks
        }
        # the large chunks compile in the BACKGROUND (ahead-of-time, on
        # shape structs — no second cache allocation) so their first use
        # never stalls the engine loop: an XLA compile is seconds on a big
        # model, and blocking _decode_cycle would freeze every active row.
        # Until a variant is ready the engine uses its small chunk.
        self._chunk_large: dict[int, object] = {}
        self._chunk_large_ready = threading.Event()

        def compile_large():
            try:
                # in mesh mode the SDS must carry the real input shardings:
                # the compiled executable accepts exactly what it was
                # lowered for, and the live params/cache are committed
                sds = lambda x: jax.ShapeDtypeStruct(  # noqa: E731
                    jnp.shape(x), jnp.result_type(x),
                    sharding=(x.sharding if mesh is not None else None),
                )
                i32 = jax.ShapeDtypeStruct(
                    (slots,), jnp.int32, sharding=self._repl
                )
                ctrl = [
                    i32,  # tokens
                    jax.ShapeDtypeStruct(
                        (slots,), jnp.bool_, sharding=self._repl
                    ),  # done
                    jax.ShapeDtypeStruct(
                        (slots,), jnp.float32, sharding=self._repl
                    ),  # temps
                    i32,  # remaining
                    sds(self._d_key),  # key
                ]
                p_sds = jax.tree_util.tree_map(sds, self.params)
                c_sds = jax.tree_util.tree_map(sds, self._cache)
                for k in self._variant_ks:
                    args = [p_sds]
                    if k > 0:
                        args.append(
                            jax.tree_util.tree_map(sds, self.draft_params)
                        )
                    args.append(c_sds)
                    if k > 0:
                        args.append(
                            jax.tree_util.tree_map(sds, self._d_cache)
                        )
                    args += ctrl
                    self._chunk_large[k] = make_chunk(
                        self._chunk_units[1], k
                    ).lower(*args).compile()
                if self.draft_params is not None and rules:
                    # warm every draft-prefill bucket shape: a re-prime
                    # can hit a bucket admission never used (context
                    # grows mid-request past the prompt's bucket), and a
                    # synchronous jit compile inside the engine loop
                    # would stall every active row for seconds. Both
                    # shapes: [1, b] (admission) and [slots, b] (the
                    # batched re-prime, which always pads to slot count)
                    for b in self.buckets:
                        self._prefill_draft(
                            self.draft_params, jnp.zeros((1, b), jnp.int32)
                        )
                        self._prefill_draft(
                            self.draft_params,
                            jnp.zeros((self.slots, b), jnp.int32),
                        )
            except Exception:
                log.exception("large-chunk compile failed; small chunk only")
            finally:
                self._chunk_large_ready.set()
        self._insert = jax.jit(
            insert_request, donate_argnums=(0,),
            out_shardings=(cache_sh if mesh is not None else None),
        )
        self._prefill = jax.jit(
            lambda params, padded, true_len, temp, key: prefill_request(
                params, cfg, padded, true_len, self.max_len, temp, key,
                top_k=self.top_k, top_p=self.top_p, mesh=mesh,
                count_drops=self._count_drops,
            ),
        )
        if self.draft_params is not None:
            # head-free: only the primed cache rows matter (the target's
            # prefill supplies the first token)
            self._prefill_draft = jax.jit(
                lambda dparams, padded: prefill_cache_only(
                    dparams, draft_cfg, padded, self.max_len, mesh=mesh,
                ),
            )
            self._insert_d = jax.jit(
                insert_request, donate_argnums=(0,),
                out_shardings=(
                    d_cache_sh if mesh is not None else None
                ),
            )
            self._insert_rows_d = jax.jit(
                insert_rows, donate_argnums=(0,),
                out_shardings=(
                    d_cache_sh if mesh is not None else None
                ),
            )
        # started HERE, not where compile_large is defined: the warm loop
        # inside it reads self._prefill_draft, which must exist first
        threading.Thread(
            target=compile_large, daemon=True, name="chunk-compile"
        ).start()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serving-engine"
        )
        self._thread.start()

    # -- public API --------------------------------------------------------
    def submit(self, tokens: list[int], max_new_tokens: int,
               temperature: float = 0.0) -> Request:
        req = Request(tokens, max_new_tokens, temperature)
        if not tokens or max_new_tokens < 1:
            req._finish("empty prompt or max_new_tokens < 1")
            return req
        if len(tokens) >= self.max_len:
            req._finish(
                f"prompt length {len(tokens)} >= engine max_len {self.max_len}"
            )
            return req
        with self._cv:
            if self._stop:
                # the loop thread is dead (or dying): enqueueing would
                # strand the request until its caller's timeout
                req._finish("engine stopped")
                return req
            self._queue.append(req)
            self.requests_total += 1
            self._cv.notify()
        return req

    def generate(self, tokens: list[int], max_new_tokens: int,
                 temperature: float = 0.0, timeout: float = 600.0) -> list[int]:
        """Blocking convenience wrapper."""
        req = self.submit(tokens, max_new_tokens, temperature)
        if not req.wait(timeout):
            raise TimeoutError(f"request {req.id} timed out")
        if req.error:
            raise RuntimeError(req.error)
        return req.out

    def wait_warm(self, timeout: float | None = None) -> bool:
        """Block until the background large-chunk compile finished (bench
        harnesses call this so the compile never lands in a timed window)."""
        return self._chunk_large_ready.wait(timeout)

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=10)

    def metrics(self) -> dict:
        """Cheap feedback snapshot (docs/serving-loop.md): the fields the
        scheduler's timeline source, the ``nanotpu_serving_*`` gauges,
        and the throughput-model tap consume. Host-side state only — no
        device sync, no jit: safe to call from a scrape thread at any
        rate. Key set is the serving-provider contract shared with the
        sim's virtual replica fleet (pinned by tests), so SLO objectives
        addressing ``ext.serving.*`` mean the same thing against either
        producer."""
        from nanotpu.metrics.stats import percentile

        with self._cv:
            queued = len(self._queue)
            tok_s = self.tok_s_ewma
            ttft_p99 = percentile(list(self.ttft_samples), 0.99)
        active = 0
        kv_used = 0
        for req in self._slot_req:
            if req is None:
                continue
            active += 1
            kv_used += min(self.max_len, len(req.prompt) + len(req.out))
        chips = self.mesh.devices.size if self.mesh is not None else 1
        return {
            "tok_s": round(tok_s, 4) if tok_s is not None else 0.0,
            "queue_depth": float(queued),
            "active": float(active),
            "slots": float(self.slots),
            "kv_occupancy": round(
                kv_used / (self.slots * self.max_len), 6
            ),
            "chips": float(chips),
            "ttft_p99_ms": (
                round(ttft_p99 * 1e3, 2) if ttft_p99 is not None else 0.0
            ),
        }

    def stats(self) -> dict:
        # ONE metrics() snapshot feeds the feedback fields below, so
        # /v1/stats and the provider contract stay definitionally
        # identical (metrics() takes _cv itself — call it before ours)
        m = self.metrics()
        # snapshot the sample deques under the same lock the engine loop
        # appends under — sorting a deque another thread mutates raises
        # RuntimeError, which would 500 /v1/stats under live traffic
        with self._cv:
            queued = len(self._queue)
            ttft = sorted(self.ttft_samples)
            lat = sorted(self.latency_samples)
            # deep-copied under the lock: the engine loop inserts new
            # occupancy buckets via setdefault mid-iteration otherwise
            bandit = {
                b: dict(arms) for b, arms in self._bandit_rate.items()
            }
        active = sum(1 for r in self._slot_req if r is not None)

        def pct(xs, p):
            return xs[min(len(xs) - 1, int(p * len(xs)))] if xs else None

        return {
            "slots": self.slots,
            "active": active,
            "queued": queued,
            # feedback surface (metrics()): the remote serving source the
            # scheduler polls reads these three off /v1/stats
            "tok_s": m["tok_s"],
            "kv_occupancy": m["kv_occupancy"],
            "chips": int(m["chips"]),
            "requests_total": self.requests_total,
            "tokens_total": self.tokens_total,
            "moe_prefill_dropped_total": self.moe_prefill_dropped_total,
            "ttft_p50_ms": pct(ttft, 0.5) and round(pct(ttft, 0.5) * 1e3, 2),
            "ttft_p99_ms": pct(ttft, 0.99) and round(pct(ttft, 0.99) * 1e3, 2),
            "latency_p50_ms": pct(lat, 0.5) and round(pct(lat, 0.5) * 1e3, 2),
            # speculation observability: mean emitted tokens per
            # speculative cycle (1 + realized acceptance x K); None until
            # a speculative chunk has run
            "spec_cycles_total": self.spec_cycles_total,
            "spec_tokens_per_cycle": (
                round(
                    self.spec_cycle_tokens_total / self.spec_cycles_total, 3
                )
                if self.spec_cycles_total else None
            ),
            # measured policy: the live per-(bucket, chunk flavor) arm
            # table (EWMA tokens/s per speculation depth), so operators
            # can see WHY the engine is choosing plain or speculative
            # chunks; keys render as "occupancy/flavor"
            "spec_bandit_tok_s": (
                {
                    f"{b[0]}/{b[1]}": {
                        str(k): (r if r is None else round(r, 1))
                        for k, r in arms.items()
                    }
                    for b, arms in bandit.items()
                }
                if self._measured else None
            ),
        }

    # -- engine loop -------------------------------------------------------
    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _next_key(self):
        self._d_key, sub = jax.random.split(self._d_key)
        return sub

    def _admit_all(self) -> None:
        """Move queued requests into free slots.

        Prefills are DISPATCHED per request (async, cheap) but their first
        tokens are fetched with ONE stacked sync at the end — on a
        high-latency link a per-admission int(first) sync would cost a
        full round trip per request."""
        admitted: list[tuple[Request, int, jax.Array, jax.Array]] = []
        while True:
            slot = next(
                (i for i, r in enumerate(self._slot_req) if r is None
                 and all(a[1] != i for a in admitted)),
                None,
            )
            if slot is None:
                break
            with self._cv:
                if not self._queue:
                    break
                req = self._queue.popleft()
            S = len(req.prompt)
            # cap generation to the cache row; speculative mode reserves
            # K+1 extra positions for the last cycle's write overshoot.
            # The floor of 1 keeps a near-max_len prompt's behavior at
            # the plain engine's boundary semantics (one prefill token,
            # no decode cycles) instead of a negative budget; it cannot
            # overflow the row — a 1-token budget freezes before any
            # speculative cycle writes, and a frozen row's (clamped)
            # writes land only in its own never-read tail
            slack = (
                self.draft_tokens + 1 if self.draft_params is not None
                else 0
            )
            req.max_new_tokens = max(1, min(
                req.max_new_tokens, self.max_len - S - slack
            ))
            bucket = self._bucket(S)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :S] = req.prompt
            out = self._prefill(
                self.params, jnp.asarray(padded), jnp.int32(S),
                jnp.float32(req.temperature), self._next_key(),
            )
            first, ks, vs = out[:3]
            # MoE: the drop scalar rides the same stacked fetch as firsts
            drops = out[3] if self._count_drops else jnp.zeros((), jnp.int32)
            self._cache = self._insert(self._cache, ks, vs, jnp.int32(slot),
                                       jnp.int32(S))
            if self._d_cache is not None:
                # prime the draft row only when the post-admission
                # occupancy could speculate; plain-regime admissions skip
                # the draft forward (the row would go stale after the
                # very next plain chunk) and regime entry re-primes it
                occ_after = sum(
                    1 for r in self._slot_req if r is not None
                ) + len(admitted) + 1
                # measured mode always primes: the bandit may pick the
                # speculative arm at any occupancy, and one bucketed
                # draft forward at admission is cheaper than a re-prime
                # round trip mid-stream
                if self._measured or self._policy_k(occ_after) > 0:
                    dks, dvs = self._prefill_draft(
                        self.draft_params, jnp.asarray(padded)
                    )
                    self._d_cache = self._insert_d(
                        self._d_cache, dks, dvs, jnp.int32(slot),
                        jnp.int32(S)
                    )
                    self._draft_stale.discard(slot)  # freshly primed
                else:
                    self._draft_stale.add(slot)
            admitted.append((req, slot, first, drops))
        if not admitted:
            return
        fetched = np.asarray(jnp.stack(
            [f for _, _, f, _ in admitted] + [d for _, _, _, d in admitted]
        ))
        firsts = fetched[: len(admitted)]
        self.moe_prefill_dropped_total += int(fetched[len(admitted):].sum())
        now = time.perf_counter()
        for (req, slot, _, _), tok in zip(admitted, firsts):
            tok = int(tok)
            req.first_token_at = now
            with self._cv:  # stats() sorts these concurrently
                self.ttft_samples.append(req.ttft_s)
            req.out.append(tok)
            self.tokens_total += 1
            if len(req.out) >= req.max_new_tokens or (
                self.eos_id >= 0 and tok == self.eos_id
            ):
                req._finish()
                with self._cv:
                    self.latency_samples.append(req.latency_s)
                continue
            req._notify_progress()  # first token is streamable immediately
            self._slot_req[slot] = req
            self._tokens[slot] = tok
            self._temps[slot] = req.temperature
            self._done[slot] = False
            self._remaining[slot] = req.max_new_tokens - 1  # first already out
            self._dirty = True

    def _policy_k(self, n_active: int, flavor: str = "large") -> int:
        """Speculation depth for a chunk at ``n_active`` occupied slots:
        the first rule covering the count decides; none -> 0 (plain).
        ``flavor`` picks the measured-mode arm table (see _bandit_pick)."""
        if self._measured:
            return self._bandit_pick(n_active, flavor)
        for max_active, rule_k in self.spec_rules:
            if n_active <= max_active:
                return rule_k
        return 0

    #: EWMA weight of one new tokens/s sample; ~last 6 chunks dominate
    BANDIT_ALPHA = 0.3
    #: per-arm samples required before exploitation starts
    BANDIT_MIN_SAMPLES = 3
    #: re-probe a losing arm every N syncs per bucket (tracks chip
    #: drift). Chip-state throughput swings faster than a long probe
    #: period can track: at 32 a bucket that sees ~30 syncs per minute
    #: never re-probed at all and exploited a stale warm-phase estimate
    BANDIT_PROBE_EVERY = 12

    @staticmethod
    def _bandit_bucket(n_active: int) -> int:
        """Occupancy bucket: 1, 2, 3-4, 5-8, 9-16, ... (powers of two).
        The spec-vs-plain tradeoff moves with how well the batched verify
        amortizes, which is roughly log-scaled in active rows."""
        b = 1
        while b < n_active:
            b *= 2
        return b

    def _bandit_pick(self, n_active: int, flavor: str = "large") -> int:
        """Measured policy: explore under-sampled arms, then exploit the
        best EWMA tokens/s for this (occupancy bucket, chunk flavor) cell,
        re-probing losers every BANDIT_PROBE_EVERY syncs. Greedy outputs
        are invariant across arms, so exploration never changes emitted
        tokens.

        Keyed by FLAVOR as well as bucket (ADVICE r5): the small chunk
        amortizes the per-chunk host sync over far fewer device steps
        than the large one, so its tokens/s samples run systematically
        low — explore samples landing on the small chunk (queue briefly
        non-empty) were sinking arms in the shared table on a penalty
        that says nothing about the arm."""
        b = (self._bandit_bucket(n_active), flavor)
        # the whole pick runs under the lock stats() snapshots with
        # (ADVICE r5): the writes are cheap scalar ops, and leaning on the
        # GIL for the _bandit_t read-modify-write would break the moment a
        # second policy-consulting thread (or a free-threaded runtime)
        # shows up
        with self._cv:
            rate = self._bandit_rate.setdefault(
                b, {k: None for k in self._variant_ks}
            )
            n = self._bandit_n.setdefault(
                b, {k: 0 for k in self._variant_ks}
            )
            for k in self._variant_ks:
                if n[k] < self.BANDIT_MIN_SAMPLES:
                    return k
            t = self._bandit_t.get(b, 0) + 1
            self._bandit_t[b] = t
            best = max(rate, key=lambda k: rate[k])
            if t % self.BANDIT_PROBE_EVERY == 0:
                # stalest loser gets a fresh sample
                losers = [k for k in self._variant_ks if k != best]
                if losers:
                    return min(losers, key=lambda k: n[k])
            return best

    def _bandit_update(self, n_active: int, k: int, tokens: int,
                       dt: float, flavor: str = "large",
                       cold: bool = False) -> None:
        """Fold one chunk's tokens/s into its (bucket, flavor, arm) EWMA.
        ``cold`` marks the first-ever execution of that compiled chunk:
        its dt includes XLA compile/dispatch warmup (seconds against a
        millisecond steady state), a sample about the COMPILER that would
        sink the arm for the next ~1/alpha real samples — dropped."""
        if cold or not self._measured or tokens <= 0 or dt <= 0:
            return
        b = (self._bandit_bucket(n_active), flavor)
        r = tokens / dt
        with self._cv:  # stats() deep-copies the arm table under this lock
            rate = self._bandit_rate.setdefault(
                b, {arm: None for arm in self._variant_ks}
            )
            n = self._bandit_n.setdefault(
                b, {arm: 0 for arm in self._variant_ks}
            )
            cur = rate[k]
            rate[k] = (
                r if cur is None
                else (1 - self.BANDIT_ALPHA) * cur + self.BANDIT_ALPHA * r
            )
            n[k] += 1

    def _reprime_draft(self) -> None:
        """Catch stale draft-cache rows up to the target's frontier.

        A plain-chunk phase advances only the target cache; before the
        next speculative chunk each surviving row's draft cache must hold
        k/v for the same context. The full token sequence is on the host
        (prompt + emitted), so this is exactly the admission-time draft
        prefill re-run at the row's current length — BATCHED: stale rows
        group by context bucket and each bucket costs ONE padded draft
        forward plus ONE scatter insert, so a plain→spec arm flip at
        occupancy B pays one device round trip per bucket instead of up
        to B (VERDICT r5 weak #5). Each batch is padded to the engine's
        slot count so a bucket has exactly one compiled shape (warmed by
        the background compile thread); padding rows scatter to an
        out-of-range slot and are dropped. Numeric wobble between a
        prefilled and an incrementally-built draft row only perturbs
        PROPOSALS — never emitted tokens, which acceptance pins to the
        target."""
        by_bucket: dict[int, list[tuple[int, int, list[int]]]] = {}
        for i in sorted(self._draft_stale):
            self._draft_stale.discard(i)
            req = self._slot_req[i]
            if req is None or self._done[i]:
                continue
            seq = req.prompt + req.out
            t_len = len(seq) - 1  # the last token is the next input
            by_bucket.setdefault(self._bucket(t_len), []).append(
                (i, t_len, seq)
            )
        for bucket, rows in by_bucket.items():
            padded = np.zeros((self.slots, bucket), np.int32)
            # padding rows target slot index == capacity -> scatter drops
            slots = np.full((self.slots,), self.slots, np.int32)
            lengths = np.zeros((self.slots,), np.int32)
            for j, (i, t_len, seq) in enumerate(rows):
                padded[j, :t_len] = seq[:t_len]
                slots[j] = i
                lengths[j] = t_len
            dks, dvs = self._prefill_draft(
                self.draft_params, jnp.asarray(padded)
            )
            self._d_cache = self._insert_rows_d(
                self._d_cache, dks, dvs, jnp.asarray(slots),
                jnp.asarray(lengths),
            )

    def _decode_cycle(self) -> None:
        """One chunk of decode steps, then host-side bookkeeping.

        The device carries tokens/done/remaining between chunks; host
        mirrors are uploaded only when admission/eviction changed them
        (``_dirty``). The chunk's [n_steps, SLOTS] token block comes back
        in one fetch — the only mandatory round trip."""
        if self._dirty:
            # mesh mode commits the control vectors replicated so every
            # chunk call (and the AOT large chunk) sees one sharding
            up = (
                (lambda a: jax.device_put(a, self._repl))
                if self._repl is not None else jnp.asarray
            )
            self._d_tokens = up(self._tokens)
            self._d_temps = up(self._temps)
            self._d_done = up(self._done)
            self._d_remaining = up(self._remaining)
            self._dirty = False
        # Chunk policy: an oversized chunk is harmless to CORRECTNESS
        # (rows freeze on device at eos/max-new; extra steps compute
        # discarded garbage), so the only reason to run a small chunk is
        # admission latency — a finished row can only be refilled at a
        # sync. Queue empty -> large chunk (amortize the link RTT);
        # requests waiting -> small chunk (free slots turn over quickly).
        with self._cv:
            queued = bool(self._queue)
        # occupancy-adaptive speculation: the first rule covering the live
        # active-slot count decides K for THIS chunk; no rule -> plain.
        # Selection happens only here, at a sync boundary, so a request
        # can cross regimes mid-stream (the invariance test pins that
        # greedy outputs don't notice).
        n_active = sum(r is not None for r in self._slot_req)
        # flavor decided BEFORE the arm. Measured mode gates "large" on
        # EVERY variant's large chunk existing: queued -> small chunk
        # regardless of k, so the bandit must consult the small-chunk arm
        # table (the two flavors' tokens/s are not comparable), and the
        # all-or-nothing gate is what guarantees the chunk actually run
        # matches the table consulted — with a partial _chunk_large (mid-
        # compile, or one variant failed to compile) a per-k fallback
        # would feed small-chunk samples to arms picked from the large
        # table, pinning that cell in exploration forever. Rule-based
        # policies never consult the table, so they keep the per-k
        # fallback and use each large chunk the moment it compiles.
        measured_large = (
            self._measured and not queued and self._chunk_large
            and all(k in self._chunk_large for k in self._variant_ks)
        )
        flavor = "large" if measured_large else "small"
        k = self._policy_k(n_active, flavor)
        if k > 0 and self._draft_stale:
            self._reprime_draft()
        # timed AFTER the re-prime: the bandit estimates each arm's
        # steady-state tokens/s, and charging the (transient, switch-only)
        # re-prime round trip into the speculative arm's sample was
        # measured to systematically sink it — every periodic probe after
        # a plain phase paid the re-prime, so the spec arm never looked
        # good at B=1 even when it was 1.5x faster sustained
        t_chunk = time.perf_counter()
        if self._measured:
            # pick/update consistency: run exactly the flavor the bandit
            # consulted (measured_large guarantees availability)
            chunk = (
                self._chunk_large[k] if flavor == "large"
                else self._chunk_small[k]
            )
        else:
            chunk = self._chunk_small[k]
            if not queued:
                large = self._chunk_large.get(k)
                if large is not None:
                    chunk, flavor = large, "large"
        cold = (k, flavor) not in self._chunk_seen
        self._chunk_seen.add((k, flavor))
        if k > 0:
            (
                self._cache, self._d_cache, self._d_tokens, self._d_done,
                self._d_remaining, self._d_key, emits, counts,
            ) = chunk(
                self.params, self.draft_params, self._cache, self._d_cache,
                self._d_tokens, self._d_done, self._d_temps,
                self._d_remaining, self._d_key,
            )
            emits = np.asarray(emits)    # [n_cycles, SLOTS, K+1]
            counts = np.asarray(counts)  # [n_cycles, SLOTS]
            self.spec_cycles_total += int((counts > 0).sum())
            self.spec_cycle_tokens_total += int(counts.sum())
            # flatten each row's valid tokens into the serving_chunk
            # [n_steps, SLOTS] layout the shared replay below consumes;
            # short rows pad by repeating their last token with count 0
            # handled via per-row step lists
            toks = None
        else:
            (
                self._cache, self._d_tokens, self._d_done,
                self._d_remaining, self._d_key, toks,
            ) = chunk(
                self.params, self._cache, self._d_tokens, self._d_done,
                self._d_temps, self._d_remaining, self._d_key,
            )
            toks = np.asarray(toks)  # [n_steps, SLOTS]; the one host sync
            if self.spec_rules:
                # a plain chunk advanced the target cache but not the
                # draft's: these rows need a re-prime before speculating
                self._draft_stale.update(
                    i for i, r in enumerate(self._slot_req) if r is not None
                )
        now = time.perf_counter()
        dt_chunk = now - t_chunk
        toks_before = self.tokens_total

        def row_tokens(i):
            """This chunk's emitted tokens for slot i, in order (frozen
            trimming replayed below, as before)."""
            if toks is not None:
                return [int(toks[k, i]) for k in range(toks.shape[0])]
            out = []
            for c in range(emits.shape[0]):
                out.extend(int(t) for t in emits[c, i, : counts[c, i]])
            return out

        # every row's carried token (frozen rows hold theirs) — keeps the
        # host mirror upload-ready for the next admission
        if toks is not None:
            self._tokens = toks[-1].astype(np.int32).copy()
        else:
            for i in range(self.slots):
                rt = row_tokens(i)
                if rt:
                    self._tokens[i] = rt[-1]
        for i, req in enumerate(self._slot_req):
            if req is None:
                continue
            # replay the device's freeze logic to pick the real tokens
            for tok in row_tokens(i):
                if self._done[i]:
                    break
                req.out.append(tok)
                self.tokens_total += 1
                self._remaining[i] -= 1
                if self._remaining[i] <= 0 or (
                    self.eos_id >= 0 and tok == self.eos_id
                ):
                    self._done[i] = True
            if self._done[i]:
                req.done_at = now
                req._finish()
                with self._cv:  # stats() sorts these concurrently
                    self.latency_samples.append(req.latency_s)
                self._slot_req[i] = None
                self._temps[i] = 0.0
                self._draft_stale.discard(i)  # evicted; nothing to re-prime
                # device `done` is already True for this row — eviction
                # alone doesn't require a re-upload
            else:
                # one wakeup per chunk per row for stream() consumers
                req._notify_progress()
        self._bandit_update(
            n_active, k, self.tokens_total - toks_before, dt_chunk,
            flavor=flavor, cold=cold,
        )
        emitted = self.tokens_total - toks_before
        if not cold and emitted > 0 and dt_chunk > 0:
            # realized tokens/s EWMA, every policy (the bandit's table is
            # measured-mode-only and per-(bucket, flavor); this is the one
            # whole-engine rate the feedback tap and /v1/stats consume).
            # Cold chunks are dropped for the same reason as in
            # _bandit_update: their dt is about the compiler.
            rate = emitted / dt_chunk
            with self._cv:  # metrics()/stats() read concurrently
                cur = self.tok_s_ewma
                self.tok_s_ewma = (
                    rate if cur is None
                    else (1 - self.BANDIT_ALPHA) * cur
                    + self.BANDIT_ALPHA * rate
                )

    def _loop(self) -> None:
        while True:
            with self._cv:
                while (
                    not self._stop
                    and not self._queue
                    and all(r is None for r in self._slot_req)
                ):
                    self._cv.wait()
                if self._stop:
                    for r in self._slot_req:
                        if r is not None:
                            r._finish("engine stopped")
                    for r in self._queue:
                        r._finish("engine stopped")
                    self._queue.clear()
                    return
            try:
                # continuous batching: fill every free slot, then run one
                # decode chunk for the active rows
                self._admit_all()
                if any(r is not None for r in self._slot_req):
                    self._decode_cycle()
            except Exception as e:  # fail requests, keep the engine alive
                log.exception("engine cycle failed")
                for i, r in enumerate(self._slot_req):
                    if r is not None:
                        r._finish(f"engine error: {e}")
                        self._slot_req[i] = None
                        self._done[i] = True
                        self._temps[i] = 0.0
                self._dirty = True
