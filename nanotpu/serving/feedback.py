"""Serving→scheduler feedback: close the placement loop (docs/serving-loop.md).

The serving engine measures its own decode tokens/s (the speculation
bandit's realized-rate EWMAs, ``Engine.metrics()``), and the scheduler's
:class:`~nanotpu.allocator.throughput.ThroughputModel` already calibrates
per-card contention online from every usage write
``Dealer.update_chip_usage`` ingests — but until this module the two
never met: placement was calibrated by chip-load proxies while the real
objective (tokens/s-per-chip, TTFT) went unmeasured. DOPPLER (PAPERS.md,
dual-policy device assignment learned from measured throughput) is the
reference for why the measured rate, not the load proxy, should drive
assignment.

Two pieces:

* :class:`ServingTap` — the metric-sync-style ingestion path. One
  replica sample is ``(node, chips, measured tok/s, expected tok/s)``;
  the tap converts the *shortfall* ``1 - measured/expected`` into the
  per-card load signal and writes it through the EXACT metric-sync
  discipline: ``Dealer.update_chip_usage(..., publish=False)`` per card,
  one ``publish_usage`` per batch. Everything downstream is the existing
  machinery, untouched: the model's ``observe`` EWMA + version bump, the
  Q16 native mirror resync, arena memo retirement, and the decision
  ledger's per-term breakdowns all reprice from measured serving
  throughput with ZERO new hot-path code (the parity pin in
  tests/test_autoscale.py holds a tap sample byte-equal to a metric-sync
  sample end to end).

* :class:`ServingMetricsSource` — the PR-11 ``TimelineSource`` for the
  serving fleet. ``sample()`` returns exactly the ``nanotpu_serving_*``
  gauge values (one producer, one honesty contract — the nanolint
  metrics-completeness pass pins :data:`_SERVING_GAUGES
  <nanotpu.metrics.serving._SERVING_GAUGES>` against
  :meth:`serving_gauge_values` both directions), so SLO objectives in
  policy.yaml's ``slo:`` section address ``ext.serving.tok_s_per_chip``
  / ``ext.serving.queue_depth`` like any built-in series.

The *provider* duck protocol: anything with ``metrics() -> dict``
carrying ``tok_s, queue_depth, active, slots, kv_occupancy, chips`` —
the real :class:`~nanotpu.serving.engine.Engine`, the sim's virtual
replica fleet (:mod:`nanotpu.sim.serve`), or
:class:`RemoteStatsProvider` polling a replica's ``/v1/stats`` over
HTTP. The key set is pinned by tests so every producer means the same
thing.

Determinism: no ambient clock or rng — ``ingest`` takes the injectable
``now`` the sim threads through, and the source only reads its
provider, so both run under the nanolint sim-determinism pass.
"""

from __future__ import annotations

import json
import logging
import urllib.request
from dataclasses import dataclass

log = logging.getLogger("nanotpu.serving.feedback")


@dataclass(frozen=True)
class ReplicaSample:
    """One replica's measured decode rate against its placement.

    ``chips`` are the card indices the replica's pod holds on ``node``
    (the dealer's assigned-chip annotation); ``expected_tok_s`` is the
    uncontended rate this placement should sustain (table value x
    per-chip rate) — the denominator that turns a measurement into a
    calibration signal."""

    node: str
    chips: tuple[int, ...]
    measured_tok_s: float
    expected_tok_s: float

    def shortfall(self) -> float:
        """``1 - measured/expected`` clamped to [0, 1]: the fraction of
        this placement's modeled throughput the replica is NOT getting —
        fed as the per-card load so the model's contention EWMA (and the
        Q16 contention term) prices it exactly like observed co-residency
        heat."""
        if self.expected_tok_s <= 0:
            return 0.0
        return min(1.0, max(
            0.0, 1.0 - self.measured_tok_s / self.expected_tok_s
        ))


class ServingTap:
    """Feed measured serving throughput into the scheduler's online
    calibration — the metric-sync-style write path (module docstring)."""

    def __init__(self, dealer):
        self.dealer = dealer
        #: replica samples ingested (all-time; introspection/tests)
        self.samples_ingested = 0
        #: per-card usage writes issued (chips x samples)
        self.cards_observed = 0

    def ingest(self, samples, now: float | None = None) -> int:
        """Write one batch of :class:`ReplicaSample`s through
        ``update_chip_usage(..., publish=False)`` + ONE
        ``publish_usage`` — the same batching discipline the metric-sync
        sweep uses, so a tap batch costs one snapshot publish, not one
        view clone per card. Samples are applied in sorted (node, chips)
        order so ingestion is deterministic regardless of caller
        iteration order. Returns the number of samples applied."""
        applied = 0
        touched: set[str] = set()
        for sample in sorted(
            samples, key=lambda s: (s.node, s.chips)
        ):
            if not sample.chips:
                continue
            load = sample.shortfall()
            for chip in sample.chips:
                self.dealer.update_chip_usage(
                    sample.node, chip, core=load, now=now, publish=False,
                )
                self.cards_observed += 1
            touched.add(sample.node)
            applied += 1
        if touched:
            self.dealer.publish_usage(tuple(sorted(touched)))
        self.samples_ingested += applied
        return applied


class ServingMetricsSource:
    """The serving fleet's ``TimelineSource`` (PR-11 duck protocol:
    ``.name`` + ``.sample()``) AND the ``nanotpu_serving_*`` gauge
    producer — one body so the timeline's ``ext.serving.*`` series and
    the scrape surface can never drift."""

    def __init__(self, provider, name: str = "serving", replicas=None):
        self.provider = provider
        self.name = name
        #: callable -> live replica count (the autoscaler's view), or
        #: None when no replica controller is attached (gauge reads 0
        #: unless the provider itself reports a fleet size)
        self._replicas = replicas

    def serving_gauge_values(self) -> dict:
        """The unlabeled ``nanotpu_serving_*`` gauge values, keyed by
        metric suffix. Keys must match ``_SERVING_GAUGES`` in
        nanotpu/metrics/serving.py exactly — the nanolint
        metrics-completeness pass pins the equivalence both ways, the
        same honesty contract the throughput/timeline/SLO gauges live
        under."""
        m = self.provider.metrics()
        chips = float(m.get("chips", 0) or 0)
        tok_s = float(m.get("tok_s", 0) or 0.0)
        if self._replicas is not None:
            replicas = float(self._replicas())
        else:
            replicas = float(m.get("replicas", 0) or 0)
        return {
            "tok_s": round(tok_s, 4),
            "tok_s_per_chip": round(tok_s / chips, 4) if chips else 0.0,
            "queue_depth": float(m.get("queue_depth", 0) or 0),
            "active_slots": float(m.get("active", 0) or 0),
            "slots": float(m.get("slots", 0) or 0),
            "kv_occupancy": round(float(m.get("kv_occupancy", 0) or 0), 6),
            "chips": chips,
            "replicas": replicas,
            "ttft_p99_ms": round(
                float(m.get("ttft_p99_ms", 0) or 0), 2
            ),
        }

    def sample(self) -> dict:
        return self.serving_gauge_values()


class RemoteStatsProvider:
    """Provider over a replica's ``/v1/stats`` endpoint — the
    production transport for a scheduler-side timeline source
    (``cmd/main --serving-stats-url``). A failed poll raises; the
    timeline's source guard turns that into an honest ``{"error": 1}``
    section instead of a stalled last-good value."""

    def __init__(self, url: str, timeout_s: float = 2.0):
        self.url = url
        self.timeout_s = float(timeout_s)

    def metrics(self) -> dict:
        with urllib.request.urlopen(
            self.url, timeout=self.timeout_s
        ) as resp:
            stats = json.load(resp)
        return {
            "tok_s": stats.get("tok_s", 0) or 0,
            "queue_depth": stats.get("queued", 0) or 0,
            "active": stats.get("active", 0) or 0,
            "slots": stats.get("slots", 0) or 0,
            "kv_occupancy": stats.get("kv_occupancy", 0) or 0,
            "chips": stats.get("chips", 1) or 1,
            "ttft_p99_ms": stats.get("ttft_p99_ms", 0) or 0,
        }
