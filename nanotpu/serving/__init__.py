from nanotpu.serving.engine import Engine, Request, SlotCache

__all__ = ["Engine", "Request", "SlotCache"]
