"""Serving-engine benchmark: mixed-length request replay on the real chip.

Prints ONE JSON line with engine throughput and TTFT/latency percentiles.
The workload: a burst of mixed-length prompts plus a trailing arrival
stream, so the engine exercises both the full-batch steady state and
continuous admission mid-decode.

  python -m nanotpu.serving.bench                 # bf16 flagship
  python -m nanotpu.serving.bench --int8          # weight-only int8
  python -m nanotpu.serving.bench --preset tiny   # CPU smoke
"""

from __future__ import annotations

import argparse
import json
import random
import time

from nanotpu.serving.server import build_engine


def percentile(xs: list[float], p: float) -> float | None:
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(p * len(xs)))]


def run(preset: str, slots: int, max_len: int, int8: bool, requests: int,
        max_new: int, seed: int = 0, kv_int8: bool = False) -> dict:
    rng = random.Random(seed)
    engine = build_engine(preset, slots, max_len, int8, kv_int8=kv_int8)
    cfg = engine.cfg
    lengths = [64, 128, 256, 512, 1024]
    lengths = [l for l in lengths if l < max_len - max_new] or [8]

    def mk_prompt(n):
        return [rng.randrange(1, cfg.vocab_size) for _ in range(n)]

    # warmup: compile prefill per bucket + the decode chunks, untimed
    for l in lengths:
        engine.generate(mk_prompt(l), 2)
    engine.wait_warm(600)

    t0 = time.perf_counter()
    reqs = []
    # half the requests burst at t=0 (queue > slots: tests admission under
    # load), the rest trickle in while earlier ones decode
    burst = requests // 2
    for i in range(burst):
        reqs.append(engine.submit(mk_prompt(rng.choice(lengths)), max_new))
    for i in range(requests - burst):
        time.sleep(0.02)
        reqs.append(engine.submit(mk_prompt(rng.choice(lengths)), max_new))
    for r in reqs:
        assert r.wait(1200), f"request {r.id} timed out"
        assert r.error is None, r.error
    wall = time.perf_counter() - t0
    engine.stop()

    gen_tokens = sum(len(r.out) for r in reqs)
    ttfts = [r.ttft_s for r in reqs if r.ttft_s is not None]
    lats = [r.latency_s for r in reqs if r.latency_s is not None]
    return {
        "preset": preset,
        "int8": int8,
        "kv_int8": kv_int8,
        "slots": slots,
        "requests": requests,
        "max_new_tokens": max_new,
        "prompt_lengths": lengths,
        "wall_s": round(wall, 3),
        "decode_tokens_per_s": round(gen_tokens / wall, 1),
        "ttft_p50_ms": round(percentile(ttfts, 0.5) * 1e3, 1),
        "ttft_p99_ms": round(percentile(ttfts, 0.99) * 1e3, 1),
        "latency_p50_ms": round(percentile(lats, 0.5) * 1e3, 1),
        "latency_p99_ms": round(percentile(lats, 0.99) * 1e3, 1),
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser("nanotpu-serve-bench")
    p.add_argument("--preset", default="flagship")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--max-len", type=int, default=2048)
    p.add_argument("--int8", action="store_true")
    p.add_argument("--kv-int8", action="store_true")
    p.add_argument("--requests", type=int, default=48)
    p.add_argument("--max-new", type=int, default=128)
    args = p.parse_args(argv)
    out = run(args.preset, args.slots, args.max_len, args.int8,
              args.requests, args.max_new, kv_int8=args.kv_int8)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
