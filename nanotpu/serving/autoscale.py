"""Demand-driven replica autoscaler (docs/serving-loop.md).

Scales a fleet of sharded decode replica pods (tp x fsdp 8B decode —
the PR-3 serving patterns) against MEASURED demand: slot pressure
(queued + active requests vs provisioned slots) decides the desired
replica count, scale-up submits fresh replica pods through the normal
admission path (the r12 batch admitter drains them in one joint native
solve when enabled), and scale-down DRAINS: a victim replica stops
taking new requests and finishes its in-flight ones under a deadline
lease on the r10 recovery plane — the same lease/eviction machinery
backfill pods live under, so an overstaying replica is reclaimed by the
plane's lease sweep instead of a second expiry path.

Division of labor:

* the autoscaler DECIDES and writes pods (create / delete through the
  resilient client); it never touches chip accounting — placement stays
  with the scheduler, release with the informer path.
* the *signal* is a :class:`ServingSignal` snapshot the driver supplies:
  the sim builds it from the virtual replica fleet
  (:mod:`nanotpu.sim.serve`), production builds it from replica
  ``/v1/stats`` polls (:class:`AutoscaleLoop` takes a ``signal_fn``).
* drain completion is demand-driven (a draining replica with zero
  in-flight requests is deleted on the next cycle); the deadline is
  enforced by the recovery plane's drain-lease sweep when a plane is
  attached, by the autoscaler itself otherwise.

Victim choice is feedback-aware: the replica with the LOWEST measured
tokens/s drains first (ties by name), so a fleet calibrated by the
serving tap sheds its degraded placements at every trough and re-places
them against the repriced score table at the next peak — the
DOPPLER-style loop closure the certification scenario measures.

Determinism: the clock is injectable, decisions iterate sorted
structures only, and the one rng hook (none today) would live on the
sim's dedicated stream — the module runs under the nanolint
sim-determinism pass like the recovery plane it composes with.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass, field

from nanotpu import types
from nanotpu.analysis.witness import make_lock
from nanotpu.k8s.objects import make_container, make_pod

log = logging.getLogger("nanotpu.serving.autoscale")


@dataclass(frozen=True)
class AutoscaleConfig:
    """Knobs (scenario ``serving.autoscale`` section / cmd flags)."""

    min_replicas: int = 1
    max_replicas: int = 8
    #: decode slots one replica provisions (sizing unit for desired())
    slots_per_replica: int = 64
    #: desired fleet keeps (queued + active) at this fraction of
    #: provisioned slots — the headroom that absorbs a diurnal ramp
    #: between autoscale cycles
    target_utilization: float = 0.75
    #: min seconds between scale-ups / scale-downs (per direction:
    #: an up-ramp must not be throttled by a recent down-step)
    up_cooldown_s: float = 0.0
    down_cooldown_s: float = 5.0
    #: a draining replica may finish in-flight requests this long; past
    #: it the drain lease expires and the pod is deleted mid-flight
    drain_deadline_s: float = 10.0
    #: per-container chip demand of one replica pod (400 == 4 whole
    #: chips, one v5p host: tp=4 sharded decode)
    replica_percent: int = 400
    #: capacity-recovery priority class stamped on replica pods (serving
    #: outranks best-effort batch, yields to training gangs above it)
    priority: int = 50
    namespace: str = "default"
    pod_prefix: str = "serve-8b"


@dataclass(frozen=True)
class ServingSignal:
    """One demand snapshot the driver hands to :meth:`run_once`."""

    #: requests queued fleet-wide, not yet admitted to any slot
    queued: int
    #: replica pod name -> {"active": in-flight requests,
    #: "tok_s": measured decode rate} (absent replicas read as idle)
    replicas: dict = field(default_factory=dict)

    def active_total(self) -> int:
        return sum(
            int(r.get("active", 0)) for r in self.replicas.values()
        )


def make_replica_pod(name: str, config: AutoscaleConfig,
                     uid: str = ""):
    """One sharded-decode replica pod spec — shared by the autoscaler's
    scale-up path and the sim's static-fleet bootstrap so the OFF side
    of an A/B schedules byte-identical pods."""
    return make_pod(
        name,
        namespace=config.namespace,
        uid=uid,
        containers=[make_container(
            "decode",
            {types.RESOURCE_TPU_PERCENT: config.replica_percent},
        )],
        annotations={
            types.ANNOTATION_SERVING_REPLICA: "1",
            types.ANNOTATION_PRIORITY: str(config.priority),
        },
    )


@dataclass
class _Replica:
    """Autoscaler-tracked state for one replica pod."""

    name: str
    uid: str
    created_t: float
    node: str = ""          # set when the scheduler binds it
    draining: bool = False
    drain_deadline: float = 0.0


class ReplicaAutoscaler:
    """See module docstring. One instance per serving fleet; the driver
    (sim ``autoscale_cycle`` events or :class:`AutoscaleLoop`) owns the
    cycle cadence."""

    def __init__(self, client, config: AutoscaleConfig | None = None,
                 plane=None, clock=time.monotonic, uid_of=None):
        self.client = client
        self.config = config or AutoscaleConfig()
        #: uid source for fresh replica pods. Real k8s assigns uids
        #: server-side, so production leaves this None (empty uid in the
        #: create body); the sim's fake apiserver stores bodies verbatim,
        #: so it injects its own deterministic uid counter here.
        self.uid_of = uid_of
        if self.config.min_replicas < 0 or \
                self.config.max_replicas < self.config.min_replicas:
            raise ValueError(
                "autoscale needs 0 <= min_replicas <= max_replicas, got "
                f"{self.config.min_replicas}/{self.config.max_replicas}"
            )
        #: the r10 recovery plane: drain deadlines become leases its
        #: sweep enforces; None = the autoscaler enforces them itself
        self.plane = plane
        self.clock = clock
        self._lock = make_lock("ReplicaAutoscaler._lock")
        self._replicas: dict[str, _Replica] = {}
        self._seq = 0
        self._last_up = float("-inf")
        self._last_down = float("-inf")
        # action counters (status() / the sim report; monotonic)
        self.scale_ups = 0
        self.scale_downs = 0
        self.drains_started = 0
        self.drains_completed = 0
        self.drain_kills = 0

    # -- introspection -----------------------------------------------------
    def replica_count(self) -> int:
        """Live replicas (bound + pending + draining) — the
        ``nanotpu_serving_replicas`` gauge."""
        with self._lock:
            return len(self._replicas)

    def replica_names(self) -> list[str]:
        with self._lock:
            return sorted(self._replicas)

    def status(self) -> dict:
        with self._lock:
            reps = {
                name: {
                    "node": r.node, "draining": r.draining,
                }
                for name, r in sorted(self._replicas.items())
            }
        return {
            "replicas": reps,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "drains_started": self.drains_started,
            "drains_completed": self.drains_completed,
            "drain_kills": self.drain_kills,
        }

    # -- sizing policy -----------------------------------------------------
    def desired(self, signal: ServingSignal) -> int:
        """Replicas needed to hold (queued + active) at
        ``target_utilization`` of provisioned slots, clamped to
        [min, max]."""
        cfg = self.config
        demand = signal.queued + signal.active_total()
        per = max(1.0, cfg.slots_per_replica * cfg.target_utilization)
        return max(
            cfg.min_replicas,
            min(cfg.max_replicas, math.ceil(demand / per)),
        )

    # -- the control cycle -------------------------------------------------
    def run_once(self, now: float | None = None,
                 signal: ServingSignal | None = None) -> dict:
        """One autoscale cycle. Returns::

            {"created": [Pod, ...],       # fresh replica pods submitted
             "deleted": [(name, uid)],    # drained/killed pods removed
             "draining": [name, ...],     # drains STARTED this cycle
             "actions": [(kind, detail)]} # journal-ready, in order
        """
        now = self.clock() if now is None else now
        signal = signal or ServingSignal(queued=0)
        cfg = self.config
        actions: list[tuple[str, str]] = []
        created: list = []
        deleted: list[tuple[str, str]] = []
        draining: list[str] = []

        self._reconcile(now, actions)
        self._finish_drains(now, signal, actions, deleted)

        with self._lock:
            live = sorted(
                name for name, r in self._replicas.items()
                if not r.draining
            )
        desired = self.desired(signal)
        if desired > len(live) and now - self._last_up >= cfg.up_cooldown_s:
            self._last_up = now
            self.scale_ups += 1
            for _ in range(desired - len(live)):
                pod = self._create_replica(now, actions)
                if pod is not None:
                    created.append(pod)
        elif desired < len(live) and \
                now - self._last_down >= cfg.down_cooldown_s:
            self._last_down = now
            self.scale_downs += 1
            for name in self._drain_victims(
                live, len(live) - desired, signal
            ):
                self._start_drain(name, now, signal, actions, deleted)
                draining.append(name)
        return {
            "created": created, "deleted": deleted,
            "draining": draining, "actions": actions,
        }

    # -- cycle internals ---------------------------------------------------
    def _reconcile(self, now: float, actions) -> None:
        """Sync the registry with the cluster: learn bind placements,
        drop pods that vanished out from under us (node death, operator
        delete, the recovery plane's drain-lease sweep) — production
        has no driver-side bookkeeping to lean on, so the cluster is
        the source of truth."""
        try:
            pods = self.client.list_pods()
        except Exception as e:
            log.warning("autoscale reconcile list failed: %s", e)
            return
        seen: dict[str, object] = {}
        for pod in pods:
            ann = pod.annotations
            if ann.get(types.ANNOTATION_SERVING_REPLICA) != "1":
                continue
            if pod.namespace != self.config.namespace:
                continue
            seen[pod.name] = pod
        prefix = self.config.pod_prefix + "-"
        with self._lock:
            for name in sorted(set(self._replicas) - set(seen)):
                rep = self._replicas.pop(name)
                if rep.draining:
                    # attribute the vanish honestly: past the deadline
                    # it was the plane's lease sweep killing an
                    # overstayer mid-flight, not a graceful completion
                    # — drain_kills must not read 0 just because a
                    # plane (rather than we) enforced the deadline
                    if rep.drain_deadline and now >= rep.drain_deadline:
                        self.drain_kills += 1
                    else:
                        self.drains_completed += 1
                actions.append(("replica-gone", name))
            for name, pod in sorted(seen.items()):
                rep = self._replicas.get(name)
                if rep is None:
                    # adopted (e.g. a pre-existing static fleet handed
                    # to the autoscaler, or our own pods after a
                    # restart): manage it like our own — and advance
                    # the name counter past it, or the next scale-up
                    # would collide with an adopted name (409 from a
                    # real apiserver) and starve a post-restart ramp
                    rep = self._replicas[name] = _Replica(
                        name=name, uid=pod.uid, created_t=0.0,
                    )
                    if name.startswith(prefix) and \
                            name[len(prefix):].isdigit():
                        self._seq = max(
                            self._seq, int(name[len(prefix):])
                        )
                    actions.append(("replica-adopt", name))
                if pod.node_name and rep.node != pod.node_name:
                    rep.node = pod.node_name
                    actions.append(
                        ("replica-bound", f"{name} @ {pod.node_name}")
                    )

    def _create_replica(self, now: float, actions):
        with self._lock:
            self._seq += 1
            name = f"{self.config.pod_prefix}-{self._seq}"
        pod = make_replica_pod(
            name, self.config,
            uid=self.uid_of() if self.uid_of is not None else "",
        )
        try:
            server_pod = self.client.create_pod(pod)
        except Exception as e:
            log.warning("replica create %s failed: %s", name, e)
            actions.append(("replica-create-failed", name))
            return None
        with self._lock:
            self._replicas[name] = _Replica(
                name=name, uid=server_pod.uid, created_t=now,
            )
        actions.append(("scale-up", name))
        return server_pod

    def _drain_victims(self, live: list[str], n: int,
                       signal: ServingSignal) -> list[str]:
        """Lowest measured tokens/s first (the feedback-aware choice:
        degraded placements shed at the trough), unbound replicas before
        anything (they serve nothing), ties by name."""
        def key(name: str):
            with self._lock:
                bound = bool(self._replicas[name].node)
            stats = signal.replicas.get(name) or {}
            return (bound, float(stats.get("tok_s", 0.0)), name)

        return sorted(live, key=key)[:n]

    def _start_drain(self, name: str, now: float,
                     signal: ServingSignal, actions, deleted) -> None:
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None or rep.draining:
                return
            bound = bool(rep.node)
            stats = signal.replicas.get(name) or {}
            idle = int(stats.get("active", 0)) == 0
            if not bound or idle:
                # nothing in flight (or never scheduled): skip the drain
                # window and delete outright
                rep_uid = rep.uid
            else:
                rep.draining = True
                rep.drain_deadline = (
                    now + self.config.drain_deadline_s
                )
                rep_uid = None
        if rep_uid is not None:
            self._delete(name, rep_uid, "scale-down", actions, deleted)
            return
        self.drains_started += 1
        actions.append(("drain-start", name))
        if self.plane is not None:
            with self._lock:
                rep = self._replicas.get(name)
                node, uid, deadline = (
                    rep.node, rep.uid, rep.drain_deadline
                ) if rep is not None else ("", "", 0.0)
            if node:
                self.plane.note_drain(
                    uid, name, self.config.namespace, node, deadline,
                )

    def _finish_drains(self, now: float, signal: ServingSignal,
                       actions, deleted) -> None:
        with self._lock:
            drains = [
                (name, r.uid, r.drain_deadline)
                for name, r in sorted(self._replicas.items())
                if r.draining
            ]
        for name, uid, deadline in drains:
            stats = signal.replicas.get(name) or {}
            if int(stats.get("active", 0)) == 0:
                self._delete(name, uid, "drain-complete", actions,
                             deleted, drained=True)
            elif self.plane is None and now >= deadline:
                # no recovery plane to sweep the lease: enforce the
                # deadline ourselves (in-flight requests are the
                # driver's to retry)
                self.drain_kills += 1
                self._delete(name, uid, "drain-expired", actions, deleted)

    def _delete(self, name: str, uid: str, kind: str, actions,
                deleted, drained: bool = False) -> None:
        try:
            self.client.delete_pod(self.config.namespace, name)
        except Exception as e:
            log.warning("replica delete %s failed: %s", name, e)
            actions.append(("replica-delete-failed", name))
            return
        with self._lock:
            self._replicas.pop(name, None)
        if drained:
            self.drains_completed += 1
        if self.plane is not None:
            self.plane.pod_gone(uid)
        actions.append((kind, name))
        deleted.append((name, uid))


class AutoscaleLoop:
    """Production driver: one daemon thread running
    ``autoscaler.run_once(clock(), signal_fn())`` every ``period_s``.
    ``signal_fn`` supplies the demand snapshot (e.g. aggregated replica
    ``/v1/stats`` polls via
    :class:`~nanotpu.serving.feedback.RemoteStatsProvider`). The sim
    never uses this — it steps the autoscaler deterministically through
    ``autoscale_cycle`` events."""

    def __init__(self, autoscaler: ReplicaAutoscaler, signal_fn,
                 period_s: float = 2.0, gate=None):
        self.autoscaler = autoscaler
        self.signal_fn = signal_fn
        self.period_s = period_s
        #: optional write gate (docs/ha.md "Degraded mode"): a callable
        #: answering False pauses cycles — every scale decision is an
        #: apiserver write, doomed while the link is down. None ==
        #: always run (the same contract RecoveryLoop/BatchLoop honor).
        self.gate = gate
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="autoscale",
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                if self.gate is not None and not self.gate():
                    continue  # degraded: skip the cycle, stay alive
                self.autoscaler.run_once(
                    self.autoscaler.clock(), self.signal_fn()
                )
            except Exception:  # the loop must outlive any one cycle
                log.exception("autoscale cycle failed")
