from nanotpu.dealer.dealer import BindError, Dealer, plan_from_pod
from nanotpu.dealer.nodeinfo import NodeInfo
from nanotpu.dealer.usage import ChipUsageSample, UsageStore

__all__ = ["Dealer", "BindError", "plan_from_pod", "NodeInfo", "UsageStore", "ChipUsageSample"]
