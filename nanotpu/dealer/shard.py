"""Per-pool snapshot shards: the dealer's RCU publication domains.

The r6 hot path published ONE ``_Snapshot`` for the whole fleet: every
commit advanced every cached candidate-list view, and every structural
change dropped them all. That is fine at 256 hosts and wrong at 4096 —
"millions of users" means Filter/Prioritize over thousands of nodes per
cycle, and a monolithic arena makes every bind pay for the whole fleet
(ISSUE r7 tentpole; Tesserae's partitioned-placement result is the
reference for why splitting the search space does not cost placement
quality — scores are per-node pure functions, so a partition merge is
exact, not approximate).

A :class:`_Shard` is one independent publication domain keyed by **slice
family** (TPU generation + the slice-label family, i.e. the pool): it
owns its own RCU-published snapshot chain, publisher lock, commit
sequence, structural epoch, and :class:`~nanotpu.dealer.perf.PerfCounters`
— so a bind on pool A republishes pool A's views only (an incremental
delta), pool B's readers never even observe a generation bump, and
Filter/Prioritize fan scoring out across shards in parallel native calls.

Merge determinism: per-shard score lists reassemble by candidate
position (exact), and consumers that want "the best k hosts" use
:func:`merge_top_k`, a single deterministic reduce ordered by
``(-score, name)`` — shard count can never change the answer, which is
what the sharded-vs-single parity pin in tests/test_shard.py asserts
byte-for-byte. ``splice_filter_payloads``/``splice_priorities_payloads``
merge per-shard fused ``nanotpu_score_render`` responses bytewise; they
require each shard's candidates to form one contiguous run of the request
order (the caller checks), so the merged body is byte-identical to what a
single shard covering every candidate would have rendered.

Every shard lock is built through the witness factories
(docs/static-analysis.md): the runtime lock-order witness and the static
lock-discipline pass both see ``_Shard._publish_lock``, and production
code never holds two shard publish locks at once (``Dealer._republish``
publishes shards strictly one at a time), so no cross-shard order exists
to invert.
"""

from __future__ import annotations

import re

from nanotpu.analysis.witness import make_lock
from nanotpu.dealer.perf import PerfCounters

#: the shard key of an unsharded dealer (one shard holds the whole fleet)
DEFAULT_SHARD_KEY = "all"

#: ``slice-3`` / ``v4slice-0`` -> family ``slice`` / ``v4slice``: slices of
#: one pool share a label prefix and differ only in the trailing index
_TRAILING_INDEX = re.compile(r"-\d+$")


def family_of(slice_name: str) -> str:
    """The slice-family (pool) component of a shard key: the slice label
    with its trailing ``-<index>`` stripped. Empty label -> empty family
    (unlabeled nodes pool together per generation)."""
    if not slice_name:
        return ""
    return _TRAILING_INDEX.sub("", slice_name)


def shard_key_of(info) -> str:
    """Shard key for a NodeInfo: ``<generation>/<slice family>`` — one
    shard per pool, the partition the fleet factory (sim/fleet.py) and
    real multi-pool clusters both produce."""
    return f"{info.generation}/{family_of(info.slice_name)}"


class _Snapshot:
    """One RCU-published, immutable view of a shard's placement state.

    Read verbs (Filter/Prioritize) consume whatever the owning shard's
    ``_published`` points at WITHOUT the dealer lock: the reference swap
    is atomic under the GIL, ``nodes``/``non_tpu`` are never mutated
    after publication, and each cached candidate-list view is a frozen
    :class:`~nanotpu.dealer.batch.BatchScorer` whose row arrays are
    written once. Writers build a successor snapshot after their commit
    and swap it in (``Dealer._republish_shard``) — readers never contend
    with them and never trigger synchronous rebuilds; at worst they score
    against the previous generation, the same staleness window the old
    lock-and-probe path already had (kube-scheduler's bind re-checks
    under the node lock either way).

    ``views`` maps a candidate-name tuple to ``(scorer, known names,
    non-TPU names, name->row index)`` — or ``None`` when that list cannot
    take the batch path in this snapshot (cold/unknown candidates,
    heterogeneous pool, native unavailable). Caching the None verdict is
    sound because anything that could change it (a node materializing, a
    topology change) is structural and structural publishes start with
    empty views. Reader threads insert into ``views`` lazily; dict ops
    are atomic under the GIL and a racing double-build is just wasted
    work.
    """

    __slots__ = ("gen", "nodes", "non_tpu", "views")

    def __init__(self, gen: int, nodes: dict, non_tpu: frozenset):
        self.gen = gen
        self.nodes = nodes
        self.non_tpu = non_tpu
        self.views: dict[tuple, tuple | None] = {}


class _Shard:
    """One publication domain: snapshot chain + publisher state + perf.

    All fields except ``perf`` and ``key`` are written under
    ``_publish_lock`` (``epoch`` under the dealer lock); ``_published``
    is read lock-free by verbs. ``perf`` may be the dealer's own counters
    (single-shard mode aliases them so existing attribution reads are
    unchanged) or shard-private ones (sharded mode, where per-shard
    attribution is the point)."""

    __slots__ = (
        "key", "perf", "epoch", "_publish_lock", "_published",
        "_pub_epoch", "_commit_seq", "_pending", "_pending_all",
        "_pending_lock",
    )

    def __init__(self, key: str, perf: PerfCounters | None = None):
        self.key = key
        self.perf = perf or PerfCounters()
        #: bumped (under the dealer lock) on any structural change to this
        #: shard's membership; a mismatch with ``_pub_epoch`` makes the
        #: next publish rebuild the mapping and drop the views
        self.epoch = 0
        self._publish_lock = make_lock("_Shard._publish_lock")
        self._published = _Snapshot(0, {}, frozenset())
        self._pub_epoch = -1
        #: bumped at the START of every publish attempt on this shard,
        #: including skipped ones: lets a reader detect that a commit
        #: raced its lazy view build (see Dealer._view_for)
        self._commit_seq = 0
        #: commit-pipeline coalescing state (docs/bind-pipeline.md):
        #: changed node names whose snapshot publish has been ENQUEUED but
        #: not yet swapped — a publish leader (or the next reader) drains
        #: them into ONE snapshot swap. ``_pending_all`` marks a queued
        #: probe-everything publish (structural sweep / cold-node warmup).
        #: Guarded by ``_pending_lock`` (tiny, compute-only critical
        #: sections; in nanolint's HOT_LOCKS); both are read lock-free as
        #: a truthiness fast path by readers.
        self._pending: set[str] = set()
        self._pending_all = False
        self._pending_lock = make_lock("_Shard._pending_lock")


def merge_top_k(scored_lists, k: int | None = None) -> list[tuple[str, int]]:
    """THE deterministic top-k reduce over per-shard ``(name, score)``
    lists: score descending, then name ascending — a total order with no
    hash-dependent ties, so the merge is independent of shard count,
    shard iteration order, and per-shard list order. ``k=None`` returns
    the full merged ranking. The order is byte-stable across shard
    splits for EVERY rater because scores are bit-deterministic
    integers — including the throughput model since ABI 7, whose
    fixed-point native evaluation (docs/scoring.md) leaves no float
    rounding for a platform or shard boundary to perturb."""
    merged: list[tuple[str, int]] = []
    for scored in scored_lists:
        merged.extend(scored)
    merged.sort(key=lambda ns: (-ns[1], ns[0]))
    if k is None:
        return merged
    return merged[:k]


# -- bytewise payload splicing (the sharded fused-render merge) ------------
#
# Each per-shard payload comes from our own native renderer
# (native/allocator.cc nanotpu_render_*), whose frame is fixed:
# filter  = {"NodeNames":[...],"FailedNodes":{...},"Error":""}
# priorities = [{"Host":...,"Score":...},...]
# The frame byte-patterns below cannot occur INSIDE a payload string:
# any '"' within a JSON-encoded node name is escaped to '\"', so the
# unescaped '],"FailedNodes":{' run only ever appears as the frame.

_FILTER_HEAD = b'{"NodeNames":['
_FILTER_MID = b'],"FailedNodes":{'
_FILTER_TAIL = b'},"Error":""}'


def splice_filter_payloads(payloads: list[bytes]) -> bytes | None:
    """Merge per-shard ExtenderFilterResult bodies into the body a single
    shard over the concatenated candidate list would render. Caller
    guarantees the shard runs are contiguous and in request order; None
    on any frame surprise (caller falls back to the list path)."""
    names: list[bytes] = []
    fails: list[bytes] = []
    for p in payloads:
        if not (p.startswith(_FILTER_HEAD) and p.endswith(_FILTER_TAIL)):
            return None
        mid = p.find(_FILTER_MID, len(_FILTER_HEAD))
        if mid < 0:
            return None
        inner_names = p[len(_FILTER_HEAD):mid]
        inner_fails = p[mid + len(_FILTER_MID):-len(_FILTER_TAIL)]
        if inner_names:
            names.append(inner_names)
        if inner_fails:
            fails.append(inner_fails)
    return (
        _FILTER_HEAD + b",".join(names)
        + _FILTER_MID + b",".join(fails) + _FILTER_TAIL
    )


def splice_priorities_payloads(payloads: list[bytes]) -> bytes | None:
    """Merge per-shard HostPriorityList bodies (see
    :func:`splice_filter_payloads` for the contract)."""
    inner: list[bytes] = []
    for p in payloads:
        if not (p.startswith(b"[") and p.endswith(b"]")):
            return None
        body = p[1:-1]
        if body:
            inner.append(body)
    return b"[" + b",".join(inner) + b"]"
