"""Fleet ICI-fragmentation: the two-level metric shared by the sim
report's certification walk and the telemetry timeline's fleet tap.

Fragmentation is two-level, matching how a gang actually lands: chips
within a host must be ICI-contiguous on the host torus
(:meth:`nanotpu.topology.Torus.compactness`), and a multi-host gang's
hosts must be adjacent on the slice host-grid (the same
``_grid_compactness`` the gang scorer awards its bonus with). Each level
is a free-chip-weighted mean compactness of the FREE capacity; the fleet
score is ``1 - intra * inter``, so 0.0 means every free chip sits in a
contiguous block on a contiguous run of hosts (a new gang can land on
ICI) and values toward 1.0 mean free capacity is scattered fragments no
sub-torus demand can use. Host-level matters most: a 4-chip host's free
set is almost always compact, but churn strews free HOSTS across the
slice grid.

This lives in the dealer package (not the sim) because the timeline
samples it on every production tick — the sim imports it, never the
other way around.
"""

from __future__ import annotations

from nanotpu.dealer.gang import _grid_compactness
from nanotpu.topology import parse_slice_coords


def fragmentation_of(dealer) -> float:
    """Fleet ICI-fragmentation in [0, 1] from the dealer's live accounting
    (0 == all free capacity contiguous; see module docstring)."""
    snap = dealer.debug_snapshot()
    intra_weighted = 0.0
    total_free = 0
    # slice name -> (free-host coords, free whole chips on them)
    slices: dict[str, tuple[list, int]] = {}
    for name in sorted(snap["node_infos"]):
        info = snap["node_infos"][name]
        free = info.chips.whole_free_indexes()
        if not free:
            continue
        intra_weighted += info.chips.torus.compactness(free) * len(free)
        total_free += len(free)
        # nodes without slice labels are their own singleton slice
        key = info.slice_name or f"__solo__{name}"
        try:
            coord = parse_slice_coords(info.slice_coords)
        except Exception:
            coord = (0, 0, 0)
        coords, chips = slices.get(key, ([], 0))
        coords.append(coord)
        slices[key] = (coords, chips + len(free))
    if total_free == 0:
        return 0.0  # nothing free: nothing to fragment
    inter_weighted = sum(
        _grid_compactness(coords) * chips
        for coords, chips in slices.values()
    )
    intra = intra_weighted / total_free
    inter = inter_weighted / total_free
    return round(1.0 - intra * inter, 4)
