"""Batch admission: one fused native solve for the whole pending queue.

The extender contract is pod-at-a-time and it shows: every pending pod
costs a full Filter -> Prioritize -> Bind round trip, so a 4096-host
fleet clears ~hundreds of pods/s through the read path while the r7
bind-storm row proves the write path alone absorbs ~1.5k binds/s — the
per-pod solve, not the committer, is the bottleneck. Batched placement
(Tesserae; Gavel's round-based joint solve over the per-(shape x
slice-type) throughput table) fixes both the throughput and the
packing-quality half: arrival order is a bad packing order, and a solver
that sees the whole batch can best-fit it.

:class:`BatchAdmitter` is that mode, strictly OPT-IN (``dealer.batch``
is None by default and every existing path is byte-identical without
it):

* **drain** — :meth:`collect` pulls the controller's view of
  unscheduled TPU pods (the coalescing queue's cache), minus pods
  already mid-bind (barrier-parked gang members hold reservations; their
  not-yet-bound SIBLINGS are exactly what the batch serves, completing
  the barrier);
* **solve** — :meth:`plan` sorts the batch into the canonical solve
  order (namespace, name, uid — so the same pending SET in any arrival
  order yields the identical assignment, byte for byte) and hands it to
  ``Dealer.pack_pods``: one ``nanotpu_batch_pack`` crossing per shard
  (ABI 8) packing all K demands jointly against the frozen Q16 scoring
  rows with in-C scratch occupancy, then the deterministic cross-shard
  reduce (score desc, name asc — ``merge_top_k``'s total order);
* **commit** — winners bind through the UNCHANGED r7 write path
  (``Dealer.bind``: reserve -> annotate -> bind subresource, publish
  coalescing, per-member rollback). Strict-gang winners are dispatched
  on their own threads (kube-scheduler's async-bind shape: every member
  must be able to park at the barrier concurrently) and never awaited;
  everything else commits inline. Losers — no feasible candidate, bind
  failure, or no batch plan at all — fall back to the pod-at-a-time
  path untouched.

Every cycle is attributed (``PerfCounters.batch_*``), audited (typed
ledger reason ``batch_packed`` + the per-pod batch cycle id, sampling-
gated like the assume-TTL sweeper), and surfaced on
``/debug/decisions``'s ``batch`` field. See docs/batch-admission.md for
the solve-order/lookahead/determinism/fallback contracts.
"""

from __future__ import annotations

import logging
import threading

from nanotpu.analysis.witness import make_lock
from nanotpu.dealer.dealer import BindError
from nanotpu.obs.decisions import REASON_BATCH_PACKED
from nanotpu.utils import pod as podutil

log = logging.getLogger("nanotpu.admit")

#: default finalists re-ranked per pick (docs/batch-admission.md "The
#: lookahead rule"): the top L candidates by (score desc, index asc) are
#: re-ranked by fewest post-placement whole-free chips — best-fit, which
#: preserves whole hosts for gangs. 1 == the exact pod-at-a-time argmax.
DEFAULT_LOOKAHEAD = 4

#: default cap on demands per cycle: bounds the native crossing's scratch
#: work and the commit burst behind it.
DEFAULT_MAX_BATCH = 256


class AdmitResult:
    """One batch-admission cycle's outcome, in solve order."""

    __slots__ = ("cycle", "planned", "bound", "dispatched", "failed",
                 "unplaced", "deferred", "fell_back")

    def __init__(self, cycle: int):
        self.cycle = cycle
        #: (pod, node, score) picks the joint solve produced
        self.planned: list[tuple] = []
        #: (pod, node, score) whose bind committed inline
        self.bound: list[tuple] = []
        #: (pod, node, score) strict-gang winners handed to async bind
        #: threads (outcome arrives through the normal gang machinery)
        self.dispatched: list[tuple] = []
        #: (pod, BindError) whose commit failed (accounting rolled back
        #: by Dealer.bind; the pod-at-a-time path retries them)
        self.failed: list[tuple] = []
        #: pods the joint solve found no feasible candidate for
        self.unplaced: list = []
        #: pods beyond ``max_batch`` this cycle never offered to the
        #: solve — NOT fallbacks: the next cycle (or a re-post) serves
        #: them, and the route reports them so no pod silently vanishes
        self.deferred: list = []
        #: True when no batch plan existed at all (cold candidates, hook
        #: rater, recovery plane, native off) and EVERY pod fell back
        self.fell_back = False


class BatchAdmitter:
    """See module docstring. One instance per dealer; attach via
    ``dealer.batch = admitter`` (the /debug surface reads it there)."""

    def __init__(self, dealer, controller=None,
                 lookahead: int = DEFAULT_LOOKAHEAD,
                 max_batch: int = DEFAULT_MAX_BATCH, obs=None,
                 cycle_base: int = 0):
        if lookahead < 1:
            raise ValueError(f"lookahead must be >= 1, got {lookahead!r}")
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch!r}")
        if cycle_base < 0:
            raise ValueError(f"cycle_base must be >= 0, got {cycle_base!r}")
        self.dealer = dealer
        self.controller = controller
        self.lookahead = int(lookahead)
        self.max_batch = int(max_batch)
        #: explicit observability bundle for the audit trail; None
        #: falls back to the dealer's CURRENT bundle at audit time (the
        #: serving layer attaches its bundle to the dealer after
        #: construction, and an admitter built earlier must not freeze
        #: that None in)
        self._obs = obs
        #: guards the cycle counter + last-cycle summary ONLY — never
        #: held across the solve or any apiserver write (nanolint
        #: HOT_LOCKS holds that discipline)
        self._lock = make_lock("BatchAdmitter._lock")
        #: ``cycle_base`` lets a rebuilt admitter (the sim's agent
        #: restart) keep cycle ids monotonic: the surviving ledger still
        #: holds the old cycles' records, and a reused id would merge
        #: two unrelated joint solves in a batch_cycle join
        self._cycles = int(cycle_base)
        self._last: dict = {}
        #: uids the last solved cycle found unplaced — collect() demotes
        #: them behind fresh pods when the queue overflows max_batch
        self._unplaced_prev: set[str] = set()
        #: uids handed to an async strict-gang bind thread that has not
        #: finished yet: they hold no reservation until the thread
        #: reaches Dealer.bind's reserve step, so collect() must skip
        #: them or the next cycle would pack (and bind) them again
        self._inflight: set[str] = set()

    # -- drain -------------------------------------------------------------
    @staticmethod
    def solve_order(pods) -> list:
        """THE canonical solve order: (namespace, name, uid) ascending,
        deduplicated by uid — falling back to namespace/name for pods
        the apiserver has not stamped a uid on, so two DISTINCT uid-less
        pods never collapse into one (first copy wins — a retrying
        client's duplicate entry is the same pod, and packing it twice
        would double-charge scratch occupancy and race two binds).
        Determinism contract (docs/batch-admission.md): the same pending
        SET in any arrival order enters the solver identically, so the
        joint assignment is a pure function of (set, fleet state)."""
        seen: set[str] = set()
        out = []
        for p in sorted(pods, key=lambda p: (p.namespace, p.name, p.uid)):
            key = p.uid or p.key()
            if key not in seen:
                seen.add(key)
                out.append(p)
        return out

    def collect(self) -> list:
        """Production drain: the controller's unscheduled TPU pods,
        minus uids already holding reservations (barrier-parked gang
        members are MID-bind; packing them again would trip the
        already-bound idempotency guard — their unbound siblings are
        what completes the barrier). When the queue overflows
        ``max_batch``, pods the PREVIOUS cycle found unplaced are
        demoted behind fresh ones before the cap — a persistently-
        infeasible front would otherwise occupy every batch slot forever
        and starve later-sorting pods out of the batch path entirely
        (they re-enter on the very next cycle; this is a one-cycle
        rotation, not a drop)."""
        if self.controller is None:
            return []
        with self._lock:
            inflight = set(self._inflight)
            unplaced_prev = set(self._unplaced_prev)
        pods = [
            p for p in self.controller.unscheduled_pods()
            if not self.dealer.has_reservation(p.uid)
            and p.uid not in inflight
        ]
        ordered = self.solve_order(pods)
        if len(ordered) > self.max_batch and unplaced_prev:
            ordered = (
                [p for p in ordered if p.uid not in unplaced_prev]
                + [p for p in ordered if p.uid in unplaced_prev]
            )
        return ordered[: self.max_batch]

    # -- solve -------------------------------------------------------------
    def plan(self, pods, node_names: list[str]):
        """Joint solve only (no commits): returns ``(ordered pods,
        per-pod picks)`` where picks is ``Dealer.pack_pods``'s answer —
        None for "no batch plan, fall back whole"."""
        ordered = self.solve_order(pods)[: self.max_batch]
        return ordered, self._solve(ordered, node_names)

    def _solve(self, ordered, node_names: list[str]):
        """The native crossing for an ALREADY-canonical (solve-ordered,
        deduped, capped) batch — so admit() sorts exactly once."""
        if not ordered:
            return []
        return self.dealer.pack_pods(
            ordered, node_names, lookahead=self.lookahead
        )

    # -- commit ------------------------------------------------------------
    def admit(self, pods, node_names: list[str] | None = None,
              bind=None) -> AdmitResult:
        """One full batch-admission cycle: solve, then commit winners
        through the r7 write path. ``bind(node, pod)`` overrides the
        committer — the sim passes a virtual-time binder and commits
        INLINE for determinism; the default is ``Dealer.bind`` with
        strict-gang winners dispatched on their own threads (every
        member must be able to park at the gang barrier concurrently —
        a sequential committer would wedge on the first member). Losers
        fall back to the pod-at-a-time path untouched."""
        if node_names is None:
            node_names = self.dealer.node_names()
        with self._lock:
            self._cycles += 1
            cycle = self._cycles
        result = AdmitResult(cycle)
        perf = self.dealer.perf
        perf.batch_cycles += 1
        ordered_all = self.solve_order(pods)
        # beyond-cap pods are DEFERRED, visibly: the next cycle (or the
        # caller's re-post) serves them — never silently dropped
        result.deferred = ordered_all[self.max_batch:]
        ordered = ordered_all[: self.max_batch]
        picks = self._solve(ordered, node_names)
        if picks is None:
            result.fell_back = True
            result.unplaced = list(ordered)
            perf.batch_fallbacks += len(ordered)
            self._note_cycle(result)
            return result
        binder = bind if bind is not None else self._bind_default
        for pod, pick in zip(ordered, picks):
            if pick is None:
                result.unplaced.append(pod)
                continue
            node, score = pick
            result.planned.append((pod, node, score))
            self._audit_planned(pod, cycle)
            if bind is None and podutil.gang_is_strict(pod):
                gang = podutil.gang_of(pod)
                if gang and gang[1] > 1:
                    self._dispatch_strict(pod, node, cycle)
                    result.dispatched.append((pod, node, score))
                    continue
            try:
                binder(node, pod)
            except BindError as e:
                result.failed.append((pod, e))
                self._audit_outcome(pod, node, e.reason, False)
                continue
            result.bound.append((pod, node, score))
            self._audit_outcome(pod, node, REASON_BATCH_PACKED, True)
        perf.batch_packed += len(result.bound) + len(result.dispatched)
        perf.batch_fallbacks += len(result.unplaced) + len(result.failed)
        self._note_cycle(result)
        return result

    def run_once(self) -> AdmitResult | None:
        """Drain + admit (the production BatchLoop body). None when the
        pending queue is empty."""
        pods = self.collect()
        if not pods:
            return None
        return self.admit(pods)

    def _bind_default(self, node: str, pod) -> None:
        self.dealer.bind(node, pod)

    def _dispatch_strict(self, pod, node: str, cycle: int) -> None:
        """Async bind for a strict-gang winner: the bind parks at the
        gang barrier until the siblings (packed in this same cycle, each
        on its own thread) arrive — exactly kube-scheduler's concurrent
        bind-goroutine shape the strict mode was designed against.
        Outcomes flow through the normal gang machinery (barrier open /
        timeout rollback / K8s Events); the admitter never waits."""

        def run():
            try:
                self.dealer.bind(node, pod)
                self._audit_outcome(pod, node, REASON_BATCH_PACKED, True)
            except BindError as e:
                self._audit_outcome(pod, node, e.reason, False)
                log.info(
                    "batch cycle %d: strict gang member %s -> %s failed: "
                    "%s (pod-at-a-time path retries)",
                    cycle, pod.key(), node, e,
                )
            except Exception:
                log.exception(
                    "batch cycle %d: strict gang member %s -> %s died",
                    cycle, pod.key(), node,
                )
            finally:
                with self._lock:
                    self._inflight.discard(pod.uid)

        with self._lock:
            self._inflight.add(pod.uid)
        threading.Thread(
            target=run, daemon=True, name=f"batch-bind-{pod.name}"
        ).start()

    # -- audit + status ----------------------------------------------------
    @property
    def obs(self):
        """The audit bundle: the explicit one, else the dealer's."""
        if self._obs is not None:
            return self._obs
        return getattr(self.dealer, "obs", None)

    def _sampled(self, uid: str) -> bool:
        obs = self.obs
        return (
            obs is not None and obs.enabled and obs.tracer.sampled(uid)
        )

    def _audit_planned(self, pod, cycle: int) -> None:
        """Stamp the pod's building decision cycle with this batch cycle
        id (sampling-gated like the sweeper's expiry audit): the record
        that eventually finalizes carries ``batch_cycle`` — the ledger's
        proof the placement came from a joint solve, joinable across the
        whole batch."""
        if self._sampled(pod.uid):
            self.obs.ledger.batch_cycle(pod.uid, cycle, pod=pod.key())

    def _audit_outcome(self, pod, node: str, reason: str,
                       bound: bool) -> None:
        if self._sampled(pod.uid):
            self.obs.ledger.bind_outcome(
                pod.uid, node, reason, bound, pod=pod.key()
            )

    def _note_cycle(self, result: AdmitResult) -> None:
        with self._lock:
            # whole-batch fallbacks say nothing about individual
            # feasibility, so they reset the demotion set rather than
            # demote every offered pod
            self._unplaced_prev = (
                set() if result.fell_back
                else {p.uid for p in result.unplaced if p.uid}
            )
            self._last = {
                "cycle": result.cycle,
                "offered": len(result.planned) + len(result.unplaced),
                "planned": len(result.planned),
                "bound": len(result.bound),
                "dispatched": len(result.dispatched),
                "failed": len(result.failed),
                "unplaced": len(result.unplaced),
                "deferred": len(result.deferred),
                "fell_back": result.fell_back,
            }

    @property
    def cycles(self) -> int:
        """Lifetime cycle count — the ``cycle_base`` seed for a rebuilt
        admitter (agent restart) so batch cycle ids stay monotonic."""
        with self._lock:
            return self._cycles

    def status(self) -> dict:
        """``/debug/decisions``'s ``batch`` field (docs/observability.md
        + docs/batch-admission.md): knobs, lifetime counters, and the
        last cycle's shape."""
        perf = self.dealer.perf_totals()
        with self._lock:
            last = dict(self._last)
            cycles = self._cycles
        return {
            "enabled": True,
            "lookahead": self.lookahead,
            "max_batch": self.max_batch,
            "cycles": cycles,
            "packed": perf["batch_packed"],
            "fallbacks": perf["batch_fallbacks"],
            "contended": perf["batch_contended"],
            "last": last,
        }


class BatchLoop:
    """Production cadence driver (cmd/main's ``--batch``): drain the
    pending queue into one admission cycle every ``period_s``. The sim
    never uses this — it steps the admitter through virtual-time
    ``batch_admit`` events instead (docs/simulation.md)."""

    def __init__(self, admitter: BatchAdmitter, period_s: float = 0.5,
                 gate=None):
        if period_s <= 0:
            raise ValueError(f"period_s must be > 0, got {period_s!r}")
        self.admitter = admitter
        self.period_s = period_s
        #: optional write gate (docs/ha.md "Degraded mode"): a callable
        #: answering False pauses cycles — a batch cycle is a burst of
        #: apiserver writes, all doomed while the link is down. None ==
        #: always run.
        self.gate = gate
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        """Idempotent AND restart-safe (the old unguarded start stacked
        a second daemon thread on a double call; an HA promotion
        restarts the loop against the promoted dealer — pinned by the
        promote-under-load test)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="batch-admit"
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                if self.gate is not None and not self.gate():
                    continue  # degraded: skip the cycle, stay alive
                self.admitter.run_once()
            except Exception:  # the loop must outlive any cycle
                log.exception("batch admission cycle failed")

    def stop(self) -> None:
        """Idempotent; joins (not from the loop's own thread) so the
        caller can close the dealer immediately after."""
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=2.0)
