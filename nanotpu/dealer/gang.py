"""Gang-aware placement state: ICI-affinity scoring for multi-pod jobs.

The kube-scheduler extender protocol is strictly one-pod-at-a-time
(Filter/Bind per pod, routes.go:19-27), so gang knowledge must live in the
dealer's memory the way the PlanCache does (SURVEY §7 hard part #2). Pods
declare membership via the ``tpu.io/gang-name``/``gang-size`` annotations
(BASELINE configs[3-4]: a 32-pod Llama job, an 8-expert Mixtral binpack).

Placement is *soft* gang affinity: Prioritize boosts candidate nodes that
are ICI-close to where the gang's already-bound members sit —

* different slice than bound members  -> no bonus (DCN hop, worst case);
* same slice                          -> base bonus;
* same slice AND the candidate host keeps the gang's host set compact on
  the slice torus                      -> up to the full bonus.

A hard gang barrier (refusing to bind until all members are schedulable) is
deliberately NOT the default: the extender cannot see the scheduler's queue,
and wedging Bind invites deadlock with non-TPU constraints; kube-scheduler
retries make soft affinity converge in practice.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from functools import lru_cache

from nanotpu.topology import Coord, parse_slice_coords

#: Gang keys are "<namespace>/<gang-name>" — the annotation value alone would
#: merge same-named gangs across namespaces (the Dealer builds the key).

#: Portion of the score band a full gang-affinity match can add.
GANG_BONUS = 30


@dataclass
class GangMember:
    uid: str
    node: str


@dataclass
class _Gang:
    size: int = 0
    members: dict[str, str] = field(default_factory=dict)  # uid -> node


class GangTracker:
    def __init__(self):
        self._lock = threading.Lock()
        self._gangs: dict[str, _Gang] = {}
        self._by_uid: dict[str, str] = {}  # uid -> gang name

    def record_bound(self, gang: str, size: int, uid: str, node: str) -> None:
        with self._lock:
            g = self._gangs.setdefault(gang, _Gang())
            g.size = max(g.size, size)
            g.members[uid] = node
            self._by_uid[uid] = gang

    def forget_pod(self, uid: str) -> None:
        with self._lock:
            gang = self._by_uid.pop(uid, None)
            if gang is None:
                return
            g = self._gangs.get(gang)
            if g is not None:
                g.members.pop(uid, None)
                if not g.members:
                    self._gangs.pop(gang, None)

    def bound_nodes(self, gang: str) -> list[str]:
        with self._lock:
            g = self._gangs.get(gang)
            return sorted(set(g.members.values())) if g else []

    def status(self) -> dict:
        with self._lock:
            return {
                name: {"size": g.size, "bound": len(g.members)}
                for name, g in self._gangs.items()
            }


def gang_affinity_bonus(
    candidate_slice: str,
    candidate_coords: str,
    member_slices: list[tuple[str, str]],
) -> int:
    """Score bonus in [0, GANG_BONUS] for placing the next gang pod.

    ``member_slices``: (slice name, "x,y,z" coords) of nodes hosting bound
    members. Unlabeled topology degrades to slice-name matching only.
    """
    if not member_slices:
        return 0
    same_slice = [
        coords for slc, coords in member_slices if slc and slc == candidate_slice
    ]
    if not candidate_slice or not same_slice:
        return 0
    base = GANG_BONUS // 2
    try:
        cand = parse_slice_coords(candidate_coords) if candidate_coords else None
        members = [parse_slice_coords(c) for c in same_slice if c]
    except ValueError:
        cand, members = None, []
    if cand is None or not members:
        return base
    # compactness of the union of hosts on a PLAIN (non-wrapping) host grid:
    # the grid is inferred from the coords' bounding box, so assuming
    # wraparound would make the two most distant hosts look adjacent
    coords = members + [cand]
    compact = _grid_compactness(coords)
    return base + int(round((GANG_BONUS - base) * compact))


def _grid_compactness(coords: list[Coord]) -> float:
    """ICI-compactness of the OCCUPIED host cells on a plain grid, in [0, 1]:
    fraction of the best-achievable nearest-neighbor adjacencies for that
    many distinct hosts.

    Duplicates are deduped deliberately: a candidate host that already runs a
    bound gang member (possible for fractional-chip gangs) is zero ICI hops
    away, so colocating must score maximal — never below an adjacent host.
    """
    return _grid_compactness_cached(tuple(sorted(set(coords))))


@lru_cache(maxsize=65536)
def _grid_compactness_cached(coords: tuple[Coord, ...]) -> float:
    from nanotpu.topology import _max_links_for_volume

    k = len(coords)
    if k <= 1:
        return 1.0
    cells = set(coords)
    links = sum(
        1
        for (x, y, z) in cells
        for d in ((1, 0, 0), (0, 1, 0), (0, 0, 1))
        if (x + d[0], y + d[1], z + d[2]) in cells
    )
    best = _max_links_for_volume(k)
    return min(links / best, 1.0) if best else 1.0
