"""Gang-aware placement state: ICI-affinity scoring for multi-pod jobs.

The kube-scheduler extender protocol is strictly one-pod-at-a-time
(Filter/Bind per pod, routes.go:19-27), so gang knowledge must live in the
dealer's memory the way the PlanCache does (SURVEY §7 hard part #2). Pods
declare membership via the ``tpu.io/gang-name``/``gang-size`` annotations
(BASELINE configs[3-4]: a 32-pod Llama job, an 8-expert Mixtral binpack).

Placement is *soft* gang affinity: Prioritize boosts candidate nodes that
are ICI-close to where the gang's already-bound members sit —

* different slice than bound members  -> no bonus (DCN hop, worst case);
* same slice                          -> base bonus;
* same slice AND the candidate host keeps the gang's host set compact on
  the slice torus                      -> up to the full bonus.

A hard gang barrier (refusing to bind until all members are schedulable) is
deliberately NOT the default: the extender cannot see the scheduler's queue,
and wedging Bind invites deadlock with non-TPU constraints; kube-scheduler
retries make soft affinity converge in practice.

An **opt-in strict mode** exists for jobs that need all-or-nothing
placement (``tpu.io/gang-policy: strict`` — VERDICT r2 missing #5): each
member's Bind applies its chip reservation, then PARKS on the gang's
:class:`GangBarrier` until bound+parked members reach ``gang-size``; a
member that times out (``tpu.io/gang-timeout-seconds``, default 30s) rolls
its own reservation back and fails its bind, so an incomplete gang
converges to "not at all" while completed arrivals still open the barrier
for retried members. This is safe against the default-scheduler deadlock
because kube-scheduler runs its bind phase asynchronously (one goroutine
per pod): members' Bind calls genuinely overlap, and the bounded park
guarantees no reservation outlives an incomplete gang.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache

from nanotpu.analysis.witness import make_condition, make_lock
from nanotpu.topology import Coord, parse_slice_coords

#: Gang keys are "<namespace>/<gang-name>" — the annotation value alone would
#: merge same-named gangs across namespaces (the Dealer builds the key).

#: Portion of the score band a full gang-affinity match can add.
GANG_BONUS = 30


@dataclass
class GangMember:
    uid: str
    node: str


@dataclass
class _Gang:
    size: int = 0
    members: dict[str, str] = field(default_factory=dict)  # uid -> node


class GangTracker:
    def __init__(self, on_gang_empty=None):
        self._lock = make_lock("GangTracker._lock")
        self._gangs: dict[str, _Gang] = {}
        self._by_uid: dict[str, str] = {}  # uid -> gang name
        #: bumped on every membership change; consumers key memoized
        #: member-derived state (Dealer._gang_member_slices) on it
        self.rev = 0
        #: called (outside the tracker lock) with the gang key when its
        #: last member is forgotten — the Dealer drops the gang's strict
        #: barrier here, so a RE-submitted same-named gang starts with a
        #: closed barrier instead of inheriting a stale open one
        self._on_gang_empty = on_gang_empty

    def record_bound(self, gang: str, size: int, uid: str, node: str) -> None:
        with self._lock:
            g = self._gangs.setdefault(gang, _Gang())
            g.size = max(g.size, size)
            g.members[uid] = node
            self._by_uid[uid] = gang
            self.rev += 1

    def forget_pod(self, uid: str) -> None:
        emptied = None
        with self._lock:
            gang = self._by_uid.pop(uid, None)
            if gang is None:
                return
            g = self._gangs.get(gang)
            if g is not None:
                g.members.pop(uid, None)
                if not g.members:
                    self._gangs.pop(gang, None)
                    emptied = gang
            self.rev += 1
        if emptied is not None and self._on_gang_empty is not None:
            # outside the lock: the callback takes the Dealer's lock, and
            # Dealer code holding its lock calls INTO this tracker
            self._on_gang_empty(emptied)

    def bound_nodes(self, gang: str) -> list[str]:
        with self._lock:
            g = self._gangs.get(gang)
            return sorted(set(g.members.values())) if g else []

    def bound_count(self, gang: str) -> int:
        with self._lock:
            g = self._gangs.get(gang)
            return len(g.members) if g else 0

    def status(self) -> dict:
        with self._lock:
            return {
                name: {"size": g.size, "bound": len(g.members)}
                for name, g in self._gangs.items()
            }


class GangBarrier:
    """Park point for one strict gang's Binds (see module docstring).

    ``parked`` holds the uids currently waiting WITH a chip reservation
    applied; the barrier opens when bound members + parked members reach
    ``size`` and stays open (a later replacement pod for a completed gang
    binds straight through)."""

    def __init__(self, size: int):
        self.cv = make_condition("GangBarrier.cv")
        #: the barrier threshold — the LARGEST size any member has
        #: declared (Dealer raises it under ``cv`` as members arrive).
        #: One member with a typoed smaller size must not open the
        #: barrier early, regardless of arrival order.
        self.size = size
        self.parked: set[str] = set()
        self.open = False
        #: threads between fetch and release (Dealer-lock maintained):
        #: keeps barrier cleanup from orphaning a fetched-but-not-yet-
        #: parked thread onto a removed object
        self.users = 0
        #: batched-commit state (Dealer._commit_gang_batch, all under
        #: ``cv``): while ``committing`` the opener is fanning the claimed
        #: members' API writes out through the dealer's commit pool —
        #: ``open`` stays False so late arrivals keep parking, and claimed
        #: members' timeouts are suspended (their write is in flight; a
        #: timeout rollback would double-book the chips the batch worker
        #: is committing). ``results`` delivers each claimed member's
        #: bound Pod or BindError back to its own parked bind thread.
        self.committing = False
        self.claimed: set[str] = set()
        self.results: dict[str, object] = {}


class WaitObservation:
    """Exactly-once observation of one strict-gang park window.

    The gang-wait histogram must record each park window EXACTLY once,
    across every exit — barrier open, timeout rollback, batched-commit
    result delivery, and the capacity-recovery paths that can now
    de-park a member mid-window (a backfill lease expiring inside the
    window must not let a retry-then-raise exit observe the same wait
    twice). Call sites wrap the window in try/finally around
    :meth:`observe`; the ``_done`` latch makes a second call — from a
    nested finally, a re-raised rollback, or a future exit path — a
    counted no-op instead of a duplicate histogram sample."""

    __slots__ = ("hist", "t0", "_done")

    def __init__(self, hist, t0: float):
        #: the histogram (``Observability.gang_wait``), or None when no
        #: observability bundle is attached — observe() then no-ops
        self.hist = hist
        self.t0 = t0
        self._done = False

    @property
    def observed(self) -> bool:
        return self._done

    def observe(self, now: float) -> bool:
        """Record the wait once; True iff THIS call recorded it."""
        if self._done or self.hist is None:
            return False
        self._done = True
        self.hist.observe(now - self.t0)
        return True


def gang_affinity_bonus(
    candidate_slice: str,
    candidate_coords: str,
    member_slices: list[tuple[str, str]],
) -> int:
    """Score bonus in [0, GANG_BONUS] for placing the next gang pod.

    ``member_slices``: (slice name, "x,y,z" coords) of nodes hosting bound
    members. Unlabeled topology degrades to slice-name matching only.
    """
    return GangScorer(member_slices).bonus(candidate_slice, candidate_coords)


class GangScorer:
    """Per-Prioritize-call gang bonus with O(1) per candidate.

    The naive bonus recomputes grid compactness of (members + candidate)
    from scratch for every candidate — O(members) set work x fan-out, which
    profiled at ~40% of the 256-host scheduling cycle. The member set is
    FIXED for the duration of one Prioritize call, so this precomputes, per
    slice, the members' occupied-cell set and their internal link count
    once; a candidate then costs six set lookups:

        links(M + {c}) = links(M) + sum_d [c+d in M] + [c-d in M]   (c not in M)

    (+direction adjacency convention counts each link once). Semantics are
    identical to :func:`gang_affinity_bonus` — equivalence is test-pinned.
    """

    _DIRS = ((1, 0, 0), (0, 1, 0), (0, 0, 1))

    def __init__(self, member_slices: list[tuple[str, str]]):
        self.empty = not member_slices
        # slice -> (cells set, internal links, had_unparsable_coords)
        self._slices: dict[str, tuple[set, int]] = {}
        by_slice: dict[str, list[str]] = {}
        for slc, coords in member_slices:
            if slc:
                by_slice.setdefault(slc, []).append(coords)
        for slc, coord_strs in by_slice.items():
            try:
                cells = {parse_slice_coords(c) for c in coord_strs if c}
            except ValueError:
                cells = set()
            links = sum(
                1
                for (x, y, z) in cells
                for d in self._DIRS
                if (x + d[0], y + d[1], z + d[2]) in cells
            )
            self._slices[slc] = (cells, links)

    def bonus(self, candidate_slice: str, candidate_coords: str) -> int:
        if self.empty:
            return 0
        entry = self._slices.get(candidate_slice) if candidate_slice else None
        if entry is None:
            return 0  # different slice than every bound member: DCN hop
        base = GANG_BONUS // 2
        cells, links = entry
        if not cells:
            return base  # members' coords unlabeled/unparsable
        try:
            cand = (
                parse_slice_coords(candidate_coords)
                if candidate_coords else None
            )
        except ValueError:
            cand = None
        if cand is None:
            return base
        if cand in cells:
            # colocating with a bound member is zero ICI hops: maximal
            # (same dedup rule as _grid_compactness)
            k, total = len(cells), links
        else:
            x, y, z = cand
            total = links + sum(
                ((x + dx, y + dy, z + dz) in cells)
                + ((x - dx, y - dy, z - dz) in cells)
                for dx, dy, dz in self._DIRS
            )
            k = len(cells) + 1
        if k <= 1:
            compact = 1.0
        else:
            from nanotpu.topology import _max_links_for_volume

            best = _max_links_for_volume(k)
            compact = min(total / best, 1.0) if best else 1.0
        return base + int(round((GANG_BONUS - base) * compact))


def _grid_compactness(coords: list[Coord]) -> float:
    """ICI-compactness of the OCCUPIED host cells on a plain grid, in [0, 1]:
    fraction of the best-achievable nearest-neighbor adjacencies for that
    many distinct hosts.

    Duplicates are deduped deliberately: a candidate host that already runs a
    bound gang member (possible for fractional-chip gangs) is zero ICI hops
    away, so colocating must score maximal — never below an adjacent host.
    """
    return _grid_compactness_cached(tuple(sorted(set(coords))))


@lru_cache(maxsize=65536)
def _grid_compactness_cached(coords: tuple[Coord, ...]) -> float:
    from nanotpu.topology import _max_links_for_volume

    k = len(coords)
    if k <= 1:
        return 1.0
    cells = set(coords)
    links = sum(
        1
        for (x, y, z) in cells
        for d in ((1, 0, 0), (0, 1, 0), (0, 0, 1))
        if (x + d[0], y + d[1], z + d[2]) in cells
    )
    best = _max_links_for_volume(k)
    return min(links / best, 1.0) if best else 1.0
