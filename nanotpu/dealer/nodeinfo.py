"""Per-node allocation state + plan cache (rebuild of ``pkg/dealer/node.go``).

The reference's NodeInfo holds a flat card array and a plan cache keyed by
demand hash (node.go:18-42); ours holds a :class:`ChipSet` on the node's ICI
torus and adds a per-node lock so Assume/Score/Bind on *different* nodes never
serialize (the reference funneled every verb through one global mutex,
dealer.go:81 — the documented p50 bottleneck, SURVEY §6).
"""

from __future__ import annotations

from nanotpu import types
from nanotpu.analysis.witness import make_lock, make_rlock
from nanotpu.allocator.core import ChipSet, Demand, Plan
from nanotpu.allocator.rater import Rater
from nanotpu.k8s.objects import Node
from nanotpu.topology import DEFAULT_HOST_TOPOLOGY
from nanotpu.utils import node as nodeutil

#: Process-wide chip-state change counter. Mutations are rare (a bind, an
#: eviction, a load-metric write) while scoring fan-outs are hot; scorers
#: read this to answer "did ANY node change since my last refresh" in one
#: comparison instead of probing every candidate's version. Bumps take a
#: dedicated lock (the per-node locks differ, and a lost += would let a
#: scorer serve stale state forever); unlocked reads are safe — a torn
#: read is impossible for a Python int, and a bump racing the read is the
#: same staleness window the per-node probe loop already has.
_state_gen = 0
_state_gen_lock = make_lock("nodeinfo._state_gen_lock")


def state_generation() -> int:
    return _state_gen


class NodeInfo:
    """Chip accounting for one node, with a demand-hash plan cache."""

    @staticmethod
    def fingerprint_of(node: Node) -> tuple:
        """Everything placement depends on, computed WITHOUT building chip
        state — refresh paths compare this against live NodeInfos, so it
        must be cheap (the resync loop calls it for every node)."""
        chip_count = nodeutil.get_chip_count(node)
        generation = node.labels.get(types.LABEL_TPU_GENERATION, "v5p")
        topo = node.labels.get(
            types.LABEL_TPU_TOPOLOGY, DEFAULT_HOST_TOPOLOGY.get(generation)
        )
        return (
            chip_count, generation, topo,
            node.labels.get(types.LABEL_TPU_SLICE, ""),
            node.labels.get(types.LABEL_TPU_SLICE_COORDS, ""),
        )

    def __init__(self, node: Node):
        self.name = node.name
        #: the node object this accounting was built from — the HA delta
        #: stream and checkpoint snapshot (docs/ha.md) need the raw to
        #: rebuild an identical NodeInfo on the standby/restart side
        #: (no copy: client reads hand out private objects already)
        self.node_raw = node.raw
        self.lock = make_rlock("NodeInfo.lock")
        (
            chip_count, generation, topo, self.slice_name, self.slice_coords,
        ) = self.fingerprint_of(node)
        self.generation = generation
        self.topology = topo
        self.chip_count = chip_count
        self.chips = ChipSet.for_node(chip_count, topo, generation)
        self.chips.key = self.name
        #: demand hash -> Plan (node.go:20,44-57)
        self._plan_cache: dict[str, Plan] = {}
        #: the rater cache token the current _plan_cache contents were
        #: computed under (None == tokenless rater); a token move clears
        #: the WHOLE cache rather than minting new keys, so the cache
        #: stays bounded by live demand shapes — folding the token into
        #: each key would strand one dead Plan per (shape, token) on any
        #: node the sweep paths stop clearing
        self._plan_cache_token = None
        #: bumped on every chip-state mutation; the batch scorer
        #: (dealer/batch.py) uses it to refresh only changed rows
        self.version = 0

    @classmethod
    def restore(cls, name: str, node_raw: dict | None, fp: tuple,
                chip_rows: list, lock_factory=None) -> "NodeInfo":
        """Rebuild from checkpointed derived state (docs/ha.md warm
        restart): the fingerprint tuple and per-chip rows were computed
        once at checkpoint time, so the restart pays none of the label /
        quantity parsing ``__init__`` derives from the node object.
        ``lock_factory`` (witness.rlock_factory) amortizes the witness
        activation probe across a bulk restore."""
        self = cls.__new__(cls)
        self.name = name
        self.node_raw = node_raw
        self.lock = (
            lock_factory() if lock_factory is not None
            else make_rlock("NodeInfo.lock")
        )
        (
            self.chip_count, self.generation, self.topology,
            self.slice_name, self.slice_coords,
        ) = fp
        self.chips = ChipSet.restore(
            self.chip_count, self.topology, self.generation, chip_rows
        )
        self.chips.key = name
        self._plan_cache = {}
        self._plan_cache_token = None
        self.version = 0
        return self

    def _bump(self) -> None:
        # caller holds self.lock; also advances the process-wide change
        # counter so scorers can skip their per-node version probe loop
        # entirely when NOTHING changed since their last refresh (256
        # attribute probes per verb add up at large fan-out)
        self.version += 1
        global _state_gen
        with _state_gen_lock:
            _state_gen += 1

    def fingerprint(self) -> tuple:
        """Everything placement depends on; a drift means the NodeInfo must
        be rebuilt (node resize / relabel detection)."""
        return (
            self.chip_count, self.generation, self.topology,
            self.slice_name, self.slice_coords,
        )

    # -- verbs -------------------------------------------------------------
    def assume(self, demand: Demand, rater: Rater) -> Plan | None:
        """Compute (or re-use) a plan for this demand (node.go:44-57).

        Returns None when infeasible. The plan is cached so the immediately
        following Score and Bind reuse it without re-packing.

        The cache is VERSION-GUARDED by the rater when it exposes a
        ``cache_token`` (the throughput rater's model version): a rater
        whose score depends on state outside this node's chips — the
        online contention EWMA, a hot-reloaded throughput table — moves
        that token on every model change, and a moved token clears the
        whole cache before lookup, so a plan scored against pre-sync
        usage can never satisfy a post-sync lookup, even on paths that
        bypass :meth:`set_chip_load`'s clear. Raters without the hook
        keep the bare demand-hash behavior bit-identically.
        """
        token = getattr(rater, "cache_token", None)
        with self.lock:
            if token is not None:
                t = token()
                if t != self._plan_cache_token:
                    self._plan_cache.clear()
                    self._plan_cache_token = t
            key = demand.hash()
            cached = self._plan_cache.get(key)
            if cached is not None:
                return cached
            if not self.chips.can_fit(demand):
                return None
            plan = rater.choose(self.chips, demand)
            if plan is not None:
                self._plan_cache[key] = plan
            return plan

    def score(self, demand: Demand, rater: Rater) -> int:
        """Cached plan's score; recompute on miss; SCORE_MIN when infeasible
        (node.go:59-68)."""
        plan = self.assume(demand, rater)
        return plan.score if plan is not None else types.SCORE_MIN

    def invalidate_plans(self) -> None:
        """Drop every cached plan unconditionally — the RATER changed
        (``Dealer.install_rater``, docs/policy-programs.md), so plans
        scored under the old policy must not serve the new one. Chip
        state is untouched and ``version`` does not move: batch-scorer
        rows mirror chip state, which is rater-independent."""
        with self.lock:
            self._plan_cache.clear()
            self._plan_cache_token = None

    def bind(self, demand: Demand, rater: Rater) -> Plan | None:
        """Apply the (cached or recomputed) plan to chip accounting and drop
        the cache — the node's state changed (node.go:70-84)."""
        with self.lock:
            plan = self.assume(demand, rater)
            if plan is None:
                return None
            self.chips.allocate(plan)
            self._plan_cache.clear()
            self._bump()
            return plan

    def unbind(self, plan: Plan) -> None:
        """Undo a bind whose API write failed (the reference leaked the
        allocation until Release in this case)."""
        with self.lock:
            self.chips.release(plan)
            self._plan_cache.clear()
            self._bump()

    def allocate(self, plan: Plan) -> None:
        """Account an externally-learned placement (reconciler/boot replay,
        node.go:86-89)."""
        with self.lock:
            self.chips.allocate(plan)
            self._plan_cache.clear()
            self._bump()

    def release(self, plan: Plan) -> None:
        """Return a completed pod's chips (node.go:91-94)."""
        with self.lock:
            self.chips.release(plan)
            self._plan_cache.clear()
            self._bump()

    # -- metrics ingestion -------------------------------------------------
    def set_chip_load(self, chip: int, load: float) -> None:
        with self.lock:
            if 0 <= chip < len(self.chips.chips):
                self.chips.chips[chip].load = max(0.0, min(1.0, load))
                # load shifts rater scores; cached plans are stale. This
                # clear only covers updates routed THROUGH this node —
                # model state that moves without touching it (a usage
                # sync's EWMA calibration, a throughput-table reload) is
                # covered by the rater cache token in assume()'s key.
                self._plan_cache.clear()
                self._bump()

    # -- introspection -----------------------------------------------------
    def status(self) -> dict:
        with self.lock:
            avail, free = self.chips.available_percent_and_free_chips()
            return {
                "name": self.name,
                "generation": self.generation,
                "topology": "x".join(map(str, self.chips.torus.dims)),
                "slice": self.slice_name,
                "usage": round(self.chips.usage(), 4),
                "available_percent": avail,
                "free_chips": free,
                "chips": self.chips.snapshot(),
            }
