"""Batched candidate scoring: flattened node state + one native call per
Filter/Prioritize fan-out, RCU-style.

The per-node path costs Python-loop overhead per candidate (NodeInfo lock,
plan-cache lookup, ctypes marshalling, gang bonus) — at 256 hosts that
Python dominates the scheduling cycle (VERDICT r1 weak #3). The scorer
keeps ctypes arrays of every candidate's per-chip free/total/load and hands
the whole pool to ``native.score_batch`` / ``native.score_render``
(native/allocator.cc), which returns feasibility + the final score (rate +
compactness band + gang bonus) — and, on the fused path, the full response
JSON — for every node in one call.

Concurrency model (r6, sharded in r7): a scorer adopted into a dealer
snapshot is FROZEN (``freeze()``) — its row arrays are written once and
never mutated, so
read verbs consume them without probing node versions or copying rows.
Writers publish a successor via :meth:`advanced`, a copy-on-write clone
that memmoves the arrays and re-reads only rows whose ``NodeInfo.version``
moved. What IS shared across that chain is the per-candidate-list arena:
one reader lock, the score/feasibility output buffers, the one-slot memo
(keyed by ``state_rev``, which advances with every clone, so a stale view's
result can never satisfy a fresh view's lookup), the gang-encoding cache,
and the pre-baked renderer blobs — so the steady-state request allocates
no wire buffers at all. Readers of any view in the chain serialize on the
arena lock; publishers never take it (they only read the predecessor's
immutable arrays), which is the whole point: Filter/Prioritize never
contend with Assume/bind writers.

Under the sharded dealer (r7, nanotpu/dealer/shard.py) every scorer —
rows, arena, renderer blobs — belongs to exactly ONE shard's snapshot
chain and covers only that shard's candidates; parallel per-shard
``run()``/``score_render`` calls therefore touch disjoint arenas and
never contend (the arena lock still serializes readers WITHIN a shard).
The native calls release the GIL, which is what makes the per-shard
fan-out genuinely parallel.

The standalone (non-snapshot) mode keeps the historical self-refreshing
behavior for tests and ad-hoc use: ``run()`` probes node versions and
refreshes rows in place, exactly as before.

Result parity with the per-node path (NodeInfo.assume / Dealer.score) is
fuzz-enforced by tests/test_native.py.
"""

from __future__ import annotations

import ctypes
import itertools

from nanotpu import native, types
from nanotpu.allocator.throughput import quantize
from nanotpu.analysis.witness import make_lock
from nanotpu.dealer import nodeinfo as nodeinfo_mod
from nanotpu.dealer.nodeinfo import NodeInfo
from nanotpu.dealer.perf import PerfCounters
from nanotpu.topology import parse_slice_coords

#: sink for standalone scorers built without a dealer (tests, tools)
_DEFAULT_PERF = PerfCounters()

#: attributes shared by reference across an advanced() chain: static
#: geometry plus the per-candidate-list arena (lock, output buffers, memo,
#: gang cache, renderer blobs) — and, for model raters (ABI 7,
#: docs/scoring.md), the model handle, the generation index, and the
#: model-mirror box (the mirror itself is copy-on-write, so sharing the
#: BOX means one resync serves the whole chain)
_SHARED_ATTRS = (
    "infos", "dims", "chip_count", "slice_names", "node_coords", "coord_ok",
    "_lock", "_memo", "_gang_cache", "_renderer_box", "out_feas",
    "out_score", "c_dims", "c_demands", "_perf", "_rev_counter",
    "_model", "_model_box", "generations", "gen_idx", "c_base_q",
)


class _ModelMirror:
    """One write-once quantized snapshot of the throughput model's
    contention state, laid out for the native call (ABI 7) and stamped
    with the model ``version`` it mirrors: ``cont_sum``/``cont_cnt`` are
    per-candidate int32 arrays (Q16 per-card EWMA sum, calibrated card
    count; count 0 = uncalibrated, the native formula falls back to the
    view's ``load_q`` rows). Published copy-on-write into the chain's
    shared ``_model_box`` under the arena lock — readers mid-call keep
    the mirror they captured, exactly the RCU discipline the row arrays
    already follow — and retired by version compare on the next call
    after any model mutation (one resync per metric-sync batch, since
    sweeps batch their observes between reads)."""

    __slots__ = ("version", "cont_sum", "cont_cnt")


class BatchScorer:
    """Flattened state for one (ordered) candidate list of a uniform pool.

    Built when: the native library is loadable, every candidate has the
    same torus dims/chip count (<= 64 chips), and the rater is binpack,
    spread, or a model rater (throughput — ``model`` carries its
    ThroughputModel and native calls evaluate the quantized fixed-point
    formula in C, ABI 7) — the Dealer falls back to the per-node path
    otherwise.
    """

    @staticmethod
    def build(infos: list[NodeInfo],
              perf: PerfCounters | None = None,
              model=None) -> "BatchScorer | None":
        if not infos or not native.available():
            return None
        dims = infos[0].chips.torus.dims
        count = infos[0].chip_count
        if count > 64:
            return None
        for info in infos:
            if info.chips.torus.dims != dims or info.chip_count != count:
                return None  # heterogeneous pool
        return BatchScorer(infos, dims, count, perf=perf, model=model)

    def __init__(self, infos: list[NodeInfo], dims, chip_count: int,
                 perf: PerfCounters | None = None, model=None):
        self.infos = infos
        self.dims = tuple(dims)
        self.chip_count = chip_count
        n, c = len(infos), chip_count
        self._perf = perf or _DEFAULT_PERF
        #: arena lock: serializes READERS of every view in this chain
        #: around the shared output buffers/memo/renderer; publishers
        #: (advanced()) never take it
        self._lock = make_lock("BatchScorer.arena")
        self.free = (ctypes.c_int32 * (n * c))()
        self.total = (ctypes.c_int32 * (n * c))()
        self.load = (ctypes.c_double * (n * c))()
        #: Q16-quantized mirror of ``load`` — the fixed-point formula's
        #: uncalibrated-contention fallback (quantized at row-copy time,
        #: the same float→int edge the per-node path applies, so hook /
        #: native / per-node consume identical integers)
        self.load_q = (ctypes.c_int32 * (n * c))()
        self.hbm = (ctypes.c_int32 * (n * c))()  # -1 == untracked
        self.versions: list[int | None] = [None] * n
        #: throughput model (ABI 7) or None; with a model, native calls
        #: pass the quantized mirror and evaluate the model formula in C
        self._model = model
        #: [mirror or None] — chain-shared box, swapped copy-on-write
        #: under the arena lock (see _ModelMirror)
        self._model_box: list = [None]
        # generation index: per-row indirection into the per-call
        # base_q array (generations are static for a NodeInfo's life,
        # so this is write-once chain state like the coords)
        gens: list[str] = []
        gen_index: dict[str, int] = {}
        self.gen_idx = (ctypes.c_int32 * n)()
        for idx, info in enumerate(infos):
            g = info.generation
            i = gen_index.get(g)
            if i is None:
                i = gen_index[g] = len(gens)
                gens.append(g)
            self.gen_idx[idx] = i
        self.generations = gens
        #: per-call scratch: quantized base fraction per generation for
        #: the current demand's shape (filled under the arena lock)
        self.c_base_q = (ctypes.c_int32 * max(len(gens), 1))()
        #: nodeinfo.state_generation() at last refresh; -1 forces the
        #: first refresh to probe every row (standalone mode only)
        self._last_state_gen = -1
        #: advanced per in-place refresh AND per advanced() clone; memo-key
        #: component, so a result computed against one view can never
        #: satisfy a lookup against another. Drawn from a chain-shared
        #: itertools counter (next() is C-atomic): concurrent advanced()
        #: calls on the same scorer (publisher vs a reader's racing-commit
        #: re-advance) must fork SIBLINGS with distinct revs, or two
        #: different row states would share one memo key
        self._rev_counter = itertools.count(1)
        self.state_rev = 0
        #: False once adopted into a dealer snapshot: rows are immutable
        #: and run()/payloads skip the version-probe/refresh entirely
        self._mutable = True
        #: one-slot memo BOX shared across the chain: [key] where key =
        #: (demand hash, prefer, state_rev, gang sig); the score/feas
        #: ARENA buffers hold the matching result
        self._memo: list = [None]
        #: score+feasibility output arena, reused for every native call
        #: in this chain (under self._lock)
        self.out_feas = (ctypes.c_uint8 * max(n, 1))()
        self.out_score = (ctypes.c_int32 * max(n, 1))()
        self.c_dims = (ctypes.c_int32 * 3)(*self.dims)
        self.c_demands = (ctypes.c_int32 * 16)()
        #: [renderer tuple or None]: (names_key, qnames blob/off, prio
        #: blob/off, fail blob/off, out buffer) — pre-baked JSON fragments,
        #: shared by the whole chain (names never change within it)
        self._renderer_box: list = [None]
        # gang sig -> encoded ctypes arrays (a gang's member set only
        # changes when one of its pods binds; re-encoding per verb wastes
        # ~0.1ms at 256 hosts). State-independent, shared across the chain.
        self._gang_cache: dict[tuple, tuple] = {}
        # static gang geometry per node
        self.slice_names = [i.slice_name for i in infos]
        self.node_coords = (ctypes.c_int32 * (n * 3))()
        self.coord_ok = (ctypes.c_uint8 * n)()
        for idx, info in enumerate(infos):
            try:
                cd = (
                    parse_slice_coords(info.slice_coords)
                    if info.slice_coords else None
                )
            except ValueError:
                cd = None
            if cd is not None:
                self.coord_ok[idx] = 1
                self.node_coords[3 * idx] = cd[0]
                self.node_coords[3 * idx + 1] = cd[1]
                self.node_coords[3 * idx + 2] = cd[2]
        self._copy_row_range(range(n))

    # -- row state ---------------------------------------------------------
    def _copy_row_range(self, indices) -> None:
        """Read the given candidates' chip state into the row arrays
        (per-node lock held per row)."""
        c = self.chip_count
        for idx in indices:
            info = self.infos[idx]
            with info.lock:
                v = info.version
                base = idx * c
                for j, chip in enumerate(info.chips.chips):
                    self.free[base + j] = chip.percent_free
                    self.total[base + j] = chip.percent_total
                    self.load[base + j] = chip.load
                    self.load_q[base + j] = quantize(chip.load)
                    self.hbm[base + j] = (
                        chip.hbm_free_mib if chip.hbm_total_mib else -1
                    )
                self.versions[idx] = v

    def freeze(self) -> None:
        """Adopt into a snapshot: rows become immutable; state drift is
        delivered by the publisher via :meth:`advanced` instead of being
        probed on the read path."""
        self._mutable = False

    def advanced(self, candidates=None) -> "BatchScorer":
        """Publisher-side copy-on-write successor. Returns ``self`` when
        no candidate's chip state moved (the common off-pool publish);
        otherwise a frozen clone sharing the arena with fresh row arrays
        — readers still running on the predecessor keep its (immutable)
        arrays, which is what makes the swap safe without their lock.

        ``candidates`` narrows the version probe to those row indices —
        the writer KNOWS which node its commit touched, and probing all
        256 rows per bind was measured at ~15% of the scheduling cycle.
        None probes every row (fallback for callers without that
        knowledge)."""
        probe = range(len(self.infos)) if candidates is None else candidates
        changed = [
            i for i in probe if self.infos[i].version != self.versions[i]
        ]
        if not changed:
            return self
        new = BatchScorer.__new__(BatchScorer)
        for attr in _SHARED_ATTRS:
            setattr(new, attr, getattr(self, attr))
        n, c = len(self.infos), self.chip_count
        new.free = (ctypes.c_int32 * (n * c))()
        new.total = (ctypes.c_int32 * (n * c))()
        new.load = (ctypes.c_double * (n * c))()
        new.load_q = (ctypes.c_int32 * (n * c))()
        new.hbm = (ctypes.c_int32 * (n * c))()
        ctypes.memmove(new.free, self.free, ctypes.sizeof(self.free))
        ctypes.memmove(new.total, self.total, ctypes.sizeof(self.total))
        ctypes.memmove(new.load, self.load, ctypes.sizeof(self.load))
        ctypes.memmove(new.load_q, self.load_q, ctypes.sizeof(self.load_q))
        ctypes.memmove(new.hbm, self.hbm, ctypes.sizeof(self.hbm))
        new.versions = list(self.versions)
        new._copy_row_range(changed)
        new.state_rev = next(self._rev_counter)
        new._last_state_gen = -1
        new._mutable = False
        self._perf.view_advances += 1
        return new

    def _refresh(self) -> None:
        # standalone mode only: one comparison answers "did ANY node
        # change anywhere" — the common fan-out case (nothing changed
        # since the last verb) skips the per-candidate version probe loop
        # entirely. Captured BEFORE probing: a mutation landing mid-loop
        # re-probes next refresh.
        gen = nodeinfo_mod.state_generation()
        if gen == self._last_state_gen:
            return
        changed = [
            i for i, info in enumerate(self.infos)
            if info.version != self.versions[i]
        ]
        if changed:
            self._copy_row_range(changed)
            self.state_rev = next(self._rev_counter)
        self._last_state_gen = gen

    def _gang_arrays(self, member_slices: list[tuple[str, str]]):
        """Encode gang member host cells per slice for the native call.
        Mirrors gang.GangScorer.__init__: one unparsable coord voids the
        whole slice's cells (those candidates get the base bonus)."""
        by_slice: dict[str, list[str]] = {}
        for slc, coords in member_slices:
            if slc:
                by_slice.setdefault(slc, []).append(coords)
        if not by_slice:
            return None
        slice_index = {slc: i for i, slc in enumerate(by_slice)}
        cells_flat: list[int] = []
        offsets = [0]
        for slc, coord_strs in by_slice.items():
            try:
                cells = {parse_slice_coords(c) for c in coord_strs if c}
            except ValueError:
                cells = set()
            for cell in sorted(cells):
                cells_flat.extend(cell)
            offsets.append(len(cells_flat) // 3)
        n = len(self.infos)
        node_slice = (ctypes.c_int32 * n)(
            *(slice_index.get(s, -1) for s in self.slice_names)
        )
        n_slices = len(by_slice)
        c_cells = (ctypes.c_int32 * max(len(cells_flat), 1))(*cells_flat)
        c_off = (ctypes.c_int32 * (n_slices + 1))(*offsets)
        return (
            node_slice, self.node_coords, self.coord_ok,
            n_slices, c_cells, c_off,
        )

    def _gang_of(self, member_slices):
        """Cached gang encoding (shared across the view chain — it is
        state-independent). Caller holds the arena lock."""
        if not member_slices:
            return None, None
        gang_sig = tuple(member_slices)
        gang = self._gang_cache.get(gang_sig)
        if gang is None and gang_sig not in self._gang_cache:
            gang = self._gang_arrays(member_slices)
            self._gang_cache[gang_sig] = gang
            while len(self._gang_cache) > 64:
                self._gang_cache.pop(next(iter(self._gang_cache)))
        return gang, gang_sig

    def _memo_key(self, demand, prefer_used: bool, gang_sig, model_rev):
        return (
            demand.hash(), prefer_used, self.state_rev, gang_sig, model_rev
        )

    def _sync_model_locked(self) -> _ModelMirror:
        """Rebuild the quantized model mirror copy-on-write (caller
        holds the arena lock). One :meth:`ThroughputModel.mirror_snapshot`
        — a single model-lock hold for the whole candidate list, the
        same discipline as the hook's ``contention_q_many`` — then the
        fresh arrays swap into the chain-shared box. Counted as
        ``model_syncs``: between metric-sync batches the version compare
        short-circuits and this never runs."""
        version, table = self._model.mirror_snapshot(
            [info.name for info in self.infos]
        )
        n = len(self.infos)
        mirror = _ModelMirror()
        mirror.version = version
        mirror.cont_sum = (ctypes.c_int32 * max(n, 1))()
        mirror.cont_cnt = (ctypes.c_int32 * max(n, 1))()
        for i, info in enumerate(self.infos):
            entry = table.get(info.name)
            if entry is not None:
                mirror.cont_sum[i] = entry[0]
                mirror.cont_cnt[i] = entry[1]
        self._model_box[0] = mirror
        self._perf.model_syncs += 1
        return mirror

    def _ensure_mirror_locked(self) -> _ModelMirror:
        """Current model mirror, resynced if the model version moved
        (caller holds the arena lock)."""
        mirror = self._model_box[0]
        if mirror is None or mirror.version != self._model.version:
            mirror = self._sync_model_locked()
        return mirror

    def _model_args_locked(self, demand, mirror: _ModelMirror):
        """The native model tuple for one call (caller holds the arena
        lock): resolve this demand's shape against the table into the
        per-generation base array (O(#generations) Python — the per-ROW
        work all happens in C). Called only when a native call will
        actually run; memo hits skip the table resolution entirely."""
        base = self._model.base_q_for(demand, self.generations)
        self.c_base_q[: len(base)] = base
        return (
            self.gen_idx, self.c_base_q, len(self.generations),
            mirror.cont_sum, mirror.cont_cnt, self.load_q,
        )

    def _prepare_locked(self, demand, prefer_used: bool, member_slices):
        """The shared pre-native protocol (caller holds the arena lock):
        refresh in standalone mode, resolve the gang encoding, sync the
        model mirror (model raters only), probe the one-slot memo.
        Returns ``(gang, key, have_scores, model_args)``; when
        ``have_scores`` is False the memo has been cleared (the arena is
        about to be overwritten) and the caller must ``_commit_memo(key)``
        after a successful native call. One copy of this invariant — the
        list path and the fused render path must never drift. The memo
        key carries the mirror version: model scores may move without a
        row bump (a calibration sample), and a key that ignored that
        would serve pre-sync scores — exactly the staleness the model's
        cache token exists to kill."""
        if self._mutable:
            self._refresh()
        gang, gang_sig = self._gang_of(member_slices)
        mirror = None
        if self._model is not None:
            mirror = self._ensure_mirror_locked()
        key = self._memo_key(
            demand, prefer_used, gang_sig,
            mirror.version if mirror is not None else None,
        )
        if self._memo[0] == key:
            self._perf.memo_hits += 1
            # arena already holds this exact result; model args unused
            return gang, key, True, None
        self._memo[0] = None  # arena about to be overwritten
        model_args = (
            self._model_args_locked(demand, mirror)
            if mirror is not None else None
        )
        return gang, key, False, model_args

    def _commit_memo(self, key) -> None:
        """Record a completed native call's result as the arena's memo
        (caller holds the arena lock)."""
        self._perf.native_calls += 1
        self._memo[0] = key

    def _run_locked(self, demand, prefer_used: bool, member_slices):
        """Native call under the arena lock; the results land in the
        shared ``out_feas``/``out_score`` arena (valid until the next
        native call in this chain — callers copy or render under the same
        lock hold)."""
        gang, key, have_scores, model_args = self._prepare_locked(
            demand, prefer_used, member_slices
        )
        if have_scores:
            return self.out_feas, self.out_score
        feas, score = native.score_batch(
            self.dims, len(self.infos), self.free, self.total, self.load,
            list(demand.percents), prefer_used, types.PERCENT_PER_CHIP,
            gang,
            hbm_flat=self.hbm,
            hbm_demand=[
                demand.hbm_of(i) for i in range(len(demand.percents))
            ],
            out=(self.out_feas, self.out_score),
            model=model_args,
        )
        self._commit_memo(key)
        return feas, score

    def run(
        self,
        demand,
        prefer_used: bool,
        member_slices: list[tuple[str, str]] | None = None,
        score_hook=None,
    ) -> tuple[list[bool], list[int]]:
        """(feasible per node, final score per node) in candidate order.

        ``score_hook`` is the Python-side scoring fallback for raters
        whose model the native engine cannot (or may not) evaluate
        (``NANOTPU_NATIVE_MODEL=0`` — docs/scoring.md): feasibility
        still comes from the (memoized) native call — placement
        feasibility is rater-independent — but the returned scores are
        ``score_hook(self, demand, feasible)`` over this view's frozen
        row arrays. Hook results are computed fresh on every call and
        NEVER land in the arena memo: the hook reads live model state
        (the contention EWMA) that moves without a row version bump, so
        memoizing it would serve pre-sync scores — exactly the
        staleness the model's cache token exists to kill. The NATIVE
        model path (``model`` set, no hook) has no such problem: its
        memo key carries the mirror version, so its scores memoize like
        any other native result and retire the instant the model
        moves."""
        with self._lock:
            feas, score = self._run_locked(demand, prefer_used, member_slices)
            n = len(self.infos)
            feasible = [bool(feas[i]) for i in range(n)]
            if score_hook is not None:
                return feasible, score_hook(self, demand, feasible)
            return feasible, list(score[:n])

    def pack(
        self, demands, prefer_used: bool, lookahead: int = 1
    ) -> list[tuple[int, int, list[list[int]]]]:
        """Joint greedy-with-lookahead pack of ``demands`` against this
        view's frozen rows in ONE native crossing (ABI 8,
        docs/batch-admission.md). Caller order IS the solve order: the
        native solver keeps a scratch occupancy copy updated in C
        between picks, so demand ``j`` is scored against the state
        demand ``i``'s placement produced. Returns ``(row index, score,
        per-container chip ids)`` per demand, row index -1 when no
        candidate can host it. Scores exclude the gang bonus (the joint
        solve packs capacity; gang affinity keeps shaping the
        pod-at-a-time path) and are byte-equal to the pod-at-a-time
        wire score otherwise — ``lookahead=1`` IS the per-pod argmax,
        the K=1 parity contract tests/test_admit.py pins. Results never
        touch the arena memo: the scratch outputs are per-call arrays,
        so an in-flight Filter's memoized scores stay valid. Raises
        :class:`native.NativeUnavailable` when the caller should fall
        back to the pod-at-a-time path."""
        with self._lock:
            if self._mutable:
                self._refresh()
            # signature grouping: equal (percents, hbm) demands share the
            # solver's per-signature feasibility/score cache, so a
            # K-demand pack costs O(#signatures x nodes + K x dirty)
            # placement evaluations instead of O(K x nodes)
            sig_of: dict[tuple, int] = {}
            reps: list = []
            sigs: list[int] = []
            pcts: list[list[int]] = []
            hbms: list[list[int]] = []
            for d in demands:
                pct = list(d.percents)
                hbm = [d.hbm_of(i) for i in range(len(pct))]
                key = (tuple(pct), tuple(hbm))
                sig = sig_of.get(key)
                if sig is None:
                    sig = sig_of[key] = len(reps)
                    reps.append(d)
                sigs.append(sig)
                pcts.append(pct)
                hbms.append(hbm)
            model_args = None
            if self._model is not None:
                # per-SIGNATURE base rows (each demand shape resolves its
                # own table row), same Q16 integers the ABI 7 path feeds
                mirror = self._ensure_mirror_locked()
                flat: list[int] = []
                for rep in reps:
                    flat.extend(
                        self._model.base_q_for(rep, self.generations)
                    )
                c_base = (ctypes.c_int32 * max(len(flat), 1))(*flat)
                model_args = (
                    self.gen_idx, c_base, len(self.generations),
                    mirror.cont_sum, mirror.cont_cnt, self.load_q,
                )
            self._perf.native_calls += 1
            return native.batch_pack(
                self.dims, len(self.infos), self.free, self.total,
                self.load, pcts, prefer_used, types.PERCENT_PER_CHIP,
                hbm_flat=self.hbm, demand_hbm=hbms,
                demand_sig=sigs, n_sigs=max(len(reps), 1),
                model=model_args,
                lookahead=max(1, min(int(lookahead), 64)),
            )

    # -- fused score+render (the Filter/Prioritize fan-out fast path) ------

    def ensure_renderer(self, names_key: tuple[str, ...]) -> bool:
        """Build the pre-baked JSON fragment blobs for this candidate
        order once (names repeat every scheduling cycle, and the whole
        advanced() chain shares one renderer). Returns False when the
        native renderer is unavailable."""
        with self._lock:
            r = self._renderer_box[0]
            if r is not None and r[0] == names_key:
                return True
            return self._build_renderer(names_key)

    def _build_renderer(self, names_key: tuple[str, ...]) -> bool:
        # caller holds self._lock: the publish of the renderer must not
        # race filter_payload/priorities_payload's capture of it
        if not native.available():
            return False
        n = len(names_key)
        if n != len(self.infos):
            return False
        import json as _json

        qnames = [_json.dumps(nm).encode() for nm in names_key]
        prio = [b'{"Host":%s,"Score":' % q for q in qnames]
        reason = _json.dumps(types.REASON_NO_CAPACITY).encode()
        fail = [b"%s:%s" % (q, reason) for q in qnames]

        def blob(parts):
            off = (ctypes.c_int32 * (n + 1))()
            total = 0
            for i, p in enumerate(parts):
                off[i] = total
                total += len(p)
            off[n] = total
            return b"".join(parts), off

        q_blob, q_off = blob(qnames)
        p_blob, p_off = blob(prio)
        f_blob, f_off = blob(fail)
        # output capacity: every candidate in whichever list is larger,
        # plus digits/braces slack per entry and fixed wrapper text
        cap = max(len(p_blob), len(q_blob) + len(f_blob)) + 16 * n + 64
        out_buf = ctypes.create_string_buffer(cap)
        self._renderer_box[0] = (
            names_key, q_blob, q_off, p_blob, p_off, f_blob, f_off, out_buf
        )
        self._perf.renderer_builds += 1
        return True

    def _payload(self, demand, prefer_used: bool, member_slices,
                 mode: int) -> bytes | None:
        """Fused native score+render: one crossing, zero per-request wire
        allocations. ``mode`` 0 = ExtenderFilterResult, 1 =
        HostPriorityList. None -> caller uses the list-based path."""
        with self._lock:
            r = self._renderer_box[0]
            if r is None:
                return None
            gang, key, have_scores, model_args = self._prepare_locked(
                demand, prefer_used, member_slices
            )
            try:
                payload = native.score_render(
                    self.c_dims, len(self.infos), self.free, self.total,
                    self.load, list(demand.percents), prefer_used,
                    types.PERCENT_PER_CHIP, gang, self.hbm,
                    [demand.hbm_of(i) for i in range(len(demand.percents))],
                    self.out_feas, self.out_score, have_scores, mode,
                    r[1], r[2], r[3], r[4], r[5], r[6], r[7],
                    demands_buf=self.c_demands,
                    model=model_args,
                )
            except native.NativeUnavailable:
                return None
            if not have_scores:
                self._commit_memo(key)
            return payload

    def priorities_payload(
        self, demand, prefer_used: bool, member_slices=None
    ) -> bytes | None:
        """The full HostPriorityList response body, scored and rendered in
        native code. None -> caller uses the list-based path."""
        return self._payload(demand, prefer_used, member_slices, 1)

    def filter_payload(
        self, demand, prefer_used: bool, member_slices=None
    ) -> bytes | None:
        """The full ExtenderFilterResult response body (candidates only —
        the caller handles non-pool nodes), scored and rendered in native
        code. None -> caller uses the list-based path."""
        return self._payload(demand, prefer_used, member_slices, 0)
