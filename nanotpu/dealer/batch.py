"""Batched candidate scoring: persistent flattened node state + one native
call per Filter/Prioritize fan-out.

The per-node path costs Python-loop overhead per candidate (NodeInfo lock,
plan-cache lookup, ctypes marshalling, gang bonus) — at 256 hosts that
Python dominates the scheduling cycle (VERDICT r1 weak #3). The scorer
keeps ctypes arrays of every candidate's per-chip free/total/load, refreshes
only rows whose NodeInfo.version moved, and hands the whole pool to
``native.score_batch`` (native/allocator.cc nanotpu_score_batch), which
returns feasibility + the final score (rate + compactness band + gang
bonus) for every node in one call.

Result parity with the per-node path (NodeInfo.assume / Dealer.score) is
fuzz-enforced by tests/test_native.py.
"""

from __future__ import annotations

import ctypes
import threading

from nanotpu import native, types
from nanotpu.dealer import nodeinfo as nodeinfo_mod
from nanotpu.dealer.nodeinfo import NodeInfo
from nanotpu.topology import parse_slice_coords


class BatchScorer:
    """Flattened state for one (ordered) candidate list of a uniform pool.

    Built when: the native library is loadable, every candidate has the
    same torus dims/chip count (<= 64 chips), and the rater is binpack or
    spread — the Dealer falls back to the per-node path otherwise.
    """

    @staticmethod
    def build(infos: list[NodeInfo]) -> "BatchScorer | None":
        if not infos or not native.available():
            return None
        dims = infos[0].chips.torus.dims
        count = infos[0].chip_count
        if count > 64:
            return None
        for info in infos:
            if info.chips.torus.dims != dims or info.chip_count != count:
                return None  # heterogeneous pool
        return BatchScorer(infos, dims, count)

    def __init__(self, infos: list[NodeInfo], dims, chip_count: int):
        self.infos = infos
        self.dims = tuple(dims)
        self.chip_count = chip_count
        n, c = len(infos), chip_count
        self._lock = threading.Lock()  # buffers shared across verb threads
        self.free = (ctypes.c_int32 * (n * c))()
        self.total = (ctypes.c_int32 * (n * c))()
        self.load = (ctypes.c_double * (n * c))()
        self.hbm = (ctypes.c_int32 * (n * c))()  # -1 == untracked
        self.versions: list[int | None] = [None] * n
        #: nodeinfo.state_generation() at last refresh; -1 forces the
        #: first refresh to probe every row
        self._last_state_gen = -1
        #: bumped whenever _refresh copies any row; memo-key component
        self.state_rev = 0
        # (demand hash, state_rev, gang sig) -> (feasible, scores): Filter
        # and the immediately following Prioritize share one native call
        self._memo: tuple | None = None
        #: (names_key, qnames blob/off, prio blob/off, fail blob/off,
        #: out buffer) — pre-baked JSON fragments for the native renderers
        self._renderer: tuple | None = None
        # gang sig -> encoded ctypes arrays (a gang's member set only
        # changes when one of its pods binds; re-encoding per verb wastes
        # ~0.1ms at 256 hosts)
        self._gang_cache: dict[tuple, tuple] = {}
        # static gang geometry per node
        self.slice_names = [i.slice_name for i in infos]
        self.node_coords = (ctypes.c_int32 * (n * 3))()
        self.coord_ok = (ctypes.c_uint8 * n)()
        for idx, info in enumerate(infos):
            try:
                cd = (
                    parse_slice_coords(info.slice_coords)
                    if info.slice_coords else None
                )
            except ValueError:
                cd = None
            if cd is not None:
                self.coord_ok[idx] = 1
                self.node_coords[3 * idx] = cd[0]
                self.node_coords[3 * idx + 1] = cd[1]
                self.node_coords[3 * idx + 2] = cd[2]

    def _refresh(self) -> None:
        # one comparison answers "did ANY node change anywhere" — the
        # common fan-out case (nothing changed since the last verb) skips
        # the per-candidate version probe loop entirely. Captured BEFORE
        # probing: a mutation landing mid-loop re-probes next refresh.
        gen = nodeinfo_mod.state_generation()
        if gen == self._last_state_gen:
            return
        c = self.chip_count
        changed = False
        for idx, info in enumerate(self.infos):
            # cheap unlocked probe first: versions only ever increment
            if info.version == self.versions[idx]:
                continue
            with info.lock:
                v = info.version
                base = idx * c
                for j, chip in enumerate(info.chips.chips):
                    self.free[base + j] = chip.percent_free
                    self.total[base + j] = chip.percent_total
                    self.load[base + j] = chip.load
                    self.hbm[base + j] = (
                        chip.hbm_free_mib if chip.hbm_total_mib else -1
                    )
                self.versions[idx] = v
            changed = True
        if changed:
            self.state_rev += 1
        self._last_state_gen = gen

    def _gang_arrays(self, member_slices: list[tuple[str, str]]):
        """Encode gang member host cells per slice for the native call.
        Mirrors gang.GangScorer.__init__: one unparsable coord voids the
        whole slice's cells (those candidates get the base bonus)."""
        by_slice: dict[str, list[str]] = {}
        for slc, coords in member_slices:
            if slc:
                by_slice.setdefault(slc, []).append(coords)
        if not by_slice:
            return None
        slice_index = {slc: i for i, slc in enumerate(by_slice)}
        cells_flat: list[int] = []
        offsets = [0]
        for slc, coord_strs in by_slice.items():
            try:
                cells = {parse_slice_coords(c) for c in coord_strs if c}
            except ValueError:
                cells = set()
            for cell in sorted(cells):
                cells_flat.extend(cell)
            offsets.append(len(cells_flat) // 3)
        n = len(self.infos)
        node_slice = (ctypes.c_int32 * n)(
            *(slice_index.get(s, -1) for s in self.slice_names)
        )
        n_slices = len(by_slice)
        c_cells = (ctypes.c_int32 * max(len(cells_flat), 1))(*cells_flat)
        c_off = (ctypes.c_int32 * (n_slices + 1))(*offsets)
        return (
            node_slice, self.node_coords, self.coord_ok,
            n_slices, c_cells, c_off,
        )

    def _run_locked(self, demand, prefer_used: bool, member_slices):
        """Native call under self._lock; returns the memoized
        (feasible ctypes u8, score ctypes i32) buffers — valid only while
        the lock is held OR until the next state change (the memo keeps
        them alive; a fresh call allocates fresh buffers)."""
        self._refresh()
        gang_sig = tuple(member_slices) if member_slices else None
        key = (demand.hash(), prefer_used, self.state_rev, gang_sig)
        if self._memo is not None and self._memo[0] == key:
            return self._memo[1], self._memo[2]
        gang = None
        if member_slices:
            if gang_sig in self._gang_cache:
                gang = self._gang_cache[gang_sig]
            else:
                gang = self._gang_arrays(member_slices)
                self._gang_cache[gang_sig] = gang
                while len(self._gang_cache) > 64:
                    self._gang_cache.pop(next(iter(self._gang_cache)))
        feas, score = native.score_batch(
            self.dims, len(self.infos), self.free, self.total, self.load,
            list(demand.percents), prefer_used, types.PERCENT_PER_CHIP,
            gang,
            hbm_flat=self.hbm,
            hbm_demand=[
                demand.hbm_of(i) for i in range(len(demand.percents))
            ],
        )
        self._memo = (key, feas, score)
        return feas, score

    def run(
        self,
        demand,
        prefer_used: bool,
        member_slices: list[tuple[str, str]] | None = None,
    ) -> tuple[list[bool], list[int]]:
        """(feasible per node, final score per node) in candidate order."""
        with self._lock:
            feas, score = self._run_locked(demand, prefer_used, member_slices)
            n = len(self.infos)
            return [bool(feas[i]) for i in range(n)], list(score[:n])

    # -- fused score+render (the Filter/Prioritize fan-out fast path) ------

    def ensure_renderer(self, names_key: tuple[str, ...]) -> bool:
        """Build the pre-baked JSON fragment blobs for this candidate
        order once (names repeat every scheduling cycle). Returns False
        when the native renderer is unavailable."""
        with self._lock:
            if self._renderer is not None and self._renderer[0] == names_key:
                return True
            return self._build_renderer(names_key)

    def _build_renderer(self, names_key: tuple[str, ...]) -> bool:
        # caller holds self._lock: the publish of self._renderer must not
        # race filter_payload/priorities_payload's capture of it
        if not native.available():
            return False
        n = len(names_key)
        if n != len(self.infos):
            return False
        import json as _json

        qnames = [_json.dumps(nm).encode() for nm in names_key]
        prio = [b'{"Host":%s,"Score":' % q for q in qnames]
        reason = _json.dumps(types.REASON_NO_CAPACITY).encode()
        fail = [b"%s:%s" % (q, reason) for q in qnames]

        def blob(parts):
            off = (ctypes.c_int32 * (n + 1))()
            total = 0
            for i, p in enumerate(parts):
                off[i] = total
                total += len(p)
            off[n] = total
            return b"".join(parts), off

        q_blob, q_off = blob(qnames)
        p_blob, p_off = blob(prio)
        f_blob, f_off = blob(fail)
        # output capacity: every candidate in whichever list is larger,
        # plus digits/braces slack per entry and fixed wrapper text
        cap = max(len(p_blob), len(q_blob) + len(f_blob)) + 16 * n + 64
        out_buf = ctypes.create_string_buffer(cap)
        self._renderer = (
            names_key, q_blob, q_off, p_blob, p_off, f_blob, f_off, out_buf
        )
        return True

    def priorities_payload(
        self, demand, prefer_used: bool, member_slices=None
    ) -> bytes | None:
        """The full HostPriorityList response body, scored and rendered in
        native code. None -> caller uses the list-based path."""
        with self._lock:
            r = self._renderer  # captured under lock: rebuilds can't race
            if r is None:
                return None
            _, score = self._run_locked(demand, prefer_used, member_slices)
            try:
                return native.render_priorities(
                    r[3], r[4], score, len(self.infos), r[7]
                )
            except native.NativeUnavailable:
                return None

    def filter_payload(
        self, demand, prefer_used: bool, member_slices=None
    ) -> bytes | None:
        """The full ExtenderFilterResult response body (candidates only —
        the caller handles non-pool nodes), scored and rendered in native
        code. None -> caller uses the list-based path."""
        with self._lock:
            r = self._renderer  # captured under lock: rebuilds can't race
            if r is None:
                return None
            feas, _ = self._run_locked(demand, prefer_used, member_slices)
            try:
                return native.render_filter(
                    r[1], r[2], r[5], r[6], feas, len(self.infos), b"", r[7]
                )
            except native.NativeUnavailable:
                return None
