"""Live chip-usage store for load-aware scheduling.

Rebuild of ``pkg/dealer/nodeusage.go`` + the staleness logic of
``pkg/dealer/stats.go``. Two deliberate fixes:

* timestamps are UTC epoch seconds — the reference compared against
  wall-clock in a hardcoded Asia/Shanghai zone (stats.go:36, type.go:13);
* one lock per store, no unlocked getter variants (nodeusage.go:48-56 were
  fragile).

Values are utilization fractions in [0, 1]; out-of-range and stale samples
read as 0 (scheduling must degrade to load-blind, never crash).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from nanotpu.analysis.witness import make_lock

#: Grace added to a policy's sync period when judging staleness
#: (reference: 5 min, type.go:6).
STALENESS_GRACE_S = 300.0


@dataclass
class ChipUsageSample:
    core: float = 0.0
    memory: float = 0.0
    updated_at: float = 0.0  # epoch seconds, UTC


class UsageStore:
    """node -> chip -> latest usage sample."""

    def __init__(self, window_s: float = 15.0):
        self._lock = make_lock("UsageStore._lock")
        self._data: dict[str, dict[int, ChipUsageSample]] = {}
        #: expected sync period; staleness cutoff = window + grace
        self.window_s = window_s

    def update(
        self,
        node: str,
        chip: int,
        core: float | None = None,
        memory: float | None = None,
        now: float | None = None,
    ) -> None:
        ts = time.time() if now is None else now
        with self._lock:
            sample = self._data.setdefault(node, {}).setdefault(
                chip, ChipUsageSample()
            )
            if core is not None:
                sample.core = core
            if memory is not None:
                sample.memory = memory
            sample.updated_at = ts

    def effective_load(self, node: str, chip: int, now: float | None = None) -> float:
        """Usable load signal for scoring: max(core, memory) utilization,
        0 when absent, stale, or out of range (nodeusage.go:82-111)."""
        ts = time.time() if now is None else now
        with self._lock:
            sample = self._data.get(node, {}).get(chip)
        if sample is None:
            return 0.0
        if ts - sample.updated_at > self.window_s + STALENESS_GRACE_S:
            return 0.0
        load = max(sample.core, sample.memory)
        if not 0.0 <= load <= 1.0:
            return 0.0
        return load

    def forget_node(self, node: str) -> None:
        with self._lock:
            self._data.pop(node, None)

    def snapshot(self) -> dict[str, dict[int, dict]]:
        with self._lock:
            return {
                node: {
                    chip: {
                        "core": s.core,
                        "memory": s.memory,
                        "updated_at": s.updated_at,
                    }
                    for chip, s in chips.items()
                }
                for node, chips in self._data.items()
            }
