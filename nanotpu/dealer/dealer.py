"""The Dealer: authoritative in-memory allocation state + K8s writer.

Rebuild of ``pkg/dealer/dealer.go``. Same verb semantics (Assume / Score /
Bind / Allocate / Release / Forget, boot-time reconstruction from assumed-pod
annotations), different concurrency and failure design:

* **per-node locks instead of one global mutex** — the reference serialized
  every verb on one ``sync.Mutex`` (dealer.go:81,90,139,156), making
  concurrent-pod p50 lock-dominated (SURVEY §6). Here the dealer lock only
  guards the maps; chip accounting locks per node, and Assume fans out over
  candidate nodes on a shared thread pool (vs the reference's fixed 4
  goroutines, dealer.go:113-134).
* **no swallowed errors** — the reference returned success when a non-
  conflict pod-update error occurred during Bind (dealer.go:188); we raise,
  and also roll chip accounting back (the reference leaked it until Release).
* **node eviction exists** — NodeMaps never evicted deleted nodes in the
  reference (dealer.go:271-301).
* **per-pool snapshot shards** (``shards="auto"``) — read verbs consume
  RCU-published snapshots partitioned by slice family
  (:mod:`nanotpu.dealer.shard`): a commit republishes only its own
  shard's views (incremental deltas), Filter/Prioritize fan native
  scoring out across shards in parallel, and results merge back into
  candidate order exactly (per-node scores are pure functions, so the
  partition costs nothing in placement quality — docs/sharding.md).
  ``shards=1`` (default) keeps the whole fleet in one shard with
  byte-identical behavior to the unsharded dealer.
* **pipelined bind commits** (``pipeline_depth>1`` — docs/bind-pipeline.md)
  — the write path scales the way r6 scaled reads: snapshot publishes
  COALESCE (a commit only enqueues its delta; the next reader of the
  shard drains everything pending into one swap through a non-blocking
  leader election, so a storm burst costs one view advance per READ
  instead of one per bind), the redundant second republish of a clean
  bind is skipped outright (``perf.publish_skips``), and a complete
  strict gang's member commits fan out concurrently through a bounded
  commit pool so a 64-member gang costs ~1 write round-trip, not 64
  sequential ones. Depth 1 with coalescing off (the default) takes the
  exact pre-pipeline code path — wire behavior byte-identical.

The K8s API remains the durable checkpoint: placement lives in pod
annotations, and a restarted dealer replays them (dealer.go:58-72,279-299).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from nanotpu import native, types
from nanotpu.allocator.core import Demand, Plan
from nanotpu.analysis.witness import make_lock, make_rlock
from nanotpu.allocator.rater import Rater
from nanotpu.dealer.batch import BatchScorer
from nanotpu.dealer.gang import (
    GangBarrier,
    GangScorer,
    GangTracker,
    WaitObservation,
)
from nanotpu.dealer.nodeinfo import NodeInfo
from nanotpu.dealer.perf import PerfCounters
from nanotpu.dealer.shard import (
    DEFAULT_SHARD_KEY,
    _Shard,
    _Snapshot,
    merge_top_k,
    shard_key_of,
    splice_filter_payloads,
    splice_priorities_payloads,
)
from nanotpu.dealer.usage import UsageStore
from nanotpu.k8s import events
from nanotpu.k8s.client import ApiError, Clientset, ConflictError, NotFoundError
from nanotpu.k8s.events import EventRecorder
from nanotpu.k8s.objects import Node, Pod
from nanotpu.k8s.resilience import BreakerOpenError, FencedError
from nanotpu.obs import set_current
from nanotpu.obs.decisions import (
    REASON_ALREADY_BOUND,
    REASON_API_ERROR,
    REASON_BIND_FAILED,
    REASON_BREAKER_OPEN,
    REASON_FENCED,
    REASON_GANG_TIMEOUT,
    REASON_INSUFFICIENT_CHIPS,
    REASON_NODE_CHANGED,
    REASON_NOT_TPU_NODE,
    REASON_POD_RELEASED,
)
from nanotpu.utils import node as nodeutil
from nanotpu.utils import pod as podutil
from nanotpu.utils.deadline import Deadline, check as deadline_check

log = logging.getLogger("nanotpu.dealer")

#: Bind retries on optimistic-lock conflicts (reference looped on the same
#: error message, dealer.go:178-186).
BIND_CONFLICT_RETRIES = 3

#: Number of UNKNOWN candidate nodes above which Assume uses the thread
#: pool. Warm-node checks are ~2-3us and GIL-bound, so the pool loses on
#: them at ANY fan-out (measured 6x slower at 256 warm nodes); cold nodes
#: cost a blocking apiserver GET each, and those must overlap.
ASSUME_COLD_POOL_THRESHOLD = 2

#: Max released-pod tombstones kept for idempotency (K8s UIDs never recur,
#: so eviction only risks re-releasing ancient, long-deleted pods).
RELEASED_TOMBSTONES_MAX = 100_000

#: Cross-shard pack refinement cap (docs/batch-admission.md "The
#: cross-shard reduce"): each round re-packs every shard with (its
#: reduce winners + the still-unplaced demands) so the leftovers price
#: against the true residual capacity instead of round 1's everything-
#: lands-here over-charge. The error shrinks geometrically — a deep
#: batch normally converges in one extra round; the cap only bounds the
#: genuinely-infeasible tail, which falls back pod-at-a-time anyway.
_PACK_REFINE_ROUNDS = 3


class BindError(Exception):
    """Bind failed; chip accounting has been rolled back. ``reason`` is
    the typed audit code (nanotpu.obs.decisions) the decision ledger
    records, so "why did this bind fail" is an enum, not a regex over
    the message."""

    def __init__(self, message: str, reason: str = REASON_BIND_FAILED):
        super().__init__(message)
        self.reason = reason


#: sentinel distinguishing "no cached view yet" from a cached None verdict
_VIEW_MISSING = object()


class _Reservation:
    """A strict-gang member's applied-but-uncommitted chip reservation.

    Registered with the Dealer for the (up to gang-timeout) park window so
    node rebuilds migrate it like a tracked pod: refresh_node re-applies
    the plan on the fresh NodeInfo; remove_node (or a failed re-apply)
    marks it invalid and the parked bind fails instead of double-booking.
    """

    __slots__ = ("node_name", "info", "plan", "valid", "gang_key", "pod",
                 "trace", "parked_at")

    def __init__(self, node_name: str, info, plan: Plan, gang_key: str,
                 pod: Pod | None = None, trace=None,
                 parked_at: float = 0.0):
        self.node_name = node_name
        self.info = info
        self.plan = plan
        self.valid = True
        self.gang_key = gang_key
        #: the pod + trace of the parked bind: a batched gang commit
        #: (Dealer._commit_gang_batch) runs this member's API writes on a
        #: commit-pool worker, which needs the member's own request
        #: context rather than the opener's
        self.pod = pod
        self.trace = trace
        #: park timestamp on the dealer's clock (obs clock when a bundle
        #: is attached — virtual in the sim — else monotonic): the
        #: telemetry timeline's oldest-park-age series reads it
        self.parked_at = parked_at


def plan_from_pod(pod: Pod) -> Plan | None:
    """Reconstruct a Plan from a bound pod's annotations
    (NewPlanFromPod, allocate.go:29-50). None when annotations are absent or
    corrupt — the caller must then leave the pod unaccounted and log loudly
    rather than guess."""
    assignments = podutil.get_assigned_chips(pod)
    if assignments is None:
        return None
    demand = Demand.from_pod(pod)
    ordered = [assignments.get(name, []) for name in demand.container_names]
    # sanity: every TPU-demanding container must have chips
    for i, percent in enumerate(demand.percents):
        if percent > 0 and not ordered[i]:
            return None
        if percent >= types.PERCENT_PER_CHIP and (
            len(ordered[i]) != percent // types.PERCENT_PER_CHIP
        ):
            return None
    return Plan(demand=demand, assignments=ordered)


class Dealer:
    """See module docstring. One instance per scheduler process."""

    def __init__(
        self,
        client: Clientset,
        rater: Rater,
        usage: UsageStore | None = None,
        assume_workers: int = 8,
        recorder: EventRecorder | None = None,
        obs=None,
        shards: int | str = 1,
        pipeline_depth: int = 1,
        coalesce: bool | None = None,
        ha_log=None,
        restore_from: str = "",
    ):
        self.client = client
        self.rater = rater
        #: rater integration hooks, resolved at init and RE-resolved only
        #: by :meth:`install_rater` (verified policy-program hot reload,
        #: docs/policy-programs.md). ``_native_model`` is the rater's
        #: ThroughputModel when the native engine can evaluate its
        #: formula in C (ABI 7, docs/scoring.md): scoring views mirror
        #: the model's quantized state and the fused score+render path
        #: serves the rater like any other — ``NANOTPU_NATIVE_MODEL=0``
        #: forces the Python row hook instead. ``_batch_hook`` is that
        #: Python-side batch row scorer (the reference implementation,
        #: and the fallback when the native model path is off):
        #: feasibility still runs native, scores come from the hook over
        #: the frozen rows, and the fused path is refused
        #: (``perf.hook_refusals``). ``_rater_observe`` taps every
        #: per-card usage write for online contention calibration;
        #: ``_rater_forget`` drops a removed node's calibration state.
        self._batch_hook = getattr(rater, "batch_score_rows", None)
        nm_fn = getattr(rater, "native_model", None)
        self._native_model = (
            nm_fn()
            if nm_fn is not None
            and os.environ.get("NANOTPU_NATIVE_MODEL", "1") != "0"
            and native.available()
            else None
        )
        #: True exactly when batch scoring must route through the Python
        #: hook (and the fused path must refuse): a hook rater whose
        #: model the native engine cannot (or may not) evaluate
        self._hook_active = (
            self._batch_hook is not None and self._native_model is None
        )
        self._rater_observe = getattr(rater, "observe_usage", None)
        self._rater_forget = getattr(rater, "forget_node", None)
        self.usage = usage or UsageStore()
        #: optional Observability bundle (nanotpu.obs): bind-commit and
        #: gang-wait histograms observe through it; None costs nothing
        #: (SchedulerAPI attaches its own bundle when the dealer has none)
        self.obs = obs
        #: the clock telemetry-visible timestamps use (reservation park
        #: times): the bundle's injectable clock when one is attached —
        #: virtual in the sim, so park ages are deterministic — else wall
        self._clock = obs.tracer.clock if obs is not None else time.monotonic
        # K8s Events on bind outcomes — the reference built a recorder and
        # never emitted (controller.go:78-81, SURVEY §5); here `kubectl
        # describe pod` shows the placement decision
        self.recorder = recorder or EventRecorder(client)
        self._lock = make_rlock("Dealer._lock")  # guards the maps below only
        self._nodes: dict[str, NodeInfo] = {}
        self._non_tpu: set[str] = set()  # negative cache for _node_info
        self._pods: dict[str, Pod] = {}  # uid -> annotated pod (PodMaps)
        # uid -> the NodeInfo INSTANCE holding this pod's chip accounting.
        # A node rebuild (refresh_node) swaps the instance in _nodes; this
        # map is what lets release/bind tell "my chips are on the current
        # object" from "my chips are stranded on an orphaned one" — the
        # identity check that makes the refresh/bind handoff race-free.
        self._accounted: dict[str, NodeInfo] = {}
        # released-uid tombstones, insertion-ordered for LRU bounding
        # (ReleasedPodMap analogue)
        self._released: dict[str, None] = {}
        self._pool = ThreadPoolExecutor(
            max_workers=assume_workers, thread_name_prefix="assume"
        )
        self.gangs = GangTracker(on_gang_empty=self._drop_gang_barrier)
        #: (gang key, gangs.rev, member slices) memo — see _gang_member_slices
        self._gms_cache: tuple | None = None
        #: gang key -> GangBarrier for strict (all-or-nothing) gangs
        self._gang_barriers: dict[str, GangBarrier] = {}
        #: uid -> parked strict-gang reservation (see _Reservation)
        self._reserved: dict[str, _Reservation] = {}
        #: pod uid -> Demand. Bind re-fetches the pod from the apiserver, so
        #: the fresh object misses Demand.from_pod's per-object memo even
        #: though container resource limits are immutable for a pod's life.
        self._demand_uid: dict[str, Demand] = {}
        #: bumped on any structural _nodes change; structural publishes
        #: rebuild the snapshot's node mapping and drop its views
        self._nodes_epoch = 0
        #: optional capacity-recovery plane
        #: (:class:`nanotpu.recovery.RecoveryPlane`), attached by the
        #: process that owns one (cmd/main's --recovery, harnesses);
        #: ``/debug/decisions`` surfaces its status when present
        self.recovery = None
        #: optional batch admitter
        #: (:class:`nanotpu.dealer.admit.BatchAdmitter`), attached by the
        #: process that owns one (cmd/main's --batch, the sim's batch
        #: scenario knob, harnesses); ``/debug/decisions`` surfaces its
        #: status when present. None == batch admission off == zero new
        #: code on any existing path (docs/batch-admission.md).
        self.batch = None
        #: gang pods whose Filter found ZERO feasible candidates — the
        #: production recovery trigger for gangs that cannot even
        #: reserve (a member must reserve to park at the barrier, so a
        #: fully-starved gang would otherwise be invisible to
        #: :meth:`parked_gang_pods`). uid -> (pod, first-starved
        #: monotonic); maintained only with a recovery plane attached,
        #: bounded, entries retire on a feasible Filter / bind / TTL.
        self._starved: dict[str, tuple[Pod, float]] = {}
        #: request-level hot-path attribution (bench deltas + /metrics);
        #: shard-level counters (publishes, view work, native calls) live
        #: on each shard's own PerfCounters — in single-shard mode the one
        #: shard ALIASES this object, so existing reads see everything
        self.perf = PerfCounters()
        #: RCU read state, one publication domain per slice family
        #: (nanotpu.dealer.shard): each shard owns its published snapshot,
        #: publisher lock, commit sequence, and structural epoch, so a
        #: commit republishes only its own shard. ``shards=1`` puts the
        #: whole fleet in one shard (behavior byte-identical to the
        #: unsharded dealer); ``shards="auto"`` keys shards by
        #: generation + slice family. Ordering rule: _republish_shard
        #: takes the shard's _publish_lock then briefly self._lock —
        #: NEVER call it while holding self._lock, and never hold two
        #: shard publish locks at once.
        if shards not in (1, "auto"):
            raise ValueError(f"shards must be 1 or 'auto', got {shards!r}")
        self._shard_fn = shard_key_of if shards == "auto" else None
        self._shards: dict[str, _Shard] = {}
        #: node name -> shard key (sharded mode only; dealer lock)
        self._shard_of: dict[str, str] = {}
        #: shard key -> {node name -> NodeInfo} (sharded mode only)
        self._members: dict[str, dict[str, NodeInfo]] = {}
        #: candidate tuple -> (nodes epoch, partition) — requests repeat
        #: the same candidate list every cycle; bounded like snap.views
        self._part_cache: dict[tuple, tuple] = {}
        if self._shard_fn is None:
            self._default_shard = _Shard(DEFAULT_SHARD_KEY, perf=self.perf)
            self._shards[DEFAULT_SHARD_KEY] = self._default_shard
        else:
            self._default_shard = None
        #: commit pipeline (docs/bind-pipeline.md): ``pipeline_depth`` is
        #: the bounded worker count for batched strict-gang commit
        #: fan-out (1 == no pool, members commit on their own bind
        #: threads — the pre-pipeline behavior); ``coalesce`` turns
        #: publish coalescing on/off independently (default: on exactly
        #: when the pipeline is). Depth 1 + coalescing off is the exact
        #: r6 code path, byte-identical on the wire.
        if (
            not isinstance(pipeline_depth, int)
            or isinstance(pipeline_depth, bool)
            or pipeline_depth < 1
        ):
            raise ValueError(
                f"pipeline_depth must be an int >= 1, got {pipeline_depth!r}"
            )
        self._pipeline_depth = pipeline_depth
        self._coalesce = (
            pipeline_depth > 1 if coalesce is None else bool(coalesce)
        )
        self._commit_pool = (
            ThreadPoolExecutor(
                max_workers=pipeline_depth, thread_name_prefix="commit"
            )
            if pipeline_depth > 1 else None
        )
        self._publish_enabled = False
        self._closed = False
        #: HA delta stream (docs/ha.md): when a
        #: :class:`nanotpu.ha.delta.DeltaLog` is attached, every commit
        #: point that already calls ``_republish`` also appends ONE typed
        #: record (node register/evict, bind/release, usage batches, gang
        #: park/unpark, view warms) for the warm standby to tail. None ==
        #: HA off == one attribute check per commit point, zero
        #: allocations (the bench A/B attribution diff pins it).
        self.ha = ha_log
        #: usage samples accumulated between deferred publishes — one
        #: ``usage`` delta per metric sweep, not one per chip
        self._ha_usage: list = []
        # boot-time GC pause (both boot paths): state reconstruction is
        # an allocation storm — tens of thousands of NodeInfos, chips,
        # and pod objects — and the cyclic collector's threshold passes
        # fire repeatedly mid-boot on garbage that is all still live,
        # measurably stretching restart latency (the same discipline the
        # bench applies around timed windows)
        import gc as _gc

        gc_was = _gc.isenabled()
        if gc_was:
            _gc.disable()
        try:
            restored = False
            if restore_from:
                # replay-free warm restart (docs/ha.md): rebuild from
                # the local checkpoint's snapshot + delta tail instead
                # of the O(fleet) annotation scan; any failure falls
                # back whole
                restored = self._restore_from_checkpoint(restore_from)
            if not restored:
                self._warm_from_cluster()
        finally:
            if gc_was:
                _gc.enable()
        self._publish_enabled = True
        self._republish()
        if self._coalesce:
            # boot publishes eagerly even under coalescing: a freshly
            # constructed dealer must expose its warm mapping (readyz,
            # tests, debug surfaces) without waiting for a first reader
            for shard in list(self._shards.values()):
                self._drain_shard(shard)
        #: boot-time assumed-pod reconstruction is complete; one of the two
        #: /readyz gates (the other is the controller's informer sync)
        self.warmed = True

    # -- boot-time state reconstruction (dealer.go:58-72) ------------------
    def _warm_from_cluster(self) -> None:
        # materialize every TPU node up front so occupancy/status cover the
        # whole pool (the reference built NodeInfo lazily per Filter,
        # dealer.go:271-301, leaving idle nodes invisible to /status)
        try:
            for node in self.client.list_nodes():
                self._node_info(node.name, node)
        except ApiError as e:
            log.warning("boot node list failed: %s", e)
        try:
            assumed = self.client.list_pods(
                label_selector={types.ANNOTATION_ASSUME: "true"}
            )
        except ApiError as e:
            log.warning("boot pre-warm list failed: %s", e)
            return
        for pod in assumed:
            if podutil.is_completed_pod(pod) or not pod.node_name:
                continue
            self._learn_bound_pod(pod)

    def _learn_bound_pod(self, pod: Pod) -> bool:
        """Fold an externally-bound pod into chip accounting (replay path,
        dealer.go:279-299 + syncPod Allocate, controller.go:210-243).

        The uid check, the chip allocation, and the map insert are ONE
        critical section, so two concurrent syncs of the same pod cannot
        both allocate, and a concurrent refresh_node cannot interleave a
        replay between our check and our commit. Blocking work (the
        apiserver GET for an unknown node) happens before the lock."""
        with self._lock:
            if pod.uid in self._pods or pod.uid in self._released:
                return False
        info = self._node_info(pod.node_name)  # may GET; no locks held
        if info is None:
            log.warning(
                "pod %s bound to unknown node %s", pod.key(), pod.node_name
            )
            return False
        plan = plan_from_pod(pod)
        if plan is None:
            log.error(
                "pod %s has assume label but missing/corrupt chip annotations; "
                "leaving unaccounted", pod.key(),
            )
            return False
        with self._lock:
            if pod.uid in self._pods or pod.uid in self._released:
                return False  # lost to a concurrent sync / bind / release
            current = self._nodes.get(pod.node_name)
            if current is not None:
                info = current  # node rebuilt while we resolved the plan
            try:
                info.allocate(plan)
            except ValueError as e:
                log.error(
                    "replaying pod %s onto %s failed: %s", pod.key(), info.name, e
                )
                return False
            self._pods[pod.uid] = pod
            self._accounted[pod.uid] = info
            # gang membership under the same lock as the commit: recording
            # after a concurrent release() completed would leave a phantom
            # member that forget_pod never clears (same rule as _bind)
            gang = podutil.gang_of(pod)
            if gang:
                self.gangs.record_bound(
                    f"{pod.namespace}/{gang[0]}", gang[1], pod.uid, pod.node_name
                )
        self._ha_emit("bound", pod=pod.raw)
        return True

    # -- node registry -----------------------------------------------------
    def _node_info(self, name: str, node: Node | None = None) -> NodeInfo | None:
        """Get-or-build per-node state (getNodeInfo, dealer.go:271-301).

        Non-TPU nodes are tombstoned so every Filter/Prioritize over a large
        mixed cluster doesn't re-GET each non-TPU candidate; the tombstone is
        cleared when the node changes (observe_node / remove_node / resync).
        """
        with self._lock:
            info = self._nodes.get(name)
            if info is None and name in self._non_tpu:
                return None
        if info is not None:
            return info
        if node is None:
            try:
                node = self.client.get_node(name)
            except NotFoundError:
                with self._lock:
                    self._non_tpu.add(name)
                    self._nodes_epoch += 1
                return None
            except ApiError:
                return None
        if not nodeutil.is_tpu_node(node):
            with self._lock:
                self._non_tpu.add(name)
                self._nodes_epoch += 1
            return None
        new_info = NodeInfo(node)
        with self._lock:
            # lost the race? keep the winner
            existing = self._nodes.get(name)
            if existing is not None:
                return existing
            self._register_node(name, new_info)
            # a node can reappear with pods still tracked (node object
            # deleted and re-created while its pods kept running): their
            # chips live on the orphaned NodeInfo — migrate them INSIDE the
            # same critical section, or a concurrent bind sees the fresh
            # instance as fully free and double-books (r1 review finding)
            # nanolint: ignore[lock-discipline]: the replay only touches
            # THIS node, which the line above just put in _nodes, so the
            # nested _node_info hits the map and never GETs the apiserver
            self._replay_tracked(name)
        self._ha_emit("node", raw=node.raw)
        return new_info

    def _register_node(self, name: str, info: NodeInfo) -> None:
        """Insert (or replace) a NodeInfo in the registry AND its shard's
        membership (caller holds ``self._lock``). Bumps the structural
        epochs that make the next publish rebuild the affected shard's
        mapping and drop its views. A relabel that moves the node across
        slice families bumps BOTH shards."""
        self._nodes[name] = info
        self._nodes_epoch += 1
        if self._shard_fn is None:
            return
        key = self._shard_fn(info)
        old_key = self._shard_of.get(name)
        if old_key is not None and old_key != key:
            self._members[old_key].pop(name, None)
            self._shards[old_key].epoch += 1
        shard = self._shards.get(key)
        if shard is None:
            shard = self._shards[key] = _Shard(key)
            self._members[key] = {}
        self._shard_of[name] = key
        self._members[key][name] = info
        shard.epoch += 1

    def _unregister_node(self, name: str) -> None:
        """Evict a node from the registry and its shard's membership
        (caller holds ``self._lock``)."""
        self._nodes.pop(name, None)
        self._nodes_epoch += 1
        if self._shard_fn is None:
            return
        key = self._shard_of.pop(name, None)
        if key is not None:
            self._members[key].pop(name, None)
            self._shards[key].epoch += 1

    def _replay_tracked(self, name: str) -> None:
        """Migrate tracked pods of node ``name`` whose accounting lives on
        an orphaned NodeInfo instance onto the current one.

        Caller MUST hold ``self._lock`` (it is an RLock; the nested
        ``_learn_bound_pod`` commits re-enter it), so no other thread can
        observe the fresh NodeInfo with the migration half done. Nothing in
        here blocks: the node is already in the map, so ``_node_info``
        inside the replay never hits the apiserver."""
        current = self._nodes.get(name)
        if current is None:
            return
        stranded = [
            p for p in self._pods.values()
            if p.node_name == name
            and self._accounted.get(p.uid) is not current
            and podutil.get_assigned_chips(p) is not None
        ]
        for p in stranded:
            self._pods.pop(p.uid, None)
            self._accounted.pop(p.uid, None)
        for p in stranded:
            self._learn_bound_pod(p)

    def observe_node(self, node: Node) -> None:
        """Materialize per-node state for a newly seen/changed node."""
        with self._lock:
            self._non_tpu.discard(node.name)
            self._nodes_epoch += 1
        self._node_info(node.name, node)
        self._republish()

    def remove_node(self, name: str) -> None:
        """Evict a deleted/resized node (missing in the reference)."""
        with self._lock:
            self._unregister_node(name)
            self._non_tpu.discard(name)
            for uid, res in self._reserved.items():
                # parked strict-gang reservations on this node are gone;
                # their binds must fail rather than commit to a dead node
                if res.node_name == name and res.valid:
                    self._invalidate_reservation(uid, res)
        self.usage.forget_node(name)
        if self._rater_forget is not None:
            self._rater_forget(name)
        self._ha_emit("node_gone", name=name)
        self._republish()

    def refresh_node(self, node: Node) -> bool:
        """Node MODIFIED handler: when capacity or topology labels drift
        from the tracked view, rebuild the NodeInfo and replay this node's
        tracked pods onto the fresh accounting. (The reference never
        noticed resizes — SURVEY bug list 'NodeMaps never evicts
        deleted/resized nodes'.) Returns True when a rebuild happened."""
        if not nodeutil.is_tpu_node(node):
            # the node stopped advertising TPU capacity entirely
            with self._lock:
                known = node.name in self._nodes
            if known:
                self.remove_node(node.name)
            return known
        # rebuild needed: node is new, REGAINED capacity (remove_node left
        # its pods tracked — a device-plugin restart does exactly this), or
        # drifted. Replay this node's ANNOTATED pods onto fresh accounting.
        # Reservation-only pods (mid-bind, no chip annotations yet) stay in
        # the map untouched — the owning bind thread finishes and detects
        # the rebuild itself (see _bind's is-current check). The swap and
        # the un-tracking are one critical section so no other thread can
        # see the new NodeInfo while a replayed pod is half-migrated.
        with self._lock:
            info = self._nodes.get(node.name)
            if (
                info is not None
                and NodeInfo.fingerprint_of(node) == info.fingerprint()
            ):
                return False
            self._register_node(node.name, NodeInfo(node))
            self._non_tpu.discard(node.name)
            # nanolint: ignore[lock-discipline]: replays only this node,
            # freshly present in _nodes — the nested _node_info never GETs
            self._replay_tracked(node.name)
            self._migrate_reservations(node.name)
        self._ha_emit("node", raw=node.raw)
        self._republish()
        log.info("node %s rebuilt (new/resized/relabeled)", node.name)
        return info is not None

    def _migrate_reservations(self, node_name: str) -> None:
        """Re-apply parked strict-gang reservations onto a rebuilt
        NodeInfo (caller holds the dealer lock). A plan the resized node
        can no longer honor marks the reservation invalid — the parked
        bind then fails instead of committing chips another pod may hold."""
        current = self._nodes.get(node_name)
        for uid, res in self._reserved.items():
            if res.node_name != node_name or not res.valid:
                continue
            if current is None or res.info is current:
                continue
            try:
                current.allocate(res.plan)
                res.info = current
            except ValueError:
                self._invalidate_reservation(uid, res)
                log.warning(
                    "parked reservation for pod uid %s lost in %s rebuild",
                    uid, node_name,
                )

    def node_names(self) -> list[str]:
        with self._lock:
            return sorted(self._nodes)

    def tracks(self, uid: str) -> bool:
        """True when this dealer currently accounts pod ``uid`` (the
        assume-TTL sweeper uses this to decide whether expiring a stale
        annotation also needs a chip-accounting rollback)."""
        with self._lock:
            return uid in self._pods

    def tracked_pods(self) -> list[Pod]:
        """Snapshot of every pod the dealer currently accounts (bound by us
        or learned). The resync loop diffs this against the live pod list to
        release pods DELETED while the watch was down — the informer
        re-list delta the reference got from client-go
        (controller.go:89-123)."""
        with self._lock:
            return list(self._pods.values())

    # -- RCU snapshot publication ------------------------------------------
    def _republish(self, changed: tuple[str, ...] = ()) -> None:
        """Publish fresh immutable snapshots on the shards a commit
        touched — the incremental-delta half of the sharded design.

        ``changed`` names the nodes the commit touched; each maps to one
        shard, and ONLY those shards republish (with the probe narrowed
        to their own changed rows). Empty ``changed`` means a structural
        sweep: every shard whose membership epoch moved republishes
        structurally, every other shard is untouched — chip-state changes
        always arrive with their node named, so an unnamed sweep never
        needs to probe rows. Single-shard mode degenerates to exactly the
        pre-shard behavior: one shard, every commit lands on it."""
        if not self._publish_enabled:
            return
        if self._shard_fn is None:
            self._republish_shard(self._default_shard, changed)
            return
        if changed:
            by_shard: dict[str, list[str]] = {}
            for n in changed:
                key = self._shard_of.get(n)
                if key is None:
                    continue  # just evicted/unknown: the sweep covers it
                by_shard.setdefault(key, []).append(n)
            for key, names in by_shard.items():
                self._republish_shard(self._shards[key], tuple(names))
        # unconditional epoch sweep (O(#shards) int compares): any shard
        # whose membership epoch moved — a relabel's OLD family, an
        # eviction, a registration — republishes on the very next commit,
        # regardless of which call path delivered it. Steady-state binds
        # pay only the compares.
        for shard in list(self._shards.values()):
            if shard.epoch != shard._pub_epoch:
                self._republish_shard(shard, ())

    def _shard_epoch_locked(self, shard: _Shard) -> int:
        """The structural epoch a publish of this shard must catch up to
        (caller holds ``self._lock``). Single-shard mode uses the global
        node epoch (tombstone changes included, exactly as before);
        sharded mode uses the shard's own membership epoch so one pool's
        churn never forces siblings to drop their views."""
        if self._shard_fn is None:
            return self._nodes_epoch
        return shard.epoch

    def _republish_shard(self, shard: _Shard,
                         changed: tuple[str, ...] = ()) -> None:
        """Publish a commit's delta on ONE shard.

        Direct mode (coalescing off — the default at pipeline depth 1)
        swaps the snapshot synchronously under the shard's publish lock:
        the exact pre-pipeline behavior. Coalescing mode
        (docs/bind-pipeline.md) is the commit BATCHER: the commit only
        ENQUEUES its delta into the shard's pending set (set ops under a
        tiny lock — the write path never does view-advance work, never
        waits on the publish lock) and the next READER of the shard's
        snapshot drains everything pending into ONE swap. All
        republishes landing between two reads of a shard — every bind
        of a storm burst, a whole drained metric sweep — fold into a
        single snapshot swap with a single copy-on-write advance per
        cached view, instead of one full advance per commit."""
        if not self._coalesce:
            with shard._publish_lock:
                self._publish_shard_locked(shard, changed)
            return
        with shard._pending_lock:
            if changed:
                shard._pending.update(changed)
            else:
                shard._pending_all = True
        shard.perf.publish_coalesced += 1

    def _drain_shard(self, shard: _Shard) -> None:
        """Reader-side coalescing drain: fold every enqueued delta on
        this shard into (at most) one snapshot swap, via a non-blocking
        publish-leader election.

        The try-acquire keeps the RCU promise that readers never block
        on publisher work: one reader becomes the leader and performs
        the swap; concurrent readers proceed against the current
        snapshot. Staleness is therefore bounded by ONE in-flight swap —
        a reader racing the leader can score a commit behind, the same
        sub-millisecond window a pre-pipeline reader racing a
        publish-in-progress already had (and kube-scheduler's bind
        re-checks feasibility against live chip state either way). A
        leader re-checks the pending set after releasing, so a delta
        enqueued while it held the lock can never park unpublished while
        readers keep arriving. Generation numbers stay strictly
        monotonic: swaps still serialize on the publish lock."""
        while shard._pending or shard._pending_all:
            if not shard._publish_lock.acquire(blocking=False):
                # a concurrent leader is mid-swap; read the current
                # snapshot rather than wait (its post-release re-check
                # keeps the delta from parking)
                return
            try:
                with shard._pending_lock:
                    # sorted(): a deterministic drain order (sets iterate
                    # in hash order, which the sim-determinism discipline
                    # bans on code the sim drives)
                    drained = tuple(sorted(shard._pending))
                    probe_all = shard._pending_all
                    shard._pending.clear()
                    shard._pending_all = False
                self._publish_shard_locked(
                    shard, () if probe_all else drained
                )
            finally:
                shard._publish_lock.release()
            # loop: a delta enqueued while we held the lock (its commit's
            # try-acquire failed against us) must not park unpublished

    def _publish_shard_locked(self, shard: _Shard,
                              changed: tuple[str, ...] = ()) -> None:
        """Swap in a fresh immutable snapshot on ONE shard (caller holds
        ``shard._publish_lock``).

        Chip-state-only publishes reuse the node mapping and ADVANCE every
        cached candidate-list view (copy-on-write: only rows whose
        NodeInfo.version moved are re-read — the common bind touches one).
        ``changed`` names the nodes the commit touched, narrowing each
        view's version probe to those rows (a full 256-row probe per bind
        costs ~15% of the cycle); empty means "unknown, probe everything".
        A publish whose probe finds nothing (e.g. the commit half of a
        bind whose reserve half already published) keeps the old views —
        and when NO view moved, the whole publish is skipped: readers
        cannot observe a difference, and the memo/state_rev stay valid.
        Structural publishes (node added/removed/rebuilt, tombstone
        changes) copy the mapping and start with empty views; the next
        read warms them. Publishers serialize on the shard's
        _publish_lock and hold self._lock only for the epoch/mapping
        capture — never while advancing views, so a slow advance cannot
        stall verb commits (and never while holding another shard's
        publish lock, so no cross-shard lock order exists)."""
        # bumped BEFORE the views capture: a reader whose lazy build
        # this publish raced past (its entry not yet inserted) sees
        # the bump and re-advances its rows before trusting them
        shard._commit_seq += 1
        old = shard._published
        with self._lock:
            epoch = self._shard_epoch_locked(shard)
            structural = epoch != shard._pub_epoch
            if structural:
                if self._shard_fn is None:
                    nodes = dict(self._nodes)
                else:
                    nodes = dict(self._members.get(shard.key, {}))
                non_tpu = frozenset(self._non_tpu)
            else:
                nodes, non_tpu = old.nodes, old.non_tpu
        views: dict[tuple, tuple | None] = {}
        moved = False
        if not structural:
            for key, entry in list(old.views.items()):
                if entry is None:
                    views[key] = None
                    continue
                scorer, names_key, non_tpu_names, index_of = entry
                if changed:
                    rows = [
                        i for n in changed
                        if (i := index_of.get(n)) is not None
                    ]
                    adv = scorer.advanced(rows) if rows else scorer
                else:
                    adv = scorer.advanced()
                if adv is scorer:
                    views[key] = entry
                else:
                    moved = True
                    views[key] = (adv, names_key, non_tpu_names,
                                  index_of)
            if not moved:
                return  # byte-identical views: nothing to publish
        snap = _Snapshot(old.gen + 1, nodes, non_tpu)
        snap.views = views
        shard._pub_epoch = epoch
        shard.perf.snapshot_publishes += 1
        if structural:
            shard.perf.snapshot_structural += 1
        shard._published = snap

    def _maybe_republish(self) -> None:
        """Catch-up publish for read verbs that warmed cold nodes (their
        apiserver GETs materialize NodeInfos without a writer commit)."""
        if self._shard_fn is None:
            if self._nodes_epoch != self._default_shard._pub_epoch:
                self._republish()
            return
        for shard in list(self._shards.values()):
            if shard.epoch != shard._pub_epoch:
                self._republish_shard(shard, ())

    @property
    def _published(self) -> _Snapshot:
        """Back-compat single-shard accessor (tests, ad-hoc
        introspection): the default shard's published snapshot. Sharded
        dealers have one snapshot PER shard — use :meth:`shard_status`
        or :meth:`debug_snapshot`."""
        shard = self._default_shard
        if shard._pending or shard._pending_all:
            self._drain_shard(shard)  # commit-pipeline read barrier
        return shard._published

    def _snapshot_gen(self) -> int:
        """Published generation for trace lines: the single shard's gen,
        or (sharded) the sum across shards — monotonic either way."""
        if self._shard_fn is None:
            return self._default_shard._published.gen
        total = 0
        # list() snapshot: _register_node can insert a brand-new shard
        # concurrently, and iterating the live dict would raise
        for shard in list(self._shards.values()):
            total += shard._published.gen
        return total

    def _published_node(self, name: str):
        """The published NodeInfo for ``name`` from its owning shard's
        snapshot (lock-free), or None when unpublished/unknown."""
        if self._shard_fn is None:
            shard = self._default_shard
            if shard._pending or shard._pending_all:
                self._drain_shard(shard)  # commit-pipeline read barrier
            return shard._published.nodes.get(name)
        key = self._shard_of.get(name)
        shard = self._shards.get(key) if key is not None else None
        if shard is None:
            return None
        if shard._pending or shard._pending_all:
            self._drain_shard(shard)  # commit-pipeline read barrier
        return shard._published.nodes.get(name)

    def _view_for(self, shard: _Shard, key: tuple):
        """The shard's published frozen view for this candidate tuple;
        builds (and caches on the snapshot) lazily on first sight. No
        dealer lock anywhere on the hit path.

        The miss path must defend against a commit racing the build: the
        rows are read from live NodeInfos, and a publish that ran
        between that read and the dict insert may have SKIPPED publishing
        (our entry wasn't cached yet, so no view moved) — caching the
        pre-commit rows then would be stale until some later commit
        touched the same node. The shard's ``_commit_seq`` (bumped by
        every publish attempt) detects the race; a detected race
        re-probes every row, which by writer program order (chip mutation
        -> republish -> seq bump) incorporates any commit the first read
        missed."""
        if shard._pending or shard._pending_all:
            # commit-pipeline read barrier (docs/bind-pipeline.md): drain
            # any coalesced-but-unswapped delta before consuming the
            # snapshot. A read either swaps the delta in itself or races
            # a leader already mid-swap — staleness is bounded by that
            # ONE in-flight swap (see _drain_shard). Two plain attribute
            # loads when idle; both always empty with coalescing off.
            self._drain_shard(shard)
        snap = shard._published
        entry = snap.views.get(key, _VIEW_MISSING)
        if entry is not _VIEW_MISSING:
            return entry
        entry = None
        built = False
        for _ in range(4):  # bounded: each retry needs a fresh racing commit
            seq = shard._commit_seq
            if not built:
                entry = self._build_view(snap, key, shard.perf)
                built = True
            else:
                scorer, names_key, non_tpu, index_of = entry
                adv = scorer.advanced()
                if adv is not scorer:
                    entry = (adv, names_key, non_tpu, index_of)
            while len(snap.views) >= 8:  # candidate pools are few & stable
                try:
                    snap.views.pop(next(iter(snap.views)), None)
                except (StopIteration, RuntimeError):
                    break  # racing evictor emptied/resized it first
            snap.views[key] = entry
            if entry is None or shard._commit_seq == seq:
                break
        if built and entry is not None:
            # view warm hint (docs/ha.md): the standby pre-builds the
            # same frozen view + renderer so its first post-promotion
            # Filter costs zero view/renderer builds. Builds are rare
            # (structural changes only), so this never rides a steady
            # request.
            self._ha_emit("view", names=list(key))
        return entry

    def _build_view(self, snap: _Snapshot, key: tuple, perf: PerfCounters):
        pairs = [(n, snap.nodes.get(n)) for n in key]
        non_tpu = {
            n for n, info in pairs if info is None and n in snap.non_tpu
        }
        if any(info is None and n not in non_tpu for n, info in pairs):
            return None  # cold candidates: take the warming per-node path
        known = [(n, info) for n, info in pairs if info is not None]
        infos = [info for _, info in known]
        scorer = BatchScorer.build(infos, perf=perf,
                                   model=self._native_model)
        if scorer is None:
            return None
        scorer.freeze()
        perf.view_builds += 1
        names = tuple(n for n, _ in known)
        # name -> row index: lets a publish advance this view by probing
        # only the rows its commit touched
        return scorer, names, non_tpu, {n: i for i, n in enumerate(names)}

    # -- rater hot swap ----------------------------------------------------
    def install_rater(self, rater) -> None:
        """Hot-swap the scoring policy (verified policy programs,
        docs/policy-programs.md: the ``PolicyWatcher``'s ``program:``
        reload lands here AFTER verification — a failing candidate never
        reaches this method, the old rater keeps serving).

        Re-resolves the integration hooks ``__init__`` captured
        (``_batch_hook``/``_native_model``/``_hook_active``/observe/
        forget) and invalidates every score artifact computed under the
        old rater: per-node plan caches (their scores embed the old
        policy) and the published frozen views (built with the old
        rater's native-model binding). Chip accounting, gang state, and
        the HA stream are untouched — scores are derived state, and the
        batch scorer's native memo already keys on ``prefer_used`` so a
        preference flip cannot serve a stale arena."""
        # resolve the native binding OUTSIDE the hot lock: native.available()
        # is a ctypes probe, and swaps are rare control-plane events
        nm_fn = getattr(rater, "native_model", None)
        native_model = (
            nm_fn()
            if nm_fn is not None
            and os.environ.get("NANOTPU_NATIVE_MODEL", "1") != "0"
            and native.available()
            else None
        )
        with self._lock:
            self.rater = rater
            self._batch_hook = getattr(rater, "batch_score_rows", None)
            self._native_model = native_model
            self._hook_active = (
                self._batch_hook is not None and self._native_model is None
            )
            self._rater_observe = getattr(rater, "observe_usage", None)
            self._rater_forget = getattr(rater, "forget_node", None)
            nodes = list(self._nodes.values())
            shards = list(self._shards.values())
        for info in nodes:
            info.invalidate_plans()
        for shard in shards:
            # drop the frozen views wholesale: they rebuild on next use
            # (a structural event, same cost class as a node join)
            shard._published.views.clear()

    # -- batched scoring fast path -----------------------------------------
    #: rater name -> prefer_used flag for the native batch engine; raters
    #: outside this map take the batch path only when they provide a
    #: Python-side ``batch_score_rows`` hook (throughput), else the
    #: per-node path (random, sample).
    _BATCH_POLICIES = {types.POLICY_BINPACK: True, types.POLICY_SPREAD: False}

    def _batch_prefer(self):
        """prefer_used flag for the batch engine, or None -> per-node
        path. Hook raters run the native engine for FEASIBILITY only
        (feasibility is rater-independent: a placement exists or it does
        not), with prefer=True; their scores come from the hook."""
        prefer = self._BATCH_POLICIES.get(self.rater.name)
        if prefer is None and self._batch_hook is not None:
            return True
        return prefer

    def _batch_plan(self, node_names: list[str]):
        """Single-shard fast plan: (scorer, ordered known names, non-TPU
        names, prefer_used) when every candidate is materialized in the
        published snapshot and the pool is uniform; None -> per-node path
        (cold candidates need apiserver GETs, or mixed topologies).
        Lock-free. Sharded dealers use :meth:`_shard_plan` instead."""
        if self._default_shard is None:
            return None
        prefer = self._batch_prefer()
        if prefer is None:
            return None
        entry = self._view_for(self._default_shard, tuple(node_names))
        if entry is None:
            return None
        scorer, names_key, non_tpu, _index_of = entry
        return scorer, names_key, non_tpu, prefer

    # -- sharded scoring plan ----------------------------------------------
    def _compute_partition(self, names_key: tuple):
        """``(parts, non_tpu names, contiguous)`` for a candidate tuple,
        or None when an unknown (cold) candidate forces the warming
        per-node path. ``parts`` is ``[(shard key, names, positions)]``
        in first-appearance (== ascending position) order; ``contiguous``
        is True when every shard's candidates form one unbroken run of
        the request order — the precondition for bytewise payload
        splicing."""
        # lock-free reads of the live maps: individual dict/set probes
        # are GIL-atomic, and a concurrent register/evict at worst yields
        # a partition that resolves to the warming path or a stale view —
        # the same staleness window every read path already tolerates
        # (the epoch key on the cache retires it at the next commit)
        shard_of = self._shard_of
        tomb = self._non_tpu
        parts: dict[str, tuple[list, list]] = {}
        non_tpu: list[str] = []
        for i, n in enumerate(names_key):
            key = shard_of.get(n)
            if key is None:
                if n in tomb:
                    non_tpu.append(n)
                    continue
                return None
            names, positions = parts.setdefault(key, ([], []))
            names.append(n)
            positions.append(i)
        contiguous = not non_tpu and all(
            pos[-1] - pos[0] + 1 == len(pos)
            for _names, pos in parts.values()
        )
        return (
            [(k, tuple(v[0]), tuple(v[1])) for k, v in parts.items()],
            non_tpu,
            contiguous,
        )

    def _shard_plan(self, node_names: list[str]):
        """Sharded fast plan: partition the candidate list by shard and
        resolve each part's frozen view. Returns ``(resolved, non_tpu,
        contiguous, prefer)`` with ``resolved = [(shard, view entry,
        names, positions)]``, or None -> per-node path. Lock-free on the
        partition-cache hit path."""
        prefer = self._batch_prefer()
        if prefer is None:
            return None
        key = tuple(node_names)
        cached = self._part_cache.get(key)
        if cached is None or cached[0] != self._nodes_epoch:
            cached = (self._nodes_epoch, self._compute_partition(key))
            # a partition is cheap to hold (names + positions), so the
            # bound is looser than the 8-entry view cache: upstream
            # predicate prefiltering can cycle many candidate subsets
            while len(self._part_cache) >= 32:
                try:
                    self._part_cache.pop(next(iter(self._part_cache)), None)
                except (StopIteration, RuntimeError):
                    break
            self._part_cache[key] = cached
        partition = cached[1]
        if partition is None:
            return None
        parts, non_tpu, contiguous = partition
        resolved = []
        for shard_key, names, positions in parts:
            shard = self._shards.get(shard_key)
            if shard is None:
                return None
            entry = self._view_for(shard, names)
            if entry is None:
                return None
            _scorer, names_key, view_non_tpu, _index = entry
            if view_non_tpu or len(names_key) != len(names):
                return None  # membership drifted under the partition
            resolved.append((shard, entry, names, positions))
        return resolved, non_tpu, contiguous, prefer

    def _run_shards(self, resolved, demand, prefer: bool, member_slices,
                    score_hook=None):
        """Score every shard part. More than one part fans out on the
        thread pool: each part is one native ``score_batch`` call that
        releases the GIL, so shards genuinely score in parallel. Results
        come back in part order (pool.map preserves it) — deterministic
        regardless of completion order. ``score_hook`` threads the
        Python-side rater hook into each part's run (throughput rater)."""
        def run_one(item):
            return item[1][0].run(demand, prefer, member_slices,
                                  score_hook=score_hook)

        if len(resolved) == 1:
            return [run_one(resolved[0])]
        return list(self._pool.map(run_one, resolved))

    def _hook_gang_bonus(self, scorer, scores, gang_scorer):
        """Fold the gang-affinity bonus into hook-path scores exactly as
        the per-node path does (native-path scores arrive with it folded
        in already): ``min(SCORE_MAX, score + bonus)`` per candidate."""
        return [
            min(
                types.SCORE_MAX,
                s + gang_scorer.bonus(info.slice_name, info.slice_coords),
            )
            for s, info in zip(scores, scorer.infos)
        ]

    def _sharded_assume(self, node_names: list[str], pod: Pod, demand,
                        trace=None):
        """Sharded Filter: parallel per-shard native scoring merged back
        into candidate order. Returns (ok, failed) — the same lists, in
        the same order, the single-shard batch path builds (the parity
        pin in tests/test_shard.py holds the merge to byte equality) —
        or None for the warming per-node path."""
        plan = self._shard_plan(node_names)
        if plan is None:
            return None
        resolved, non_tpu, _contiguous, prefer = plan
        if trace is not None:
            trace.event(
                "shard:fanout",
                f"shards={len(resolved)} "
                f"rows={sum(len(item[2]) for item in resolved)}",
            )
        runs = self._run_shards(
            resolved, demand, prefer, self._gang_member_slices(pod) or None
        )
        feas: list = [None] * len(node_names)
        for item, (feasible, _scores) in zip(resolved, runs):
            for pos, f in zip(item[3], feasible):
                feas[pos] = f
        ok = [n for n, f in zip(node_names, feas) if f]
        failed = {
            n: types.REASON_NO_CAPACITY
            for n, f in zip(node_names, feas)
            if f is False
        }
        failed.update({n: "not a TPU node" for n in non_tpu})
        return ok, failed

    def _sharded_score(self, node_names: list[str], pod: Pod, demand,
                       member_slices, trace=None):
        """Sharded Prioritize: parallel per-shard native scoring merged
        back into candidate order (non-TPU candidates score SCORE_MIN,
        exactly as the single-shard path does). None -> per-node path."""
        plan = self._shard_plan(node_names)
        if plan is None:
            return None
        resolved, _non_tpu, _contiguous, prefer = plan
        if trace is not None:
            trace.event(
                "shard:fanout",
                f"shards={len(resolved)} "
                f"rows={sum(len(item[2]) for item in resolved)}",
            )
        runs = self._run_shards(
            resolved, demand, prefer, member_slices or None,
            score_hook=self._batch_hook if self._hook_active else None,
        )
        # native-path scores (default raters AND native-model raters)
        # arrive with the gang bonus folded in; only hook scores need
        # the Python-side fold
        gs = (
            GangScorer(member_slices)
            if self._hook_active and member_slices else None
        )
        out = [types.SCORE_MIN] * len(node_names)
        for item, (_feasible, scores) in zip(resolved, runs):
            if gs is not None:
                scores = self._hook_gang_bonus(item[1][0], scores, gs)
            for pos, score in zip(item[3], scores):
                out[pos] = score
        return list(zip(node_names, out))

    def top_candidates(self, node_names: list[str], pod: Pod,
                       k: int | None = 1) -> list[tuple[str, int]]:
        """The best ``k`` feasible ``(host, score)`` pairs for this pod,
        merged across shards by the single deterministic top-k reduce
        (:func:`nanotpu.dealer.shard.merge_top_k`: score descending, then
        name ascending) — shard count cannot change the answer. The
        unsharded dealer ranks the same way, so this is THE tie-break
        contract consumers should rely on (the bench's 4096-host row
        cross-checks its HTTP-derived pick against it)."""
        demand = self._demand_of(pod)
        if not demand.is_valid():
            return []
        if self.recovery is not None:
            blocked = self.recovery.blocks(pod, node_names)
            if blocked:
                node_names = [
                    n for n in node_names if n not in blocked
                ]
        if self._shard_fn is not None:
            plan = self._shard_plan(node_names)
            if plan is not None:
                resolved, _non_tpu, _contiguous, prefer = plan
                member = self._gang_member_slices(pod) or None
                runs = self._run_shards(
                    resolved, demand, prefer, member,
                    score_hook=(
                        self._batch_hook if self._hook_active else None
                    ),
                )
                gs = (
                    GangScorer(member)
                    if self._hook_active and member else None
                )
                lists = []
                for item, (feasible, scores) in zip(resolved, runs):
                    if gs is not None:
                        scores = self._hook_gang_bonus(
                            item[1][0], scores, gs
                        )
                    lists.append([
                        (n, s)
                        for n, f, s in zip(item[2], feasible, scores)
                        if f
                    ])
                return merge_top_k(lists, k)
        ok, _failed = self.assume(node_names, pod)
        feasible_set = set(ok)
        scored = self.score(node_names, pod)
        return merge_top_k(
            [[(n, s) for n, s in scored if n in feasible_set]], k
        )

    def pack_pods(self, pods: list[Pod], node_names: list[str],
                  lookahead: int = 4):
        """Joint batch pack (ABI 8, docs/batch-admission.md): place every
        pod of ``pods`` — in the GIVEN order, which is the solve order —
        against the published frozen views in one fused native crossing
        per shard, scratch occupancy updated in C between picks so pod
        ``j`` sees pod ``i``'s placement.

        Returns a per-pod list of ``(node name, score)`` picks (``None``
        for pods the joint solve cannot place — invalid demands, or no
        feasible candidate), or ``None`` when the batch path is
        unavailable as a whole (cold/unknown candidates, a hook rater
        the native engine cannot evaluate, a recovery plane holding gang
        holes the pack cannot see, native off) — the caller then falls
        back to the pod-at-a-time path untouched.

        Sharded dealers pack every shard in parallel (the native call
        releases the GIL) and reduce per demand in solve order: the
        winning proposal is chosen score-descending then node-name-
        ascending — :func:`~nanotpu.dealer.shard.merge_top_k`'s total
        order, so a shard split can never change a SINGLE demand's pick
        (pinned by tests/test_admit.py). Per-shard scratch states are
        independent, which makes cross-shard packing CONSERVATIVE: a
        shard prices every demand as if all K landed on it, so a
        diverted demand only ever leaves the chosen shard with more
        capacity than the solve assumed — but a batch whose aggregate
        demand exceeds one shard's free capacity would strand the tail
        of the solve order (every shard virtually fills up and reports
        it infeasible). Bounded refinement rounds repair that: each
        shard re-packs (its reduce winners + the still-unplaced tail)
        so leftovers are priced against the true residual, winners keep
        their earlier picks, and the loop stops when a round places
        nothing new or after ``_PACK_REFINE_ROUNDS``. Rounds are a pure
        function of (batch, fleet state), so the determinism contract
        holds; placements stay feasible, never oversubscribed (and the
        commit path re-plans under the node lock regardless)."""
        if self._hook_active or self.recovery is not None:
            # hook raters: the native pack cannot evaluate a Python row
            # hook. Recovery plane: gang holes filter candidates per pod
            # (recovery.blocks), which a joint solve over one shared row
            # set cannot express — both fall back whole (docs/batch-
            # admission.md "Fallback semantics").
            return None
        out: list[tuple[str, int] | None] = [None] * len(pods)
        demands = []
        valid_idx = []
        for i, pod in enumerate(pods):
            d = self._demand_of(pod)
            if d.is_valid() and d.total > 0:
                valid_idx.append(i)
                demands.append(d)
        if not valid_idx:
            return out
        if self._shard_fn is None:
            batch = self._batch_plan(node_names)
            if batch is None:
                return None
            scorer, names_key, non_tpu, prefer = batch
            if non_tpu or len(names_key) != len(node_names):
                return None
            try:
                results = scorer.pack(demands, prefer, lookahead)
            except native.NativeUnavailable:
                return None
            for i, (row, score, _assign) in zip(valid_idx, results):
                if row >= 0:
                    out[i] = (names_key[row], score)
            return out
        plan = self._shard_plan(node_names)
        if plan is None:
            return None
        resolved, non_tpu, _contiguous, prefer = plan
        if non_tpu:
            return None

        def pack_one(item):
            return item[1][0].pack(demands, prefer, lookahead)

        try:
            if len(resolved) == 1:
                runs = [pack_one(resolved[0])]
            else:
                runs = list(self._pool.map(pack_one, resolved))
        except native.NativeUnavailable:
            return None
        k = len(valid_idx)
        positions: list[list[int]] = [list(range(k)) for _ in resolved]
        won_by: list[list[int]] = [[] for _ in resolved]
        shard_of_node = {
            name: s for s, item in enumerate(resolved) for name in item[2]
        }
        remaining = list(range(k))
        for round_no in range(1 + _PACK_REFINE_ROUNDS):
            if round_no:
                if len(resolved) == 1 or not remaining:
                    break
                # refinement: round 1's independent scratches charged
                # every shard with demands the reduce sent elsewhere, so
                # a batch bigger than one shard's free capacity strands
                # the tail of the solve order. Re-pack each shard with
                # (its winners + the leftovers) — leftovers now price
                # against the true residual; winners keep their picks.
                positions = [sorted(w + remaining) for w in won_by]

                def pack_sub(item_pos):
                    item, pos = item_pos
                    return item[1][0].pack(
                        [demands[j] for j in pos], prefer, lookahead
                    )

                try:
                    runs = list(self._pool.map(
                        pack_sub, list(zip(resolved, positions))
                    ))
                except native.NativeUnavailable:
                    break  # keep earlier picks; leftovers fall back
            pos_of = [
                {j: r for r, j in enumerate(pos)} for pos in positions
            ]
            placed: list[int] = []
            for j in remaining:
                proposals = []
                for s, (item, results) in enumerate(zip(resolved, runs)):
                    r = pos_of[s].get(j)
                    if r is None:
                        continue
                    row, score, _assign = results[r]
                    if row >= 0:
                        proposals.append((item[2][row], score))
                if not proposals:
                    continue
                if len(proposals) > 1:
                    # more than one shard bid for this demand: the
                    # reduce resolved a genuine contention (attribution
                    # for the bench + /debug/decisions' batch status)
                    self.perf.batch_contended += 1
                pick = merge_top_k([proposals], 1)[0]
                out[valid_idx[j]] = pick
                won_by[shard_of_node[pick[0]]].append(j)
                placed.append(j)
            if not placed:
                break
            placed_set = set(placed)
            remaining = [j for j in remaining if j not in placed_set]
        return out

    # -- fused verb fast paths ---------------------------------------------
    #
    # Filter/Prioritize at large fan-out: one native call scores every
    # candidate AND renders the full response JSON from pre-baked per-name
    # fragments (native/allocator.cc nanotpu_render_*). Only the uniform
    # all-known-candidates case qualifies; anything else returns None and
    # the verb takes the assume()/score() path. Result parity with that
    # path is pinned by tests/test_http_extender.py and the bench's
    # every-32nd-cycle cross-check.

    def _payload_plan(self, node_names: list[str], pod: Pod):
        demand = self._demand_of(pod)
        if not demand.is_valid():
            return None
        batch = self._batch_plan(node_names)
        if batch is None:
            return None
        scorer, names_key, non_tpu, prefer = batch
        if non_tpu or len(names_key) != len(node_names):
            return None  # non-pool candidates: the list path handles them
        if not scorer.ensure_renderer(names_key):
            return None
        return scorer, demand, prefer

    def _sharded_payload(self, node_names: list[str], pod: Pod,
                         mode: int) -> bytes | None:
        """Sharded fused path: parallel native ``nanotpu_score_render``
        calls — one per shard, each rendering its own slice of the
        response from its own frozen arena — then a bytewise splice in
        request order. Requires every candidate mapped to a shard and
        each shard's candidates contiguous in the request (the fleet
        factory and nodeCacheCapable candidate lists both satisfy this);
        anything else returns None and the verb takes the merged list
        path, which produces the same bytes through the render caches.
        ``mode`` 0 = ExtenderFilterResult, 1 = HostPriorityList."""
        demand = self._demand_of(pod)
        plan = self._shard_plan(node_names) if demand.is_valid() else None
        if plan is None:
            self.perf.fastpath_misses += 1
            return None
        resolved, non_tpu, contiguous, prefer = plan
        if non_tpu or not contiguous:
            self.perf.fastpath_misses += 1
            return None
        for _shard, entry, names, _pos in resolved:
            if not entry[0].ensure_renderer(names):
                self.perf.fastpath_misses += 1
                return None
        member = self._gang_member_slices(pod) or None

        def render_one(item):
            scorer = item[1][0]
            if mode == 0:
                return scorer.filter_payload(demand, prefer, member)
            return scorer.priorities_payload(demand, prefer, member)

        if len(resolved) == 1:
            payloads = [render_one(resolved[0])]
        else:
            payloads = list(self._pool.map(render_one, resolved))
        if any(p is None for p in payloads):
            self.perf.fastpath_misses += 1
            return None
        merged = (
            splice_filter_payloads(payloads) if mode == 0
            else splice_priorities_payloads(payloads)
        )
        if merged is None:
            self.perf.fastpath_misses += 1
            return None
        self.perf.fastpath_hits += 1
        return merged

    def filter_payload(self, node_names: list[str], pod: Pod) -> bytes | None:
        """ExtenderFilterResult JSON bytes, or None -> use assume()."""
        if self.recovery is not None:
            if self.recovery.blocks(pod, node_names):
                # hole-reserved candidates need per-name failed reasons
                # the pre-rendered fragments cannot express: list path
                # (holes are rare and transient; one None check when no
                # plane)
                self.perf.fastpath_misses += 1
                return None
            gang = podutil.gang_of(pod)
            if gang and gang[1] > 1:
                # gang Filters take the (render-cached) list path so a
                # zero-feasible verdict reaches the starvation trigger
                # (_note_starvation) — fused bytes bypass assume(), and
                # a fully-starved gang must not be invisible to the
                # recovery plane (docs/defrag.md)
                self.perf.fastpath_misses += 1
                return None
        if self._hook_active:
            # explicit fused-path refusal (docs/scoring.md): the native
            # renderer cannot evaluate a Python-side score hook, and a
            # half-fused answer would desync Filter from Prioritize. The
            # verb falls back to the render-cached list path — same wire
            # shape, zero view/renderer rebuilds. Counted as a DEDICATED
            # refusal, not a generic miss: "the rater opted out by
            # design" and "the fast path failed" must be different
            # numbers in the bench attribution. Native-model raters
            # (ABI 7) never land here — the fused path serves them.
            self.perf.hook_refusals += 1
            return None
        if self._shard_fn is not None:
            return self._sharded_payload(node_names, pod, 0)
        plan = self._payload_plan(node_names, pod)
        if plan is None:
            self.perf.fastpath_misses += 1
            return None
        scorer, demand, prefer = plan
        payload = scorer.filter_payload(
            demand, prefer, self._gang_member_slices(pod) or None
        )
        if payload is None:
            self.perf.fastpath_misses += 1
        else:
            self.perf.fastpath_hits += 1
        return payload

    def priorities_payload(
        self, node_names: list[str], pod: Pod
    ) -> bytes | None:
        """HostPriorityList JSON bytes, or None -> use score()."""
        if self.recovery is not None and self.recovery.blocks(
            pod, node_names
        ):
            self.perf.fastpath_misses += 1
            return None
        if self._hook_active:
            self.perf.hook_refusals += 1
            return None
        if self._shard_fn is not None:
            return self._sharded_payload(node_names, pod, 1)
        plan = self._payload_plan(node_names, pod)
        if plan is None:
            self.perf.fastpath_misses += 1
            return None
        scorer, demand, prefer = plan
        payload = scorer.priorities_payload(
            demand, prefer, self._gang_member_slices(pod) or None
        )
        if payload is None:
            self.perf.fastpath_misses += 1
        else:
            self.perf.fastpath_hits += 1
        return payload

    # -- Assume (Filter verb): dealer.go:89-136 ----------------------------
    def _demand_of(self, pod: Pod) -> Demand:
        cached = self._demand_uid.get(pod.uid)
        if cached is not None:
            return cached
        demand = Demand.from_pod(pod)
        if len(self._demand_uid) > 4096:  # long-running scheduler guard
            self._demand_uid.clear()
        self._demand_uid[pod.uid] = demand
        return demand

    def assume(
        self, node_names: list[str], pod: Pod,
        deadline: Deadline | None = None, trace=None,
    ) -> tuple[list[str], dict[str, str]]:
        """Partition candidate nodes into (schedulable, {node: reason}).

        ``deadline`` (threaded from the route layer's response budget)
        aborts an over-budget request at entry — before any per-node
        locks or apiserver warming GETs — with DeadlineExceeded; the
        route layer answers 503 and kube-scheduler's retry carries on.
        ``trace`` (same threading) records which read path served the
        request — snapshot batch vs warming per-node fan-out.

        With a capacity-recovery plane attached (``self.recovery``,
        docs/defrag.md), candidates earmarked for OTHER parked gangs'
        holes answer infeasible with a typed reason — production
        Filter enforces reservations the same way the sim's driver-side
        filtering does. One attribute load when no plane is attached."""
        if self.recovery is not None:
            blocked = self.recovery.blocks(pod, node_names)
            if blocked:
                kept = [n for n in node_names if n not in blocked]
                ok, failed = self._assume_inner(
                    kept, pod, deadline, trace
                )
                for n in node_names:
                    if n in blocked:
                        failed[n] = types.REASON_HOLE_RESERVED
            else:
                ok, failed = self._assume_inner(
                    node_names, pod, deadline, trace
                )
            self._note_starvation(pod, bool(ok))
            return ok, failed
        return self._assume_inner(node_names, pod, deadline, trace)

    #: starved-gang entries retire after this long without a refresh
    STARVED_TTL_S = 60.0
    STARVED_MAX = 512

    def _note_starvation(self, pod: Pod, feasible: bool) -> None:
        """Track gang pods whose Filter answered zero feasible nodes —
        the recovery plane's trigger for gangs that cannot reserve."""
        gang = podutil.gang_of(pod)
        if not gang or gang[1] <= 1:
            return
        with self._lock:
            if feasible:
                self._starved.pop(pod.uid, None)
                return
            if pod.uid in self._starved:
                return
            while len(self._starved) >= self.STARVED_MAX:
                self._starved.pop(next(iter(self._starved)))
            self._starved[pod.uid] = (pod, time.monotonic())

    def _assume_inner(
        self, node_names: list[str], pod: Pod,
        deadline: Deadline | None = None, trace=None,
    ) -> tuple[list[str], dict[str, str]]:
        deadline_check(deadline, "filter:start")
        if trace is not None:
            trace.event(
                "snapshot:read",
                f"gen={self._snapshot_gen()} candidates={len(node_names)}",
            )
        demand = self._demand_of(pod)
        if not demand.is_valid():
            return [], {
                n: f"invalid demand {demand.percents} (multi-chip requests "
                f"must be whole chips)"
                for n in node_names
            }

        if self._shard_fn is not None:
            merged = self._sharded_assume(node_names, pod, demand, trace)
            if merged is not None:
                return merged
        else:
            batch = self._batch_plan(node_names)
            if batch is not None:
                scorer, names_key, non_tpu, prefer = batch
                if trace is not None:
                    trace.event(
                        "native:batch-score", f"rows={len(names_key)}"
                    )
                # pass the gang context even though Filter ignores scores:
                # the native result is memoized, so the immediately
                # following Prioritize (same pod, same state) reuses this
                # exact call
                feasible, _ = scorer.run(
                    demand, prefer, self._gang_member_slices(pod) or None
                )
                ok = [n for n, f in zip(names_key, feasible) if f]
                failed = {
                    n: types.REASON_NO_CAPACITY
                    for n, f in zip(names_key, feasible)
                    if not f
                }
                failed.update({n: "not a TPU node" for n in non_tpu})
                return ok, failed

        def try_node(name: str) -> tuple[str, str | None]:
            info = self._node_info(name)
            if info is None:
                return name, "not a TPU node"
            plan = info.assume(demand, self.rater)
            if plan is None:
                return name, types.REASON_NO_CAPACITY
            return name, None

        # Pool only when several candidates are UNKNOWN: their _node_info
        # does a blocking apiserver GET each, which must overlap. Known-node
        # checks are GIL-bound microseconds where executor dispatch only
        # adds overhead — at any fan-out. (The reference hardcoded a
        # 4-goroutine pool for ANY fan-out, dealer.go:113-134.)
        with self._lock:
            cold = sum(
                1
                for n in node_names
                if n not in self._nodes and n not in self._non_tpu
            )
        # cold candidates mean blocking apiserver GETs ahead; re-probe the
        # budget so a request that already burned it parsing/queueing does
        # not start a fan-out nobody will read
        deadline_check(deadline, "filter:warm")
        if trace is not None:
            trace.event("filter:per-node", f"cold={cold}")
        if cold <= ASSUME_COLD_POOL_THRESHOLD:
            results = [try_node(n) for n in node_names]
        else:
            results = list(self._pool.map(try_node, node_names))
        ok = [n for n, err in results if err is None]
        failed = {n: err for n, err in results if err is not None}
        # cold candidates may have materialized NodeInfos: publish them so
        # the next cycle takes the snapshot path
        self._maybe_republish()
        return ok, failed

    def _gang_member_slices(self, pod: Pod) -> list[tuple[str, str]]:
        """(slice name, coords) of nodes hosting the pod's bound gang
        members; empty for non-gang pods. Memoized on the gang tracker's
        revision: Filter and Prioritize of one cycle (and every sibling pod
        until the next bind) share the lookup."""
        gang = podutil.gang_of(pod)
        if not gang:
            return []
        key = f"{pod.namespace}/{gang[0]}"
        # the memo must also see node-set changes: a member node deleted or
        # resized/relabeled (remove_node/refresh_node) changes the slice
        # geometry this caches without touching gang membership
        rev = (self.gangs.rev, self._nodes_epoch)
        cached = self._gms_cache
        if cached is not None and cached[0] == key and cached[1] == rev:
            return cached[2]
        member_slices: list[tuple[str, str]] = []
        for node in self.gangs.bound_nodes(key):
            # published snapshot first (per-shard lookup in sharded mode —
            # gang members CAN span shards): the memo-miss path then
            # usually takes no locks either (slice geometry is structural,
            # so the snapshot copy is exactly as fresh as the epoch in
            # `rev`)
            member = self._published_node(node) or self._node_info(node)
            if member is not None:
                member_slices.append((member.slice_name, member.slice_coords))
        self._gms_cache = (key, rev, member_slices)
        return member_slices

    # -- Score (Prioritize verb): dealer.go:138-153 ------------------------
    def score(self, node_names: list[str], pod: Pod,
              deadline: Deadline | None = None,
              trace=None) -> list[tuple[str, int]]:
        if self.recovery is not None:
            blocked = self.recovery.blocks(pod, node_names)
            if blocked:
                # hole-reserved candidates score SCORE_MIN in candidate
                # order — Prioritize must answer every candidate, and
                # Filter already marked these infeasible
                kept = [n for n in node_names if n not in blocked]
                scored = dict(
                    self._score_inner(kept, pod, deadline, trace)
                )
                return [
                    (
                        n,
                        types.SCORE_MIN if n in blocked
                        else scored.get(n, types.SCORE_MIN),
                    )
                    for n in node_names
                ]
        return self._score_inner(node_names, pod, deadline, trace)

    def _score_inner(self, node_names: list[str], pod: Pod,
                     deadline: Deadline | None = None,
                     trace=None) -> list[tuple[str, int]]:
        deadline_check(deadline, "priorities:start")
        if trace is not None:
            trace.event(
                "snapshot:read",
                f"gen={self._snapshot_gen()} candidates={len(node_names)}",
            )
        demand = self._demand_of(pod)
        if not demand.is_valid():
            return [(n, types.SCORE_MIN) for n in node_names]
        member_slices = self._gang_member_slices(pod)

        if self._shard_fn is not None:
            merged = self._sharded_score(
                node_names, pod, demand, member_slices, trace
            )
            if merged is not None:
                return merged
        batch = None if self._shard_fn is not None else \
            self._batch_plan(node_names)
        if batch is not None:
            bscorer, names_key, _non_tpu, prefer = batch
            if trace is not None:
                trace.event("native:batch-score", f"rows={len(names_key)}")
            _, scores = bscorer.run(
                demand, prefer, member_slices or None,
                score_hook=self._batch_hook if self._hook_active else None,
            )
            if self._hook_active and member_slices:
                scores = self._hook_gang_bonus(
                    bscorer, scores, GangScorer(member_slices)
                )
            if len(names_key) == len(node_names) and list(names_key) == node_names:
                # all candidates are known TPU nodes (the common case):
                # scores are already in candidate order
                return list(zip(node_names, scores))
            by_name = dict(zip(names_key, scores))
            return [
                (n, by_name.get(n, types.SCORE_MIN)) for n in node_names
            ]

        scorer: GangScorer | None = None
        if member_slices:
            # O(members) once; each candidate's bonus is then O(1)
            scorer = GangScorer(member_slices)
        out = []
        for name in node_names:
            info = self._node_info(name)
            if info is None:
                out.append((name, types.SCORE_MIN))
                continue
            score = info.score(demand, self.rater)
            if scorer is not None:
                bonus = scorer.bonus(info.slice_name, info.slice_coords)
                score = min(types.SCORE_MAX, score + bonus)
            out.append((name, score))
        self._maybe_republish()  # the loop may have warmed cold nodes
        return out

    def score_terms(self, node_names: list[str],
                    pod: Pod) -> dict[str, dict[str, int]]:
        """Per-candidate per-TERM score breakdown for the decision
        ledger (docs/scoring.md): {node: {base, contention,
        fragmentation[, gang], total}}. Only raters that expose
        ``rate_terms`` (throughput) produce breakdowns; everything else
        returns {} so the audit path costs one getattr. Called on
        SAMPLED requests only (the route layer's trace gate), so the
        second scoring pass never lands on the untraced hot path."""
        rate_terms = getattr(self.rater, "rate_terms", None)
        if rate_terms is None:
            return {}
        demand = self._demand_of(pod)
        if not demand.is_valid():
            return {}
        member_slices = self._gang_member_slices(pod)
        gs = GangScorer(member_slices) if member_slices else None
        out: dict[str, dict[str, int]] = {}
        for name in node_names:
            info = self._published_node(name)
            if info is None:
                with self._lock:
                    info = self._nodes.get(name)
            if info is None:
                continue
            with info.lock:
                terms = dict(rate_terms(info.chips, demand))
                # the audit contract is total == WIRE score, and the
                # wire scores an infeasible candidate SCORE_MIN (hook
                # path and per-node path alike). The assume() here is
                # the plan-cache hit the just-run scoring pass warmed —
                # not a second packing.
                if info.assume(demand, self.rater) is None:
                    terms["infeasible"] = 1
                    terms["total"] = types.SCORE_MIN
            if gs is not None:
                # the wire adds the gang bonus unconditionally (even on
                # SCORE_MIN), so the breakdown must too
                bonus = gs.bonus(info.slice_name, info.slice_coords)
                if bonus:
                    terms["gang"] = bonus
                    terms["total"] = min(
                        types.SCORE_MAX, terms["total"] + bonus
                    )
            out[name] = terms
        return out

    # -- Bind verb: dealer.go:155-203 --------------------------------------
    def bind(self, node_name: str, pod: Pod,
             deadline: Deadline | None = None, trace=None) -> Pod:
        """Apply the plan, write annotations (optimistic retry), post the
        binding. Raises BindError with accounting rolled back on failure.
        Emits a K8s Event either way (TPUAssigned / FailedBinding).

        The deadline is only probed HERE, before any reservation exists:
        once chips are reserved the bind runs to completion regardless —
        committing is idempotent-retry-safe (the _bind_outer uid guard),
        abandoning a half-written annotation is not. ``trace`` rides the
        same threading and records reservation / commit / gang-park
        events."""
        deadline_check(deadline, "bind:start")
        #: set by _reserve right after it applies+publishes the chip
        #: reservation: (NodeInfo, version at reserve time)
        reserved_state: list = []
        try:
            return self._bind_outer(node_name, pod, trace, reserved_state)
        finally:
            # one publish covers commit AND rollback: either way the chip
            # state that read verbs consume may have moved — and only on
            # this node. But a CLEAN commit moves nothing (the API writes
            # touch annotations, not chips), so when the node's version
            # still matches what _reserve published — and the NodeInfo is
            # still the registered instance (a mid-commit rebuild replays
            # onto a fresh one, which must publish) — the reserve-half
            # publish already covers everything and the second republish
            # is skipped outright instead of probed-and-dropped.
            # Rollbacks bump the version (unbind), so they always publish.
            # Both reads are GIL-atomic; a concurrent bind moving the
            # version only ever forces an extra (cheap, probe-only)
            # publish, never a skipped one.
            entry = reserved_state[-1] if reserved_state else None
            if (
                entry is not None
                and entry[0].version == entry[1]
                and self._nodes.get(node_name) is entry[0]
            ):
                self.perf.publish_skips += 1
            else:
                self._republish((node_name,))

    def _bind_outer(self, node_name: str, pod: Pod, trace=None,
                    reserved_state: list | None = None) -> Pod:
        try:
            # idempotent-retry guard: the scheduler can re-issue a bind it
            # abandoned (its extender httpTimeout elapsed) that committed
            # server-side; a second reservation for the same uid would
            # double-book
            with self._lock:
                existing = self._pods.get(pod.uid)
            if existing is not None:
                prev = existing.node_name
                if prev == node_name:
                    log.info(
                        "bind of %s to %s is already committed; idempotent "
                        "success", pod.key(), node_name,
                    )
                    return existing
                raise BindError(
                    f"pod {pod.key()} is already "
                    + (f"bound to {prev}" if prev else "mid-bind"),
                    reason=REASON_ALREADY_BOUND,
                )
            gang = podutil.gang_of(pod)
            if gang and gang[1] > 1 and podutil.gang_is_strict(pod):
                bound = self._bind_strict(node_name, pod, gang, trace,
                                          reserved_state)
            else:
                bound = self._bind(node_name, pod, trace, reserved_state)
        except BindError as e:
            self.recorder.event(
                pod, "Warning", events.REASON_FAILED_BINDING, str(e)
            )
            raise
        chips = podutil.get_assigned_chips(bound) or {}
        placed = ", ".join(
            f"{c}->[{','.join(map(str, ids))}]" for c, ids in chips.items() if ids
        )
        self.recorder.event(
            bound, "Normal", events.REASON_ASSIGNED,
            f"bound to {node_name} ({placed}; policy {self.rater.name})",
        )
        return bound

    def _bind(self, node_name: str, pod: Pod, trace=None,
              reserved_state: list | None = None) -> Pod:
        info, plan = self._reserve(node_name, pod, trace, reserved_state)
        return self._commit_reserved(info, plan, node_name, pod, trace)

    def _reserve(self, node_name: str, pod: Pod, trace=None,
                 reserved_state: list | None = None):
        """Apply the pod's chip reservation on the node (no API writes).
        Returns (NodeInfo, Plan); raises BindError when infeasible.
        ``reserved_state`` (when given) receives ``(info, version)`` at
        reserve time — the token bind()'s finally-clause compares to
        decide whether the commit moved chip state at all."""
        info = self._node_info(node_name)
        if info is None:
            raise BindError(
                f"node {node_name} is not a known TPU node",
                reason=REASON_NOT_TPU_NODE,
            )
        demand = self._demand_of(pod)
        plan = info.bind(demand, self.rater)
        if plan is None:
            raise BindError(
                f"no feasible plan for pod {pod.key()} on node {node_name}",
                reason=REASON_INSUFFICIENT_CHIPS,
            )
        if reserved_state is not None:
            # captured BEFORE the publish below: every later version bump
            # (a rollback here, a concurrent bind) carries its own
            # publish, so "version still == this" means the publish below
            # covered every chip move this bind is responsible for
            reserved_state.append((info, info.version))
        if trace is not None:
            trace.event("bind:reserved", node_name)
            if self._shard_fn is not None:
                # thread the shard identity into the bind's causal record:
                # which publication domain this reservation republished
                trace.event(
                    "bind:shard", self._shard_of.get(node_name, "?")
                )
        # publish the reservation NOW, not at bind completion: the API
        # writes (and a strict gang's park window) can take seconds, and
        # concurrent Filters reading the old snapshot would keep steering
        # co-scheduled pods onto chips this pod already holds
        self._republish((node_name,))
        return info, plan

    def _drop_gang_barrier(self, gang_key: str) -> None:
        """GangTracker on_gang_empty hook: a forgotten gang's barrier must
        not leave ``open=True`` behind for a re-submitted same-named gang
        (that would silently bypass the all-or-nothing guarantee). The
        recovery plane's hole for the gang dissolves with it — nothing
        left to hold capacity for."""
        with self._lock:
            self._gang_barriers.pop(gang_key, None)
        recovery = self.recovery
        if recovery is not None:
            recovery.gang_gone(gang_key)

    def _invalidate_reservation(self, uid: str, res: _Reservation) -> None:
        """Mark a parked reservation dead AND stop it counting toward its
        gang's barrier threshold (caller holds the dealer lock). Leaving
        the uid parked would let the barrier open one REAL member short —
        a partial commit, the exact thing strict mode forbids."""
        res.valid = False
        barrier = self._gang_barriers.get(res.gang_key)
        if barrier is not None:
            with barrier.cv:
                barrier.parked.discard(uid)
                barrier.cv.notify_all()

    def _bind_strict(self, node_name: str, pod: Pod,
                     gang: tuple[str, int], trace=None,
                     reserved_state: list | None = None) -> Pod:
        """All-or-nothing gang bind (tpu.io/gang-policy: strict): reserve,
        register the reservation (so node rebuilds migrate it), then park
        at the gang's barrier until ``barrier.size`` members hold
        reservations (bound members count); a timeout rolls this pod's
        reservation back and fails the bind with a clear message — the
        scheduler retries, and chips never stay reserved for an incomplete
        gang. See nanotpu.dealer.gang module docstring for why this is
        opt-in, and deploy/kube-scheduler-config.yaml: the extender
        httpTimeout must exceed the gang timeout or the scheduler abandons
        parked binds that later commit server-side."""
        key = f"{pod.namespace}/{gang[0]}"
        with self._lock:
            barrier = self._gang_barriers.get(key)
            if barrier is None:
                barrier = self._gang_barriers[key] = GangBarrier(gang[1])
            else:
                # the threshold is the LARGEST size any member declares: a
                # first arriver with a typoed smaller size must not leave
                # the barrier undersized (it would open before the real
                # gang is complete — a partial commit). Raising size only
                # ever tightens the open condition, so no waiter needs a
                # wakeup. Lock order: dealer lock -> barrier.cv, same as
                # _invalidate_reservation.
                with barrier.cv:
                    barrier.size = max(barrier.size, gang[1])
            barrier.users += 1
        try:
            return self._park_and_commit(barrier, key, node_name, pod, trace,
                                         reserved_state)
        finally:
            with self._lock:
                barrier.users -= 1
                # eager cleanup of a closed, idle barrier (every member
                # timed out): no unbounded growth, and no prune that could
                # orphan a concurrently-fetched barrier (users guards that)
                if (
                    barrier.users == 0
                    and not barrier.parked
                    and not barrier.open
                    and self._gang_barriers.get(key) is barrier
                ):
                    self._gang_barriers.pop(key, None)

    def _park_and_commit(self, barrier: GangBarrier, key: str,
                         node_name: str, pod: Pod, trace=None,
                         reserved_state: list | None = None) -> Pod:
        info, plan = self._reserve(node_name, pod, trace, reserved_state)
        # parking and reservation registration are ONE dealer-lock
        # critical section (lock order dealer -> cv, same as
        # _bind_strict): a batch committer captures the parked set under
        # cv but claims the reservations under the dealer lock, so a
        # member must never be visible in `parked` before its
        # reservation is registered — the committer would claim None and
        # fail a member whose chips are validly reserved
        my_res = _Reservation(
            node_name, info, plan, key, pod, trace,
            parked_at=self._clock(),
        )
        with self._lock:
            with barrier.cv:
                if pod.uid in barrier.parked:
                    info.unbind(plan)
                    raise BindError(
                        f"bind of {pod.key()} is already parked at gang "
                        f"{key}'s barrier",
                        reason=REASON_ALREADY_BOUND,
                    )
                barrier.parked.add(pod.uid)
            self._reserved[pod.uid] = my_res
        self._ha_emit("gang_park", uid=pod.uid, gang=key, node=node_name)
        if trace is not None:
            trace.event("gang:parked", key)
        timeout = podutil.gang_timeout(pod)
        deadline = time.monotonic() + timeout
        # exactly-once park-window observation (gang.WaitObservation):
        # every exit below flows through ONE latched observe, so no
        # combination of timeout rollback, batched-result delivery, and
        # recovery-driven de-parks can double-sample the histogram
        wait_obs = WaitObservation(
            self.obs.gang_wait if self.obs is not None else None,
            time.monotonic(),
        )
        try:
            try:
                batch = None
                with barrier.cv:
                    if (
                        not barrier.open
                        and not barrier.committing
                        and self.gangs.bound_count(key) + len(barrier.parked)
                        >= barrier.size
                    ):
                        if (
                            self._commit_pool is not None
                            and len(barrier.parked) > 1
                        ):
                            # batched gang commit (docs/bind-pipeline.md):
                            # the arriving member that completes the gang
                            # becomes its COMMITTER — it fans every parked
                            # member's API writes out through the bounded
                            # commit pool and only then opens the barrier,
                            # delivering per-member results. Claiming under
                            # cv suspends the claimed members' timeouts:
                            # their writes are now in flight.
                            barrier.committing = True
                            batch = sorted(barrier.parked)
                            barrier.claimed.update(batch)
                        else:
                            barrier.open = True
                            barrier.cv.notify_all()
                if batch is not None:
                    self._commit_gang_batch(barrier, key, batch, trace)
                with barrier.cv:
                    while not barrier.open:
                        if pod.uid not in barrier.parked:
                            # de-parked by _invalidate_reservation (node
                            # died mid-park): fail now, not at the
                            # timeout — the post-loop validity check
                            # raises the right error
                            break
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            if pod.uid in barrier.claimed:
                                # a batch committer claimed this member:
                                # its API write is IN FLIGHT on the commit
                                # pool and will deliver a result (the
                                # resilient client bounds its attempts) —
                                # a timeout rollback here would double-
                                # book the chips that write is committing
                                barrier.cv.wait(1.0)
                                continue
                            have = (
                                self.gangs.bound_count(key)
                                + len(barrier.parked)
                            )
                            raise BindError(
                                f"gang {key} barrier timeout: {have} of "
                                f"{barrier.size} members held reservations "
                                f"within {timeout:g}s; reservation for "
                                f"{pod.key()} rolled back",
                                reason=REASON_GANG_TIMEOUT,
                            )
                        barrier.cv.wait(remaining)
            finally:
                # ONE observation point covering every exit from the
                # park window — open, timeout, and unexpected raises
                wait_obs.observe(time.monotonic())
        except BindError:
            if trace is not None:
                trace.event("gang:timeout", key)
            with barrier.cv:
                # also clear any claim/result that raced this timeout
                # (the committer can capture a member in the window
                # between its timeout raise and this handler): a stale
                # claim would suspend a RETRY's timeout forever, and a
                # stale result would be mistaken for the retry's own
                barrier.parked.discard(pod.uid)
                barrier.claimed.discard(pod.uid)
                barrier.results.pop(pod.uid, None)
            with self._lock:
                res = self._reserved.pop(pod.uid, None)
            if res is not None and res.valid:
                res.info.unbind(res.plan)
            self._ha_emit("gang_unpark", uid=pod.uid, gang=key)
            raise
        with barrier.cv:
            barrier.parked.discard(pod.uid)
            barrier.claimed.discard(pod.uid)
            opened = barrier.open
            entry = barrier.results.pop(pod.uid, None)
            # a result is only OURS if it carries OUR reservation: an
            # entry left by a previous (timed-out) park of this uid must
            # not decide this bind — drop it and commit individually
            result = (
                entry[1] if entry is not None and entry[0] is my_res
                else None
            )
        if result is not None:
            # this member's commit ran on the batch committer's pool: the
            # result IS the outcome — accounting was committed or rolled
            # back there, exactly as it would have been on this thread
            if isinstance(result, BindError):
                raise result
            if trace is not None:
                trace.event("gang:opened", f"{key} batched")
            return result
        with self._lock:
            res = self._reserved.pop(pod.uid, None)
        if res is not None and res.valid and opened:
            if trace is not None:
                trace.event("gang:opened", key)
            return self._commit_reserved(
                res.info, res.plan, node_name, pod, trace
            )
        if res is not None and res.valid:
            # de-parked without the barrier opening (defensive): roll back
            res.info.unbind(res.plan)
        # node rebuilt/removed while parked and the plan no longer fits
        # (or the pod was forgotten): nothing to roll back — the chips
        # live on an orphaned NodeInfo or were never re-applied
        raise BindError(
            f"node {node_name} changed while {pod.key()} awaited gang "
            f"{key}'s barrier; reservation lost, bind must retry",
            reason=REASON_NODE_CHANGED,
        )

    def _commit_gang_batch(self, barrier: GangBarrier, key: str,
                           uids: list[str], trace=None) -> None:
        """Fan a complete strict gang's member commits out through the
        bounded commit pool — ``pipeline_depth`` members' annotation +
        binding writes overlap, so the gang commits in ~ceil(n/depth)
        write round-trips instead of n sequential ones.

        Claimed reservations are popped from ``_reserved`` first (the
        same ownership transfer a single member's post-open path does),
        then each member's :meth:`_commit_reserved` runs on a worker —
        bookkeeping, per-member rollback, and the assume-TTL/forget
        replay escape hatches are shared with the one-at-a-time path, so
        failure semantics are identical: a member whose write fails gets
        its accounting rolled back and a BindError result while the rest
        of the (already fully-reserved) gang commits; kube-scheduler
        retries the failed member, which binds straight through the open
        barrier. Never raises: every claimed uid gets a result and the
        barrier ALWAYS opens, even if the pool is shutting down."""
        with self._lock:
            claimed = [(uid, self._reserved.pop(uid, None)) for uid in uids]
        if trace is not None:
            trace.event("gang:batch-commit", f"{key} members={len(uids)}")
        #: uid -> (claimed reservation, bound Pod | BindError). The
        #: reservation is the result's IDENTITY: the parked thread only
        #: consumes a result carrying ITS OWN reservation, so an outcome
        #: orphaned by a timeout race can never decide a later re-bind.
        results: dict[str, tuple] = {}
        try:
            futures = {}
            for uid, res in claimed:
                if res is None or not res.valid:
                    # node removed/rebuilt while parked and the plan no
                    # longer fits — or the member's timeout raced our
                    # claim and already rolled itself back: same terminal
                    # answer the individual post-open path gives
                    results[uid] = (res, BindError(
                        f"node changed while a member of gang {key} "
                        "awaited the barrier; reservation lost, bind "
                        "must retry",
                        reason=REASON_NODE_CHANGED,
                    ))
                    continue
                try:
                    futures[self._commit_pool.submit(
                        self._commit_gang_member, res
                    )] = (uid, res)
                except Exception as e:
                    # pool shutting down (dealer.close() racing a live
                    # gang): the claimed reservation was applied via
                    # info.bind and nothing downstream will commit it —
                    # roll it back HERE or the chips leak ownerless
                    res.info.unbind(res.plan)
                    results[uid] = (res, BindError(
                        f"gang {key} commit pool unavailable ({e}); "
                        "reservation rolled back, bind must retry",
                    ))
            for future, (uid, res) in futures.items():
                results[uid] = (res, future.result())
        finally:
            with barrier.cv:
                for uid in uids:
                    # defensive: every claimed uid gets a terminal answer
                    results.setdefault(uid, (None, BindError(
                        f"gang {key} batch commit aborted before this "
                        "member's write was attempted; bind must retry",
                    )))
                barrier.results.update(results)
                barrier.committing = False
                barrier.open = True
                barrier.cv.notify_all()

    def _commit_gang_member(self, res: _Reservation):
        """One claimed member's API writes + bookkeeping on a commit-pool
        worker. Returns the bound Pod or the BindError — accounting is
        committed/rolled back inside ``_commit_reserved`` exactly as on
        the member's own bind thread. The member's trace is re-bound
        thread-locally so the resilient client's retry/breaker events
        land in the right causal record."""
        set_current(res.trace)
        try:
            bound = self._commit_reserved(
                res.info, res.plan, res.node_name, res.pod, res.trace
            )
            self.perf.gang_batched_commits += 1
            return bound
        except BindError as e:
            return e
        except Exception as e:  # defensive: a worker must never lose a
            # member's outcome — the parked thread is waiting on it
            log.exception(
                "gang member commit for pod uid(s) on %s failed "
                "unexpectedly", res.node_name,
            )
            return BindError(f"gang member commit failed: {e}")
        finally:
            set_current(None)

    def _commit_reserved(self, info, plan: Plan, node_name: str,
                         pod: Pod, trace=None) -> Pod:
        """API writes + bookkeeping for an applied reservation (the second
        half of a bind; rolls the reservation back on write failure).
        The wall-clock duration lands in the ``nanotpu_bind_commit_
        duration_seconds`` histogram when an Observability bundle is
        attached — the cost of the two apiserver writes is the part of a
        bind the dealer cannot control and the part worth a
        distribution."""
        if self.obs is not None:
            commit_t0 = time.monotonic()
            try:
                return self._commit_reserved_inner(
                    info, plan, node_name, pod, trace
                )
            finally:
                self.obs.bind_commit.observe(time.monotonic() - commit_t0)
        return self._commit_reserved_inner(info, plan, node_name, pod, trace)

    def _commit_reserved_inner(self, info, plan: Plan, node_name: str,
                               pod: Pod, trace=None) -> Pod:
        # register BEFORE the API writes: update_pod fires a MODIFIED event
        # (assume=true) that the reconciler races to allocate — the map entry
        # is what makes _learn_bound_pod a no-op for this pod
        with self._lock:
            was_released = pod.uid in self._released
            self._pods[pod.uid] = pod
            self._released.pop(pod.uid, None)
        try:
            if trace is not None:
                trace.event("bind:commit", f"annotate+bind {node_name}")
            annotated = self._write_annotations(pod, plan)
            self.client.bind_pod(annotated.namespace, annotated.name, node_name)
            # mirror what the binding subresource did server-side, so the
            # tracked copy is releasable on its own (release derives the
            # node from spec.nodeName)
            annotated.raw.setdefault("spec", {})["nodeName"] = node_name
        except ApiError as e:
            info.unbind(plan)
            with self._lock:
                self._pods.pop(pod.uid, None)
                if was_released:  # restore the tombstone we popped
                    self._mark_released(pod.uid)
            raise BindError(
                f"bind of {pod.key()} to {node_name} failed: {e}",
                reason=(
                    REASON_FENCED
                    if isinstance(e, FencedError)
                    else REASON_BREAKER_OPEN
                    if isinstance(e, BreakerOpenError)
                    else REASON_API_ERROR
                ),
            ) from e
        with self._lock:
            # a release/forget may have raced us mid-bind (pod deleted while
            # the API writes were in flight): it popped our reservation and
            # tombstoned the uid, but couldn't return the chips (the reserved
            # pod carried no annotations) — undo the allocation here
            raced = pod.uid not in self._pods
            needs_replay = False
            if not raced:
                current = self._nodes.get(node_name)
                if current is None or current is info:
                    self._pods[pod.uid] = annotated
                    self._accounted[pod.uid] = info
                    # gang membership must be recorded under the same lock as
                    # the raced check: recording after release() completed
                    # would leave a phantom member forget_pod never clears
                    gang = podutil.gang_of(pod)
                    if gang:
                        self.gangs.record_bound(
                            f"{pod.namespace}/{gang[0]}", gang[1], pod.uid,
                            node_name,
                        )
                else:
                    # a refresh_node rebuilt this node while the API writes
                    # were in flight — our chips live on the orphaned
                    # NodeInfo. The pod is annotated now; migrate via the
                    # replay path (outside the lock). Un-track first so the
                    # replay's uid check passes; refresh cannot double-replay
                    # because the decision happens under this same lock.
                    self._pods.pop(pod.uid, None)
                    self._accounted.pop(pod.uid, None)
                    needs_replay = True
        if raced:
            info.unbind(plan)
            raise BindError(
                f"pod {pod.key()} was released while bind was in flight",
                reason=REASON_POD_RELEASED,
            )
        if needs_replay:
            self._learn_bound_pod(annotated)  # emits its own HA record
        else:
            self._ha_emit("bound", pod=annotated.raw)
        recovery = self.recovery
        if recovery is not None:
            # production lifecycle hooks (docs/defrag.md): a bind landing
            # inside another gang's hole records its backfill lease, and
            # the bind that completes a gang closes that gang's hole —
            # the sim's driver-side calls are idempotent with these
            recovery.note_bound(annotated, node_name)
            gang = podutil.gang_of(annotated)
            if gang and gang[1] > 1:
                key = f"{annotated.namespace}/{gang[0]}"
                if self.gangs.bound_count(key) >= gang[1]:
                    recovery.gang_bound(key)
        return annotated

    def _write_annotations(self, pod: Pod, plan: Plan) -> Pod:
        """Pod update with optimistic-lock retry (dealer.go:177-190). Unlike
        the reference, non-conflict errors propagate instead of reading as
        success (dealer.go:188 returned nil)."""
        assignments = plan.by_container_name()
        current = pod
        for attempt in range(BIND_CONFLICT_RETRIES + 1):
            annotated = podutil.annotated_pod(
                current, assignments, policy=self.rater.name
            )
            try:
                return self.client.update_pod(annotated)
            except ConflictError:
                if attempt == BIND_CONFLICT_RETRIES:
                    raise
                current = self.client.get_pod(pod.namespace, pod.name)
        raise AssertionError("unreachable")

    # -- reconciler-driven state (dealer.go:205-255,311-319) ---------------
    def allocate(self, pod: Pod) -> bool:
        """Reconcile a scheduled+running pod into accounting (syncPod path)."""
        if not pod.node_name or not podutil.is_assumed(pod):
            return False
        learned = self._learn_bound_pod(pod)
        if learned:
            self._republish((pod.node_name,))
        return learned

    def release(self, pod: Pod) -> bool:
        """Return a completed pod's chips; idempotent via the released set
        (dealer.go:230-255).

        Only pods THIS dealer accounted (bound or learned) are releasable:
        releasing an untracked pod's annotations would hand back chips we
        never subtracted — e.g. a pod that completed before our boot, which
        _warm_from_cluster deliberately skipped — over-committing the node.
        """
        released = False
        released_node = None
        with self._lock:
            if pod.uid in self._released:
                return False
            tracked = self._pods.pop(pod.uid, None)
            accounted = self._accounted.pop(pod.uid, None)
            self._mark_released(pod.uid)
            if tracked is not None:
                plan = plan_from_pod(tracked)
                if plan is None:
                    if accounted is not None:
                        # annotated + accounted but now unreconstructible:
                        # genuine corruption. (A mid-bind reservation has no
                        # annotations AND no accounting — the bind thread's
                        # raced check returns those chips, not us.)
                        log.error(
                            "release: pod %s has no reconstructible plan",
                            pod.key(),
                        )
                else:
                    node = tracked.node_name or pod.node_name
                    # release on the instance that holds the chips; an
                    # orphaned instance (node deleted) is harmless garbage
                    info = accounted or self._nodes.get(node)
                    if info is not None:
                        try:
                            info.release(plan)
                            released = True
                            released_node = info.name
                        except ValueError as e:
                            log.error(
                                "release of %s on %s failed: %s",
                                pod.key(), node, e,
                            )
        self.gangs.forget_pod(pod.uid)
        # every first-sight release emits (tracked or not): the standby
        # must tombstone the uid too, or a late replayed `bound` could
        # resurrect a departed pod's chips on its side
        self._ha_emit(
            "released", uid=pod.uid, namespace=pod.namespace, name=pod.name,
        )
        recovery = self.recovery
        if recovery is not None:
            # lifecycle hook: a departed pod's backfill lease is cleaned
            # without an eviction (the on-time case of the lease
            # contract); gang-hole closure on emptied gangs rides the
            # tracker's on_gang_empty callback (_drop_gang_barrier)
            recovery.pod_gone(pod.uid)
        if released:
            self._republish((released_node,))
        return released

    def forget(self, pod: Pod) -> None:
        """Delete event: release if still accounted, and keep the released
        marker (dealer.go:311-319 dropped it, reopening a double-release race
        with an in-flight release; K8s UIDs never recur, so retaining the
        tombstone is safe — the set is LRU-bounded)."""
        self.release(pod)

    def _mark_released(self, uid: str) -> None:
        """Append to the bounded released-tombstone set. Caller holds lock."""
        self._released[uid] = None
        while len(self._released) > RELEASED_TOMBSTONES_MAX:
            self._released.pop(next(iter(self._released)))

    # -- migration (capacity recovery, docs/defrag.md) ---------------------
    def migrate(self, pod: Pod, target_node: str, trace=None) -> Pod:
        """Move a tracked pod's placement to ``target_node``: reserve on
        the target, rewrite the pod's chip annotations + ``nodeName`` in
        ONE apiserver write through the resilient client, then replay
        accounting source→target (release + allocate — the same
        assume/forget replay an agent restart performs, which is why an
        interrupted migration converges: the durable annotations always
        name exactly one placement).

        Raises :class:`BindError` with the target reservation rolled
        back — and the source placement untouched — on any failure, so a
        brownout mid-defrag degrades to "nothing moved". The publishes
        ride :meth:`_republish`, so with the commit pipeline on a
        migration batch folds into one coalesced snapshot swap per shard
        (docs/bind-pipeline.md)."""
        with self._lock:
            tracked = self._pods.get(pod.uid)
        if tracked is None:
            raise BindError(
                f"pod {pod.key()} is not tracked; nothing to migrate",
                reason=REASON_POD_RELEASED,
            )
        source = tracked.node_name
        if source == target_node:
            return tracked
        old_plan = plan_from_pod(tracked)
        if old_plan is None:
            raise BindError(
                f"pod {pod.key()} has no reconstructible plan; refusing "
                "to migrate an unaccountable placement",
            )
        info_t = self._node_info(target_node)
        if info_t is None:
            raise BindError(
                f"node {target_node} is not a known TPU node",
                reason=REASON_NOT_TPU_NODE,
            )
        demand = self._demand_of(tracked)
        plan_t = info_t.bind(demand, self.rater)
        if plan_t is None:
            raise BindError(
                f"no feasible plan for pod {pod.key()} on node "
                f"{target_node}",
                reason=REASON_INSUFFICIENT_CHIPS,
            )
        # publish the target reservation NOW (same rule as _reserve):
        # concurrent Filters must stop steering pods onto these chips
        # while the annotation write is in flight
        self._republish((target_node,))
        if trace is not None:
            trace.event("migrate:reserved", target_node)
        try:
            annotated = self._write_migration(tracked, plan_t, target_node)
        except (ApiError, NotFoundError) as e:
            info_t.unbind(plan_t)
            self._republish((target_node,))
            raise BindError(
                f"migration of {pod.key()} to {target_node} failed: {e}",
                reason=(
                    REASON_FENCED
                    if isinstance(e, FencedError)
                    else REASON_BREAKER_OPEN
                    if isinstance(e, BreakerOpenError)
                    else REASON_API_ERROR
                ),
            ) from e
        needs_replay = False
        with self._lock:
            if self._pods.get(pod.uid) is not tracked:
                # released/forgotten while the write was in flight: the
                # racer rolled the SOURCE accounting back; our target
                # reservation must follow (the pod object itself is the
                # racer's problem — deletion wins over migration)
                raced = True
            else:
                raced = False
                src_info = self._accounted.get(pod.uid)
                current = self._nodes.get(target_node)
                if current is None or current is info_t:
                    self._pods[pod.uid] = annotated
                    self._accounted[pod.uid] = info_t
                    gang = podutil.gang_of(annotated)
                    if gang:
                        # membership node moves with the pod (same lock
                        # as the map commit, mirroring _commit_reserved)
                        self.gangs.record_bound(
                            f"{annotated.namespace}/{gang[0]}", gang[1],
                            annotated.uid, target_node,
                        )
                else:
                    # target rebuilt mid-write: our chips live on an
                    # orphaned NodeInfo — migrate via the replay path
                    # (outside the lock), exactly as _commit_reserved
                    self._pods.pop(pod.uid, None)
                    self._accounted.pop(pod.uid, None)
                    needs_replay = True
                if src_info is not None and src_info is not info_t:
                    src_info.release(old_plan)
        if raced:
            info_t.unbind(plan_t)
            self._republish((target_node,))
            raise BindError(
                f"pod {pod.key()} was released while migration was in "
                "flight",
                reason=REASON_POD_RELEASED,
            )
        if needs_replay:
            self._learn_bound_pod(annotated)  # emits its own HA record
        else:
            # a move is just a `bound` with the new node: the standby's
            # applier releases the old placement first (docs/ha.md)
            self._ha_emit("bound", pod=annotated.raw)
        if trace is not None:
            trace.event("migrate:committed", f"{source}->{target_node}")
        self._republish(
            (source, target_node) if source else (target_node,)
        )
        return annotated

    def _write_migration(self, tracked: Pod, plan: Plan,
                         target_node: str) -> Pod:
        """The migration's single durable write: fresh GET (for the
        resourceVersion), new chip annotations AND ``spec.nodeName`` in
        one update, optimistic-retry on conflicts like
        :meth:`_write_annotations`."""
        assignments = plan.by_container_name()
        current = self.client.get_pod(tracked.namespace, tracked.name)
        for attempt in range(BIND_CONFLICT_RETRIES + 1):
            annotated = podutil.annotated_pod(
                current, assignments, policy=self.rater.name
            )
            annotated.raw.setdefault("spec", {})["nodeName"] = target_node
            try:
                return self.client.update_pod(annotated)
            except ConflictError:
                if attempt == BIND_CONFLICT_RETRIES:
                    raise
                current = self.client.get_pod(
                    tracked.namespace, tracked.name
                )
        raise AssertionError("unreachable")

    def has_reservation(self, uid: str) -> bool:
        """True when ``uid`` holds a parked strict-gang reservation (its
        capacity is already applied; the recovery plane must not clear
        more for it)."""
        with self._lock:
            res = self._reserved.get(uid)
            return res is not None and res.valid

    def parked_gang_pods(self) -> list[Pod]:
        """The production feed for
        :meth:`nanotpu.recovery.RecoveryPlane.run_once`: pods parked at
        strict-gang barriers (reservation applied, awaiting the rest of
        the gang) PLUS recently-starved gang pods (Filter answered zero
        feasible nodes — those members never reach the barrier, and
        without them a fully-fragmented fleet would hide exactly the
        gangs recovery exists for)."""
        now = time.monotonic()
        with self._lock:
            pods = [
                res.pod for res in self._reserved.values()
                if res.valid and res.pod is not None
            ]
            stale = [
                uid for uid, (p, t) in self._starved.items()
                if now - t > self.STARVED_TTL_S
                or uid in self._pods or uid in self._released
            ]
            for uid in stale:
                self._starved.pop(uid, None)
            seen = {p.uid for p in pods}
            pods += [
                p for uid, (p, _t) in self._starved.items()
                if uid not in seen
            ]
        return sorted(pods, key=lambda p: p.name)

    # -- metrics ingestion (controller metric-sync writes here) ------------
    def update_chip_usage(
        self, node: str, chip: int, core: float | None = None,
        memory: float | None = None, now: float | None = None,
        publish: bool = True,
    ) -> None:
        """``publish=False`` defers the snapshot publish: a metric sweep
        calls this once per chip, and per-chip publishes would clone every
        cached view's row arrays O(nodes x chips) times per tick — batch
        the sweep and finish with one :meth:`publish_usage`."""
        self.usage.update(node, chip, core=core, memory=memory, now=now)
        load = self.usage.effective_load(node, chip, now=now)
        if self._rater_observe is not None:
            # online contention calibration (docs/scoring.md): every
            # usage write the metric-sync loop delivers also feeds the
            # throughput model's per-card EWMA — which bumps the model
            # version, retiring every plan cached under the old one
            self._rater_observe(node, chip, load, now=now)
        info = self._node_info(node)
        if self.ha is not None and self._publish_enabled:
            # batched like the publish itself: one `usage` delta per
            # sweep (flushed below or by publish_usage), not one per chip
            self._ha_usage.append([node, chip, core, memory, now])
        if info is not None:
            info.set_chip_load(chip, load)
            if publish:
                self._republish((node,))
        if publish:
            self._ha_flush_usage()

    def publish_usage(self, nodes: tuple[str, ...]) -> None:
        """One snapshot publish covering a batch of deferred
        ``update_chip_usage(..., publish=False)`` calls."""
        self._republish(tuple(nodes))
        self._ha_flush_usage()

    def _ha_flush_usage(self) -> None:
        if not self._ha_usage:
            return
        batch, self._ha_usage = self._ha_usage, []
        self._ha_emit("usage", samples=batch)

    # -- introspection (dealer.go:303-309, routes.go:212-240) --------------
    def status(self) -> dict:
        with self._lock:
            infos = list(self._nodes.values())
            n_pods, n_released = len(self._pods), len(self._released)
        return {
            "nodes": {i.name: i.status() for i in infos},
            "assumed_pods": n_pods,
            "released_pods": n_released,
            "gangs": self.gangs.status(),
        }

    def occupancy(self) -> float:
        """Cluster-wide chip occupancy fraction — the BASELINE headline
        metric (BASELINE.json: >=95% under binpack)."""
        with self._lock:
            infos = list(self._nodes.values())
        used = sum(i.chips.percent_used() for i in infos)
        total = sum(i.chips.percent_total() for i in infos)
        return used / total if total else 0.0

    def capacity_status(self) -> dict:
        """Telemetry-timeline tap (docs/observability.md): fleet + per-
        pool occupancy and the whole-free chip count from ONE lock-held
        node list. Pools are keyed like snapshot shards
        (``generation/slice-family``) regardless of shard mode, so the
        series stay comparable across a ``--shards`` change."""
        with self._lock:
            infos = list(self._nodes.values())
        used = total = whole_free = 0
        pools: dict[str, list] = {}
        for info in infos:
            u = info.chips.percent_used()
            t = info.chips.percent_total()
            used += u
            total += t
            whole_free += info.chips.whole_free()
            agg = pools.setdefault(shard_key_of(info), [0, 0, 0])
            agg[0] += u
            agg[1] += t
            agg[2] += 1
        return {
            "occupancy": round(used / total, 6) if total else 0.0,
            "whole_free_chips": whole_free,
            "pools": {
                key: {
                    "occupancy": (
                        round(agg[0] / agg[1], 6) if agg[1] else 0.0
                    ),
                    "hosts": agg[2],
                }
                for key, agg in sorted(pools.items())
            },
        }

    def gang_park_status(self, now: float | None = None) -> dict:
        """Telemetry-timeline tap: DISTINCT gangs with members parked at
        barriers, total parked member reservations, and the oldest
        park's age on the dealer's clock (pass the sim's virtual now for
        deterministic ages). Gangs and members are separate series on
        purpose: a 64-member gang parked is ONE stuck gang, and an
        alert on "gangs stuck" must not fire 64x."""
        if now is None:
            now = self._clock()
        with self._lock:
            stamps = []
            gangs = set()
            for res in self._reserved.values():
                if res.valid:
                    stamps.append(res.parked_at)
                    gangs.add(res.gang_key)
        return {
            "parked": len(gangs),
            "parked_members": len(stamps),
            "oldest_age_s": (
                round(max(0.0, now - min(stamps)), 6) if stamps else 0.0
            ),
        }

    def shard_status(self) -> dict:
        """Per-shard publication state — generation, published host
        count, membership epoch vs published epoch, cached view count.
        A stale shard (epoch ahead of published_epoch, or a generation
        that stopped moving while siblings advance) is diagnosable from
        the outside via /debug/decisions and :meth:`debug_snapshot`."""
        out: dict[str, dict] = {}
        # list() snapshot: a concurrent _register_node may grow the dict
        for key, shard in list(self._shards.items()):
            snap = shard._published
            out[key] = {
                "gen": snap.gen,
                "hosts": len(snap.nodes),
                "epoch": (
                    self._nodes_epoch if self._shard_fn is None
                    else shard.epoch
                ),
                "published_epoch": shard._pub_epoch,
                "views": len(snap.views),
            }
        return out

    def perf_totals(self) -> dict[str, int]:
        """Fleet-wide attribution: the dealer's request-level counters
        plus every shard's own (the bench's per-rep deltas and the
        unlabeled ``nanotpu_sched_*`` gauges read this; per-shard values
        stay visible via :meth:`perf_by_shard`)."""
        out = self.perf.snapshot()
        for shard in list(self._shards.values()):
            if shard.perf is self.perf:
                continue  # single-shard mode aliases the dealer counters
            for name, value in shard.perf.snapshot().items():
                out[name] += value
        return out

    def perf_by_shard(self) -> dict[str, dict[str, int]]:
        """Per-shard attribution counter snapshots keyed by shard key."""
        return {
            key: shard.perf.snapshot()
            for key, shard in list(self._shards.items())
        }

    def debug_snapshot(self) -> dict:
        """Deep-introspection view for harnesses and invariant checkers
        (nanotpu.sim): tracked/reserved uids, uid -> accounting node, and
        the LIVE NodeInfo objects keyed by node name, plus per-shard
        publication state (``shards``). The maps are copies (safe to
        iterate), the NodeInfos are the real instances — callers
        that inspect chip state must tolerate concurrent verbs, or (like
        the single-threaded sim) guarantee none are in flight."""
        with self._lock:
            out = {
                "tracked_uids": sorted(self._pods),
                "reserved_uids": sorted(self._reserved),
                "accounted": {
                    uid: info.name for uid, info in self._accounted.items()
                },
                "node_infos": dict(self._nodes),
            }
        out["shards"] = self.shard_status()
        return out

    def pipeline_status(self) -> dict:
        """Commit-pipeline configuration + live coalescing state
        (docs/bind-pipeline.md): exposed on ``/debug/decisions`` so a
        storm's publish behavior is diagnosable from the outside."""
        shards = list(self._shards.values())
        return {
            "depth": self._pipeline_depth,
            "coalesce": self._coalesce,
            # named deltas plus parked probe-everything publishes
            # awaiting the next reader. NONZERO AFTER A WRITE BURST IS
            # NORMAL (binds only enqueue; the next read drains) — what
            # it diagnoses is a value that never returns to zero while
            # reads ARE arriving
            "pending": sum(len(shard._pending) for shard in shards)
            + sum(1 for shard in shards if shard._pending_all),
        }

    # -- HA delta stream + checkpoint (docs/ha.md) -------------------------
    def _ha_emit(self, kind: str, **data) -> None:
        """Append one record to the attached delta stream. One attribute
        check when HA is off; boot-time replay never emits (the standby
        gets boot state from its own warm boot / the checkpoint
        snapshot, not the stream)."""
        log_ = self.ha
        if log_ is not None and self._publish_enabled:
            log_.emit(kind, data)

    def apply_delta(self, rec: dict) -> bool:
        """Apply ONE state delta emitted by an active dealer into THIS
        dealer's live accounting + RCU snapshot chain (the warm
        standby's tail loop, and the checkpoint tail on warm restart).
        Every kind is idempotent — re-applied records (the bootstrap
        overlap window, duplicate tails) converge to the same state:
        ``bound`` is uid-guarded, ``released`` is tombstoned, node
        records compare fingerprints. Returns False exactly when a
        ``bound`` record could not be accounted (a conflict with stale
        local state) — the applier must then keep the pod in its
        reconcile window."""
        kind = rec.get("kind")
        data = rec.get("data") or {}
        if kind == "node":
            self.refresh_node(Node(data["raw"]))
        elif kind == "node_gone":
            self.remove_node(str(data.get("name", "")))
        elif kind == "bound":
            return self._apply_bound(Pod(data["pod"]))
        elif kind == "released":
            self.release(Pod({"metadata": {
                "uid": str(data.get("uid", "")),
                "namespace": str(data.get("namespace", "default")),
                "name": str(data.get("name", "")),
            }}))
        elif kind == "usage":
            touched: set[str] = set()
            for row in data.get("samples") or []:
                node, chip, core, memory, now = row
                self.update_chip_usage(
                    node, int(chip), core=core, memory=memory, now=now,
                    publish=False,
                )
                touched.add(node)
            if touched:
                self.publish_usage(tuple(sorted(touched)))
        # note kinds (gang_park/unpark, hole, lease, view) are the
        # coordinator's bookkeeping, not dealer state — it routes them
        return True

    def _apply_bound(self, pod: Pod) -> bool:
        """Fold a streamed placement into accounting. A uid tracked on a
        DIFFERENT node is a migration: release the old placement first
        (then clear the tombstone the release minted so the re-learn is
        not refused). Returns True when the placement is accounted
        (learned now, or already tracked on this node) — False means a
        CONFLICT (stale local state holds the chips) and the caller must
        keep the pod in its reconcile window instead of assuming the
        apply landed."""
        if not pod.node_name:
            return False
        with self._lock:
            tracked = self._pods.get(pod.uid)
            moved = (
                tracked is not None
                and tracked.node_name
                and tracked.node_name != pod.node_name
            )
            already = (
                tracked is not None
                and tracked.node_name == pod.node_name
            )
        if already:
            return True
        if moved:
            self.release(tracked)
            with self._lock:
                self._released.pop(pod.uid, None)
        learned = self._learn_bound_pod(pod)
        self._republish((pod.node_name,))
        if learned:
            return True
        with self._lock:
            # _learn_bound_pod also answers False for an idempotent
            # replay (uid already tracked/tombstoned) — only a genuine
            # allocation conflict counts as a failed apply
            return pod.uid in self._pods or pod.uid in self._released

    def warm_views(self, node_names: list[str]) -> bool:
        """Pre-build the frozen scoring view(s) + renderer(s) for a
        candidate tuple (the standby applying a ``view`` warm hint).
        After this, a Filter/Prioritize over the same tuple costs zero
        view/renderer builds — the property the failover bench pins on
        the first post-promotion Filter."""
        if not node_names:
            return False
        if self._shard_fn is None:
            if self._batch_prefer() is None:
                return False
            entry = self._view_for(self._default_shard, tuple(node_names))
            if entry is None:
                return False
            entry[0].ensure_renderer(entry[1])
            return True
        plan = self._shard_plan(list(node_names))
        if plan is None:
            return False
        for _shard, entry, names, _pos in plan[0]:
            entry[0].ensure_renderer(names)
        return True

    def checkpoint_state(self) -> dict:
        """Full restorable state snapshot (docs/ha.md): per node the
        DERIVED placement state — fingerprint tuple + per-chip rows —
        instead of the raw node object (the restart then pays none of
        the label/quantity parsing, and the snapshot bytes stay small:
        a minimal raw is synthesized from the fingerprint on restore);
        per pod the raw object (annotations are what later releases
        reconstruct plans from). Chip state is captured under each
        node's own lock; pod maps under the dealer lock. Deterministic
        ordering throughout."""
        with self._lock:
            infos = sorted(self._nodes.values(), key=lambda i: i.name)
            pods = sorted(self._pods.values(), key=lambda p: p.uid)
            node_entries = []
            for info in infos:
                with info.lock:
                    node_entries.append([
                        info.name,
                        list(info.fingerprint()),
                        info.chips.chip_rows(),
                    ])
            pod_entries = []
            for p in pods:
                gang = podutil.gang_of(p)
                # row layout: [uid, node, gang key, gang size, raw] —
                # the restore loop then touches no property chains and
                # re-parses no annotations
                pod_entries.append([
                    p.uid, p.node_name,
                    f"{p.namespace}/{gang[0]}" if gang else "",
                    gang[1] if gang else 0,
                    p.raw,
                ])
            return {"v": 3, "nodes": node_entries, "pods": pod_entries}

    def write_checkpoint(self, path: str) -> None:
        """Write a fresh checkpoint snapshot (atomic tmp+rename); a
        DeltaLog constructed with the same path appends the tail."""
        from nanotpu.ha.delta import write_checkpoint as _write

        log_ = self.ha
        _write(
            path, self.checkpoint_state(),
            seq=log_.seq if log_ is not None else 0,
        )

    def _restore_from_checkpoint(self, path: str) -> bool:
        """Warm restart: snapshot + delta-tail replay from the local
        checkpoint (docs/ha.md) — O(file), no apiserver round-trips, no
        per-raw deep copies, no annotation re-parse for pods whose plan
        was pre-resolved. Returns False (caller falls back to the full
        annotation replay) when the file is missing/corrupt."""
        from nanotpu.ha.delta import load_checkpoint

        state, records = load_checkpoint(path)
        if state is None:
            return False
        self._restore_state(state)
        for rec in records:
            try:
                self.apply_delta(rec)
            except Exception:
                log.exception(
                    "checkpoint tail replay failed at seq %s",
                    rec.get("seq"),
                )
        log.info(
            "warm restart from %s: %d nodes, %d pods, %d tail deltas",
            path, len(state.get("nodes") or []),
            len(state.get("pods") or []), len(records),
        )
        return True

    def _restore_state(self, state: dict) -> None:
        """Single-threaded boot work under one lock hold: no chip
        allocation happens here — the node rows carry the chip state
        the snapshot captured, which already reflects every tracked
        pod — and no annotation re-parsing (the pod rows carry the
        pre-derived uid/node/gang fields)."""
        from nanotpu.analysis.witness import rlock_factory

        lock_factory = rlock_factory("NodeInfo.lock")
        with self._lock:
            nodes = self._nodes
            for row in state.get("nodes") or []:
                try:
                    name = row[0]
                    # node_raw None on purpose: nothing reads it on the
                    # restore path (checkpoints store the fingerprint,
                    # node deltas carry the informer's raw), and
                    # synthesizing 4096 raws was a measured third of
                    # the whole warm boot
                    self._register_node(
                        name,
                        NodeInfo.restore(name, None, tuple(row[1]),
                                         row[2],
                                         lock_factory=lock_factory),
                    )
                except Exception:
                    log.exception("checkpoint node row unrestorable")
            pods_map = self._pods
            accounted = self._accounted
            released = self._released
            record_bound = self.gangs.record_bound
            for uid, node, gang_key, gang_size, raw in (
                state.get("pods") or []
            ):
                if uid in pods_map or uid in released:
                    continue
                info = nodes.get(node)
                if info is None:
                    continue
                pods_map[uid] = Pod(raw)
                accounted[uid] = info
                if gang_key:
                    record_bound(gang_key, gang_size, uid, node)

    def close(self) -> None:
        """Release the assume thread pool (and the commit pool when the
        pipeline is on). Needed by harnesses that churn dealers (the
        sim's agent-restart/scheduler-crash faults build a fresh dealer
        per incarnation) and by the HA pair's demoted side. IDEMPOTENT
        and safe to race a promotion mid-cycle: a second close (the old
        active's shutdown path and the coordinator's rewire both call
        it) is a no-op, and a flush of the delta checkpoint happens
        exactly once (pinned by the promote-under-load test)."""
        if self._closed:
            return
        self._closed = True
        self._pool.shutdown(wait=False)
        if self._commit_pool is not None:
            self._commit_pool.shutdown(wait=False)
        log_ = self.ha
        if log_ is not None:
            log_.flush()
