"""Hot-path attribution counters for the scheduler read/publish paths.

The r5 fan-out artifact's worst rep sat 41% under the >=1000 pods/s bar
with flat loadavg — an IN-PROCESS stall the bench could not name (VERDICT
r5 weak #2). These counters exist so the slow rep names its own cause:
the bench snapshots them around every timed window and `/metrics` exposes
them live, so "GC pause vs scorer rebuild vs renderer warmup vs fallback
path" is a delta read, not a guess.

Increment discipline: every counter is bumped either under the publish
lock (snapshot_*) or the per-candidate-list arena lock (view/renderer/
memo), where `+=` is already serialized. The fastpath_* pair is bumped on
the lock-free verb path; under CPython's GIL a lost update there is
vanishingly rare and only ever undercounts attribution, never corrupts
scheduling state.

Sharded attribution (r7): each snapshot shard owns its OWN PerfCounters
instance — publishes, view work, and native calls are attributed to the
shard that did them (``Dealer.perf_by_shard()``, the
``nanotpu_sched_shard`` metric family, and the bench's per-rep
``attr["shards"]`` split). The dealer's own instance keeps the
request-level counters (fastpath hits/misses); ``Dealer.perf_totals()``
sums both for the fleet-wide view. Single-shard dealers alias the
dealer's instance onto their one shard, so nothing changes there.
"""

from __future__ import annotations


class PerfCounters:
    """Monotonic process-lifetime counters; cheap enough for hot paths."""

    __slots__ = (
        "snapshot_publishes",
        "snapshot_structural",
        "view_builds",
        "view_advances",
        "renderer_builds",
        "fastpath_hits",
        "fastpath_misses",
        "memo_hits",
        "native_calls",
        "publish_skips",
        "publish_coalesced",
        "gang_batched_commits",
        "hook_refusals",
        "model_syncs",
        "batch_cycles",
        "batch_packed",
        "batch_fallbacks",
        "batch_contended",
    )

    def __init__(self):
        #: snapshot swaps (== published generation; structural = node-set
        #: change, which also drops candidate-list views)
        self.snapshot_publishes = 0
        self.snapshot_structural = 0
        #: fresh flattened-scorer builds (cold candidate list / topology
        #: change) vs copy-on-write advances (chip state moved under a
        #: cached list — the steady-state "rebuild" of a publish)
        self.view_builds = 0
        self.view_advances = 0
        #: pre-baked JSON fragment blob builds (once per candidate order;
        #: >0 inside a timed window means warmup leaked into it)
        self.renderer_builds = 0
        #: fused native score+render served the verb / fell back to the
        #: list-based path
        self.fastpath_hits = 0
        self.fastpath_misses = 0
        #: Filter->Prioritize shared-score memo hits vs actual native
        #: scoring calls
        self.memo_hits = 0
        self.native_calls = 0
        #: bind finally-clause republishes skipped because commit/rollback
        #: did not move chip state beyond what _reserve already published
        #: (the bench proves the two-publishes-per-bind pattern is gone)
        self.publish_skips = 0
        #: commit-pipeline publishes enqueued to the coalescing batcher
        #: instead of swapping inline (docs/bind-pipeline.md): the next
        #: reader folds ALL of a shard's pending deltas into one swap, so
        #: (coalesced - publishes) is the per-bind view-advance work the
        #: pipeline removed from the write path
        self.publish_coalesced = 0
        #: strict-gang member commits fanned out through the dealer's
        #: bounded commit pool (vs committed one-at-a-time on the member's
        #: own bind thread)
        self.gang_batched_commits = 0
        #: fused-path refusals because the rater scores through a Python
        #: row hook the native renderer cannot evaluate (docs/scoring.md)
        #: — split out of fastpath_misses so "the rater opted out" and
        #: "the fast path failed" are different numbers; the bench's
        #: native-throughput row asserts this stays ZERO
        self.hook_refusals = 0
        #: throughput-model mirror rebuilds in the scoring arena (ABI 7):
        #: one per model-version movement per view chain — a metric-sync
        #: batch costs one, a steady read window costs none
        self.model_syncs = 0
        #: batch-admission attribution (ABI 8, docs/batch-admission.md):
        #: joint-solve cycles run, demands the fused native pack placed,
        #: demands that fell back to the pod-at-a-time path (no batch
        #: plan, bind failure, invalid demand), and demands whose
        #: cross-shard reduce had more than one shard's proposal to
        #: resolve (the score-desc/name-asc contention the merge exists
        #: for)
        self.batch_cycles = 0
        self.batch_packed = 0
        self.batch_fallbacks = 0
        self.batch_contended = 0

    def snapshot(self) -> dict[str, int]:
        """Point-in-time copy (bench delta arithmetic / metrics render)."""
        return {name: getattr(self, name) for name in self.__slots__}
