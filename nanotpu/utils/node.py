"""Node-level helpers (rebuild of ``pkg/utils/node.go``)."""

from __future__ import annotations

from nanotpu import types
from nanotpu.k8s.objects import Node


def get_chip_count(node: Node) -> int:
    """Number of physical chips = capacity / 100 (pkg/utils/node.go:8-14)."""
    return node.capacity(types.RESOURCE_TPU_PERCENT) // types.PERCENT_PER_CHIP


def is_tpu_node(node: Node) -> bool:
    return get_chip_count(node) > 0


def is_tpu_enabled(node: Node) -> bool:
    """Metric-sync gate. Replaces the reference's NVIDIA-specific
    ``nvidia-device-enable=enable`` label check (pkg/controller/node.go:153-158);
    we additionally treat any node with TPU capacity as enabled so a missing
    label never silently disables load-aware scheduling."""
    if node.labels.get(types.LABEL_TPU_ENABLE) == types.LABEL_TPU_ENABLE_VALUE:
        return True
    return is_tpu_node(node)


def node_topology_labels(node: Node) -> dict[str, str]:
    """The topology-bearing labels, for logging/diagnostics."""
    keys = (
        types.LABEL_TPU_GENERATION,
        types.LABEL_TPU_TOPOLOGY,
        types.LABEL_TPU_SLICE,
        types.LABEL_TPU_SLICE_COORDS,
    )
    return {k: node.labels[k] for k in keys if k in node.labels}
