"""Request deadlines: the response-budget token threaded verb -> dealer.

kube-scheduler calls the extender under a hard ``httpTimeout``
(deploy/kube-scheduler-config.yaml): a response that arrives after it is
indistinguishable from no response, except that it also burned a handler
thread, the dealer locks, and an apiserver write slot on work nobody will
read. The route layer derives a per-verb budget from that contract
(:class:`nanotpu.routes.server.OverloadConfig`), wraps it in a
:class:`Deadline`, and threads it through ``verb.handle`` into the dealer,
which calls :func:`check` at its safe points — verb entry, before lock
acquisition, before apiserver round-trips — so an over-budget request
aborts where nothing needs rolling back instead of deep inside a commit.

Checks are deliberately sparse: once a bind holds a chip reservation it
runs to completion regardless of the deadline (committing is
idempotent-retry-safe, abandoning a half-written annotation is not).
``deadline=None`` everywhere means "no budget" — the sim and direct tests
drive verbs without one and pay zero overhead for it.
"""

from __future__ import annotations

import time


class DeadlineExceeded(Exception):
    """The request ran past its response budget; str() names the stage
    (e.g. ``filter:start``) where the overrun was detected."""


class Deadline:
    """An absolute monotonic expiry; cheap enough to probe per safe point."""

    __slots__ = ("at", "budget_s")

    def __init__(self, budget_s: float):
        self.budget_s = budget_s
        self.at = time.monotonic() + budget_s

    def remaining(self) -> float:
        return self.at - time.monotonic()

    def expired(self) -> bool:
        return time.monotonic() >= self.at


def check(deadline: Deadline | None, stage: str) -> None:
    """Raise :class:`DeadlineExceeded` when past budget; no-op for None."""
    if deadline is not None and time.monotonic() >= deadline.at:
        raise DeadlineExceeded(stage)
