"""Pod-level helpers: lifecycle predicates and the annotation codec.

Rebuild of ``pkg/utils/pod.go`` with the TPU vocabulary. Key differences from
the reference, each deliberate:

* chip assignments are *lists* of chip ids per container (topology plans can
  span chips), vs the reference's single card index (pkg/utils/pod.go:85-92);
* ``get_assigned_chips`` reads EVERY container's annotation — the reference's
  ``GetGPUIDFromAnnotation`` only read ``Containers[0]`` (pkg/utils/pod.go:34),
  a documented bug we do not replicate.
"""

from __future__ import annotations

from nanotpu import types
from nanotpu.k8s.objects import Pod


# -- lifecycle predicates (pkg/utils/pod.go:15-29) -------------------------

def is_completed_pod(pod: Pod) -> bool:
    """Deleted, Succeeded, or Failed (pkg/utils/pod.go:15-24)."""
    if pod.deletion_timestamp:
        return True
    return pod.phase in ("Succeeded", "Failed")


def is_tpu_sharing_pod(pod: Pod) -> bool:
    """Pod requests any tpu.io/chip-percent (pkg/utils/pod.go:27-29)."""
    return get_tpu_percent_from_pod(pod) > 0


def is_assumed(pod: Pod) -> bool:
    """Bind already stamped the assume annotation (pkg/utils/pod.go:81-83)."""
    return pod.annotations.get(types.ANNOTATION_ASSUME) == "true"


# -- demand readers (pkg/utils/pod.go:50-58,94-100) ------------------------

def get_tpu_percent_from_container(container) -> int:
    return container.limit(types.RESOURCE_TPU_PERCENT)


def get_tpu_percent_from_pod(pod: Pod) -> int:
    return sum(get_tpu_percent_from_container(c) for c in pod.containers)


# -- annotation codec ------------------------------------------------------

def encode_chips(chips: list[int]) -> str:
    """Chip id list -> annotation value ("0,1,2,3"; "-1" for no-TPU)."""
    if not chips:
        return str(types.NOT_NEED_TPU)
    return ",".join(str(c) for c in sorted(chips))


def decode_chips(value: str) -> list[int] | None:
    """Annotation value -> chip id list.

    The "-1" sentinel decodes to [] (container legitimately owns no chips);
    a corrupted/unparsable value decodes to None so callers can tell
    corruption apart from "no chips" and keep the pod's chips accounted for
    (the reference's GetGPUIDFromAnnotation likewise surfaced parse errors,
    pkg/utils/pod.go:32-48).
    """
    try:
        ids = [int(p) for p in value.split(",")]
    except ValueError:
        return None
    if ids == [types.NOT_NEED_TPU]:
        return []
    if not ids or any(i < 0 for i in ids):
        return None
    return sorted(set(ids))


def annotated_pod(pod: Pod, assignments: dict[str, list[int]], policy: str = "") -> Pod:
    """Return a deep-copied pod stamped with the placement decision.

    Mirrors ``GetUpdatedPodAnnotationSpec`` (pkg/utils/pod.go:65-79): one
    annotation per container plus the assume annotation AND label.

    Raises ValueError if a TPU-requesting container has no assignment —
    stamping the no-TPU sentinel for it would bind a pod the agent then
    grants nothing, an invisible failure until the workload crashes.
    """
    out = pod.deepcopy()
    ann = out.ensure_annotations()
    for c in out.containers:
        if get_tpu_percent_from_container(c) > 0 and not assignments.get(c.name):
            raise ValueError(
                f"container {c.name!r} requests TPU but has no chip assignment"
            )
        key = types.ANNOTATION_CONTAINER_FMT.format(name=c.name)
        ann[key] = encode_chips(assignments.get(c.name, []))
    ann[types.ANNOTATION_ASSUME] = "true"
    if policy:
        ann[types.ANNOTATION_BOUND_POLICY] = policy
    out.ensure_labels()[types.ANNOTATION_ASSUME] = "true"
    return out


def get_container_assigned_chips(pod: Pod, container_name: str) -> list[int] | None:
    """Parse one container's assignment back (pkg/utils/pod.go:85-92).

    Returns None when the annotation is absent (pod not bound by us).
    """
    key = types.ANNOTATION_CONTAINER_FMT.format(name=container_name)
    value = pod.annotations.get(key)
    if value is None:
        return None
    return decode_chips(value)


def get_assigned_chips(pod: Pod) -> dict[str, list[int]] | None:
    """All containers' assignments, or None if any annotation is missing.

    Fixes the reference's first-container-only bug (pkg/utils/pod.go:32-48).
    """
    out: dict[str, list[int]] = {}
    for c in pod.containers:
        chips = get_container_assigned_chips(pod, c.name)
        if chips is None:
            return None
        out[c.name] = chips
    return out


# -- capacity-recovery helpers (docs/defrag.md) ----------------------------

def priority_of(pod: Pod) -> int:
    """The pod's priority class (``tpu.io/priority``); malformed or absent
    values read as the default so a typo can never make a pod preemptible
    by accident in one direction and unevictable in the other — it just
    lands in the default class."""
    raw = pod.annotations.get(types.ANNOTATION_PRIORITY)
    if raw is None:
        return types.PRIORITY_DEFAULT
    try:
        return int(raw)
    except ValueError:
        return types.PRIORITY_DEFAULT


def expected_runtime_s(pod: Pod) -> float | None:
    """The submitter's declared runtime estimate, or None when undeclared/
    malformed — an undeclared runtime disqualifies the pod from backfill
    (the lease contract needs an expiry to enforce)."""
    import math

    raw = pod.annotations.get(types.ANNOTATION_EXPECTED_RUNTIME)
    if raw is None:
        return None
    try:
        val = float(raw)
    except ValueError:
        return None
    return val if math.isfinite(val) and val > 0 else None


def epoch_of(pod: Pod) -> int:
    """The leader-lease epoch stamped on the pod's placement
    (``tpu.io/epoch``), or 0 when absent/malformed — pre-fencing pods
    and single-replica deployments read as epoch 0, which is never
    "stale" (the sweeper's stale-epoch heal compares strictly)."""
    raw = pod.annotations.get(types.ANNOTATION_EPOCH)
    if raw is None:
        return 0
    try:
        return max(int(raw), 0)
    except ValueError:
        return 0


def strip_placement(pod: Pod, clear_node: bool = False) -> Pod:
    """Deep-copied pod with every placement mark removed: the assume
    annotation AND label, the bound-by policy, the writer-epoch stamp,
    and each container's chip annotation — exactly what the assume-TTL
    sweeper strips, shared here so preemption (which additionally clears
    ``spec.nodeName``, the requeue half of preempt-and-requeue) can
    never drift from it."""
    out = pod.deepcopy()
    ann = out.ensure_annotations()
    ann.pop(types.ANNOTATION_ASSUME, None)
    ann.pop(types.ANNOTATION_BOUND_POLICY, None)
    ann.pop(types.ANNOTATION_EPOCH, None)
    for c in out.containers:
        ann.pop(types.ANNOTATION_CONTAINER_FMT.format(name=c.name), None)
    out.ensure_labels().pop(types.ANNOTATION_ASSUME, None)
    if clear_node:
        (out.raw.get("spec") or {}).pop("nodeName", None)
    return out


# -- gang helpers (new; BASELINE configs 3-4) ------------------------------

def gang_of(pod: Pod) -> tuple[str, int] | None:
    """(gang name, size) if the pod declares gang membership, else None."""
    name = pod.annotations.get(types.ANNOTATION_GANG_NAME)
    if not name:
        return None
    try:
        size = int(pod.annotations.get(types.ANNOTATION_GANG_SIZE, "0"))
    except ValueError:
        size = 0
    return name, max(size, 0)


def gang_is_strict(pod: Pod) -> bool:
    """True when the pod opts into all-or-nothing gang binding."""
    return (
        pod.annotations.get(types.ANNOTATION_GANG_POLICY, "").strip().lower()
        == types.GANG_POLICY_STRICT
    )


def gang_timeout(pod: Pod) -> float:
    """Strict-barrier park timeout for this pod, clamped to a finite
    [0.1, 3600] s: "nan" would busy-spin Condition.wait forever and "inf"
    overflows it to an exception that escapes the rollback path — either
    way a reservation would leak on a wedged bind thread."""
    import math

    raw = pod.annotations.get(types.ANNOTATION_GANG_TIMEOUT)
    try:
        val = float(raw) if raw else types.GANG_BARRIER_TIMEOUT_S
    except ValueError:
        val = types.GANG_BARRIER_TIMEOUT_S
    if not math.isfinite(val):
        val = types.GANG_BARRIER_TIMEOUT_S
    return min(max(val, 0.1), 3600.0)
