"""Capacity recovery: priority preemption, defragmentation, gang backfill.

See :mod:`nanotpu.recovery.plane` (and docs/defrag.md) for the design.
"""

from nanotpu.recovery.plane import (  # noqa: F401
    Hole,
    Lease,
    RecoveryConfig,
    RecoveryLoop,
    RecoveryPlane,
)
