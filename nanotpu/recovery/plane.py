"""The capacity-recovery plane: preemption, defragmentation, gang backfill.

Long-lived fractional pods fragment the ICI torus until large gangs park
forever — occupancy looks healthy while the fleet's *usable* large-slice
capacity collapses (ROADMAP item 3; Tesserae's placement-quality-vs-
partitioning tradeoff). This module turns the repo's existing machinery —
the assume/forget annotation replay the agent-restart fault already
proves convergent, the native batch scoring path, the coalescing
controller queue — into an active recovery subsystem with three tools:

* **preempt-and-requeue** — pods carry a priority class
  (``tpu.io/priority``); a parked gang may evict strictly-lower-priority
  non-gang pods (placement stripped through the same
  :func:`~nanotpu.utils.pod.strip_placement` path the assume-TTL sweeper
  uses, chips rolled back via ``Dealer.forget``, the sync requeued
  through the coalescing queue with ``force=True``). A per-cycle
  **eviction budget** bounds displaced work, so preemption can never
  thrash: the budget is the proof.
* **defragmentation** — fractional pods blocking whole chips are MOVED
  instead of killed when spare capacity exists elsewhere:
  ``Dealer.migrate`` rewrites the pod's chip annotations + nodeName in
  one write through the resilient client and replays accounting
  source→target (release + allocate, the same assume/forget replay a
  restart performs), so an interrupted migration converges from the
  durable annotations. Candidate targets come from the SAME native
  scoring path Prioritize uses (``Dealer.top_candidates`` — the Q16
  fixed-point engine for the throughput rater); the defrag cost model
  then gates them — the steady-state sweep accepts a move only when the
  fleet's whole-free chip count strictly improves (the monotone rule
  that makes migration ping-pong impossible), gang clearing accepts any
  non-hole absorber, cheapest loss first. Per-cycle **migration** and
  **sweep budgets** bound churn.
* **gang backfill** — capacity cleared for a parked gang is earmarked as
  a :class:`Hole`: other pods are filtered away from hole nodes so churn
  cannot refill them, EXCEPT short low-priority pods whose declared
  runtime (``tpu.io/expected-runtime-s``) ends before the gang's
  expected start — those bind under a :class:`Lease` (reason
  ``backfilled``) and are evicted at expiry if still running (reason
  ``lease_expired``), so reserved capacity never idles and never delays
  the gang.

Every action lands in the decision ledger as a typed reason code and in
the ``nanotpu_sched_defrag_*`` / ``nanotpu_gang_backfill_*`` counters
(:mod:`nanotpu.metrics.recovery`).

Concurrency: :meth:`RecoveryPlane.run_once` runs on ONE driver at a time
(the sim's event thread on virtual time, or the production
:class:`RecoveryLoop` thread). The read hooks the scheduling path calls
(:meth:`filter_candidates`, :meth:`note_bound`) read the hole map
lock-free — individual dict probes are GIL-atomic, and a read racing a
cycle at worst sees the previous cycle's holes, the same one-update
staleness window every RCU read path in the dealer already tolerates.
Client writes (the strip / migrate annotation updates) happen with no
plane state mid-mutation, so a failed write leaves both the holes and
the cluster exactly as they were.

Determinism: the plane draws NOTHING random — victim and target choice
are total orders (priority, displaced percent, name), every map is
iterated sorted, and the injectable ``clock`` is the only time source —
so a (scenario, seed) sim run that enables recovery is as
byte-reproducible as one that does not.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from nanotpu.allocator.core import ChipResource, ChipSet, Demand
from nanotpu.k8s.client import NotFoundError
from nanotpu.metrics.recovery import RecoveryCounters
from nanotpu.obs.decisions import (
    REASON_BACKFILLED,
    REASON_DRAIN_EXPIRED,
    REASON_DRAINING,
    REASON_LEASE_EXPIRED,
    REASON_MIGRATED,
    REASON_PREEMPTED,
)
from nanotpu.utils import pod as podutil

log = logging.getLogger("nanotpu.recovery")


@dataclass
class RecoveryConfig:
    """Knobs (scenario ``recovery`` section / cmd flags; docs/defrag.md)."""

    #: max pods evicted per run_once — the anti-thrash bound
    eviction_budget: int = 8
    #: max pods migrated per run_once
    migration_budget: int = 4
    #: max migrations the STEADY-STATE defrag sweep may spend per cycle
    #: (over and above gang clearing, but never past migration_budget's
    #: leftover) — consolidation is a background trickle, not a storm
    sweep_budget: int = 2
    #: grant backfill leases inside gang holes at all
    backfill: bool = True
    #: margin a backfill pod's declared end must clear the gang's
    #: expected start by (and the slack added to its lease expiry)
    lease_grace_s: float = 0.5
    #: how far ahead a freshly opened hole promises its gang will start —
    #: the backfill window's right edge
    gang_start_horizon_s: float = 5.0
    #: a hole whose gang stopped appearing parked dissolves after this
    hole_ttl_s: float = 30.0


@dataclass
class Lease:
    """One backfilled pod's deadline lease inside a gang hole."""

    uid: str
    pod_name: str
    namespace: str
    node: str
    expires_at: float
    gang_key: str


@dataclass
class Hole:
    """Reserved-but-waiting capacity earmarked for one parked gang."""

    gang_key: str
    priority: int
    opened_t: float
    expected_start: float
    #: every node the gang's assembly plan counts on — cleared by
    #: eviction/migration AND already-free nodes claimed virtually;
    #: other pods are filtered away from them until the hole closes
    nodes: set[str] = field(default_factory=set)
    #: pod uid -> active backfill lease on a hole node
    leases: dict[str, Lease] = field(default_factory=dict)
    #: last virtual time the gang was seen parked (hole-TTL clock)
    last_parked_t: float = 0.0


def _scratch_chips(info) -> ChipSet:
    """Copy of a NodeInfo's chip state for hypothetical evaluation
    (eviction feasibility, migration gain) — never the live object."""
    with info.lock:
        chips = [
            ChipResource(
                percent_free=c.percent_free,
                percent_total=c.percent_total,
                load=c.load,
                hbm_free_mib=c.hbm_free_mib,
                hbm_total_mib=c.hbm_total_mib,
            )
            for c in info.chips.chips
        ]
    return ChipSet(info.chips.torus, chips, key=info.chips.key)


def _whole_free(chips: ChipSet) -> int:
    return chips.whole_free()


def uniform_whole_host_total(totals, infos, allowed) -> int | None:
    """The shared fast-path eligibility rule: identical whole-chip
    demands on a fleet where every allowed node's capacity equals one
    demand — virtual placement then reduces to counting fully-free
    hosts. Returns the per-member total, or None (general packing
    required). ONE implementation serves the sim's strict-gang gate and
    the plane's clearing pass so the two can never drift."""
    if not totals or len(set(totals)) != 1:
        return None
    t = totals[0]
    if t < 100 or t % 100:
        return None
    for n in allowed:
        if len(infos[n].chips.chips) * 100 != t:
            return None
    return t


def demands_fit(infos, allowed, demands, rater) -> bool:
    """All-or-nothing virtual placement: can EVERY demand place at once
    on scratch copies of the live chip state, restricted to ``allowed``
    nodes? Joint by construction — each placement consumes scratch
    capacity the next one sees, so N whole-host demands need N hosts,
    never the same one N times. The whole-host fast path is a free-host
    count (O(hosts)); the general path runs the real packer over
    lazily-copied scratch state. Shared by the sim's strict admission
    gate and the recovery plane (docs/defrag.md)."""
    t = uniform_whole_host_total(
        [d.total for d in demands], infos, allowed
    )
    if t is not None:
        free_hosts = sum(
            1 for n in allowed
            if all(
                c.percent_free == c.percent_total
                for c in infos[n].chips.chips
            )
        )
        return free_hosts >= len(demands)
    scratch: dict[str, ChipSet] = {}
    for demand in demands:
        placed = False
        for name in allowed:
            s = scratch.get(name)
            if s is None:
                s = scratch[name] = _scratch_chips(infos[name])
            if not s.can_fit(demand):
                continue
            plan = rater.choose(s, demand)
            if plan is not None:
                s.allocate(plan)
                placed = True
                break
        if not placed:
            return False
    return True


class RecoveryPlane:
    """See module docstring. One instance per scheduler process; the
    driver (sim event loop or :class:`RecoveryLoop`) owns the cycle."""

    def __init__(self, dealer, controller=None, obs=None,
                 counters: RecoveryCounters | None = None,
                 config: RecoveryConfig | None = None,
                 clock=time.monotonic):
        self.dealer = dealer
        #: the coalescing-queue requeue hook (force=True — the repair
        #: path must never shed itself); None in harnesses that own
        #: their own requeue (the sim's pending list)
        self.controller = controller
        self.obs = obs
        self.counters = counters or RecoveryCounters()
        self.config = config or RecoveryConfig()
        self.clock = clock
        #: gang key -> Hole (read lock-free by filter_candidates)
        self.holes: dict[str, Hole] = {}
        #: uid -> drain Lease (docs/serving-loop.md): a scale-down
        #: victim finishing in-flight requests under a deadline; the
        #: lease sweep DELETES an overstayer (the replica is leaving the
        #: fleet — stripping + requeueing it would reschedule it)
        self.drains: dict[str, Lease] = {}

    # -- scheduling-path read hooks ---------------------------------------
    def filter_candidates(self, pod, node_names: list[str],
                          now: float | None = None) -> list[str]:
        """Candidates minus other gangs' hole nodes. A pod that
        qualifies for backfill (non-gang, strictly lower priority, a
        declared runtime that ends ``lease_grace_s`` before the hole's
        expected start) keeps the hole's nodes — the lease is granted if
        it actually binds there (:meth:`note_bound`). May return an
        empty list: a fleet fully earmarked for parked gangs is
        deliberately closed to everything that would refill it."""
        holes = self.holes
        if not holes:
            return node_names
        now = self.clock() if now is None else now
        gang = podutil.gang_of(pod)
        my_key = f"{pod.namespace}/{gang[0]}" if gang else None
        prio = podutil.priority_of(pod)
        runtime = (
            podutil.expected_runtime_s(pod) if gang is None else None
        )
        blocked: set[str] = set()
        for key in sorted(holes):
            hole = holes.get(key)
            if hole is None or key == my_key:
                continue
            if (
                self.config.backfill
                and runtime is not None
                and prio < hole.priority
                and now + runtime + self.config.lease_grace_s
                <= hole.expected_start
            ):
                continue  # backfill-eligible: the hole stays open to it
            blocked.update(hole.nodes)
        if not blocked:
            return node_names
        return [n for n in node_names if n not in blocked]

    def blocks(self, pod, node_names: list[str],
               now: float | None = None) -> set[str]:
        """The candidates hole protection withholds from this pod —
        empty for most pods most of the time (one truthiness check when
        no hole is open). The dealer's read verbs consult this so
        production Filter/Prioritize enforce reservations exactly the
        way the sim's driver-side filtering does."""
        if not self.holes:
            return set()
        allowed = self.filter_candidates(pod, node_names, now=now)
        if len(allowed) == len(node_names):
            return set()
        allowed_set = set(allowed)
        return {n for n in node_names if n not in allowed_set}

    def note_bound(self, pod, node: str,
                   now: float | None = None) -> str | None:
        """Record the lease when a bind landed inside a hole. Returns
        the gang key leased against (the bind was a backfill) or None
        (a normal bind, or the gang landing in its own hole)."""
        holes = self.holes
        if not holes:
            return None
        now = self.clock() if now is None else now
        gang = podutil.gang_of(pod)
        my_key = f"{pod.namespace}/{gang[0]}" if gang else None
        for key in sorted(holes):
            hole = holes.get(key)
            if hole is None or node not in hole.nodes:
                continue
            if key == my_key:
                return None  # the gang itself claiming its hole
            if pod.uid in hole.leases:
                # idempotent: the dealer's commit hook and a driver-side
                # caller (the sim) can both report the same bind — one
                # lease, one counter bump, one audit record
                return key
            runtime = podutil.expected_runtime_s(pod) or 0.0
            expires = min(
                now + runtime + self.config.lease_grace_s,
                hole.expected_start,
            )
            hole.leases[pod.uid] = Lease(
                uid=pod.uid, pod_name=pod.name, namespace=pod.namespace,
                node=node, expires_at=expires, gang_key=key,
            )
            self.counters.backfill_leases += 1
            self._ha_note("lease", uid=pod.uid, action="grant")
            self._audit(pod.uid, pod.key(), node, REASON_BACKFILLED)
            return key
        return None

    def note_drain(self, uid: str, pod_name: str, namespace: str,
                   node: str, expires_at: float) -> None:
        """Register a scale-down drain lease (docs/serving-loop.md): the
        replica autoscaler's victim keeps serving its in-flight requests
        until ``expires_at``; past it the lease sweep deletes the pod.
        Idempotent per uid (the autoscaler may re-report a drain)."""
        if uid in self.drains:
            return
        self.drains[uid] = Lease(
            uid=uid, pod_name=pod_name, namespace=namespace,
            node=node, expires_at=expires_at, gang_key="",
        )
        self.counters.drain_leases += 1
        self._ha_note("lease", uid=uid, action="grant")
        self._audit(uid, f"{namespace}/{pod_name}", node, REASON_DRAINING)

    def pod_gone(self, uid: str) -> None:
        """Departure/eviction cleanup: drop any lease the pod held."""
        for key in sorted(self.holes):
            hole = self.holes.get(key)
            if hole is not None:
                hole.leases.pop(uid, None)
        self.drains.pop(uid, None)

    def gang_bound(self, gang_key: str) -> None:
        """The gang fully bound: its hole (and remaining leases) close."""
        self._close_hole(gang_key)

    def gang_gone(self, gang_key: str) -> None:
        """The gang departed/was killed: nothing to hold capacity for."""
        self._close_hole(gang_key)

    def _close_hole(self, gang_key: str) -> None:
        if self.holes.pop(gang_key, None) is not None:
            self.counters.holes_closed += 1
            self._ha_note("hole", gang=gang_key, action="close")

    def _ha_note(self, kind: str, **data) -> None:
        """Mirror a hole/lease transition into the HA delta stream
        (docs/ha.md): earmarks are control-plane intent the standby
        tracks as bookkeeping — one attribute check when HA is off."""
        emit = getattr(self.dealer, "_ha_emit", None)
        if emit is not None:
            emit(kind, **data)

    def status(self) -> dict:
        """Live plane state for ``/debug/decisions`` and the sim report."""
        holes = sorted(self.holes)
        detail = {}
        for key in holes:
            hole = self.holes.get(key)
            if hole is None:
                continue
            detail[key] = {
                "priority": hole.priority,
                "nodes": sorted(hole.nodes),
                "expected_start": round(hole.expected_start, 6),
                "leases": len(hole.leases),
            }
        return {
            "holes": len(detail),
            "leases": sum(d["leases"] for d in detail.values()),
            "drains": len(self.drains),
            "gangs": detail,
            "counters": self.counters.snapshot(),
        }

    # -- the recovery cycle ------------------------------------------------
    def run_once(self, now: float | None = None,
                 parked: list | None = None) -> dict:
        """One recovery cycle: sweep expired leases, close stale holes,
        clear capacity for parked gangs (migrate first, then preempt,
        both budget-bounded), then spend leftover migration budget on a
        general defrag sweep. ``parked`` is the driver's view of pods
        awaiting placement (the sim's pending gang pods; production
        passes ``dealer.parked_gang_pods()``). Returns::

            {"evicted": [pod names whose placement was stripped],
             "actions": [(kind, detail), ...]}   # journal-ready, in order
        """
        now = self.clock() if now is None else now
        parked = parked or []
        self.counters.recovery_cycles += 1
        actions: list[tuple[str, str]] = []
        evicted: list[str] = []
        budgets = {
            "evict": self.config.eviction_budget,
            "migrate": self.config.migration_budget,
        }

        self._sweep_drains(now, actions)
        self._sweep_leases(now, actions, evicted)
        gangs = self._parked_by_gang(parked)
        self._sweep_holes(now, gangs, actions)

        infos = self.dealer.debug_snapshot()["node_infos"]
        by_node = self._tracked_by_node()
        for key in sorted(
            gangs, key=lambda k: (-gangs[k][0], k)
        ):
            prio, members = gangs[key]
            self._clear_gang(
                key, prio, members, now, infos, by_node, budgets,
                actions, evicted,
            )
        sweep = min(budgets["migrate"], self.config.sweep_budget)
        if sweep > 0:
            self._defrag_sweep(
                now, infos, by_node, {"migrate": sweep}, actions,
            )
        return {"evicted": evicted, "actions": actions}

    # -- cycle internals ---------------------------------------------------
    def _parked_by_gang(self, parked) -> dict[str, tuple[int, list]]:
        """gang key -> (priority, pods needing capacity). Parked pods
        already holding a dealer reservation (the strict-barrier
        production path) need no clearing themselves, but the gang may
        still be SHORT members the scheduler has not sent us — those are
        covered by clearing clones of a parked member's demand."""
        groups: dict[str, list] = {}
        for pod in parked:
            gang = podutil.gang_of(pod)
            if not gang or gang[1] <= 1:
                continue
            groups.setdefault(
                f"{pod.namespace}/{gang[0]}", []
            ).append(pod)
        out: dict[str, tuple[int, list]] = {}
        for key in sorted(groups):
            members = sorted(groups[key], key=lambda p: p.name)
            prio = max(podutil.priority_of(p) for p in members)
            reserved = [
                p for p in members
                if self.dealer.has_reservation(p.uid)
            ]
            needing = [
                p for p in members
                if not self.dealer.has_reservation(p.uid)
            ]
            size = max(podutil.gang_of(p)[1] for p in members)
            short = (
                size - self.dealer.gangs.bound_count(key) - len(reserved)
            )
            # members kube-scheduler has not even attempted yet: clear
            # capacity for clones of the first parked member's demand
            for _ in range(max(short - len(needing), 0)):
                needing.append(members[0])
            if needing:
                out[key] = (prio, needing)
        return out

    def _tracked_by_node(self) -> dict[str, list]:
        by_node: dict[str, list] = {}
        for pod in sorted(self.dealer.tracked_pods(),
                          key=lambda p: p.name):
            if pod.node_name:
                by_node.setdefault(pod.node_name, []).append(pod)
        return by_node

    def _sweep_drains(self, now: float, actions) -> None:
        """Enforce scale-down drain deadlines: a draining replica still
        tracked past its lease expiry is DELETED through the resilient
        client (not stripped-and-requeued — it is leaving the fleet).
        A failed delete keeps the lease so the next cycle retries, the
        same nothing-changed contract as ``_evict``."""
        for uid in sorted(self.drains):
            lease = self.drains[uid]
            if not self.dealer.tracks(uid):
                self.drains.pop(uid, None)  # drained/deleted on its own
                continue
            if now < lease.expires_at:
                continue
            client = self.dealer.client
            try:
                fresh = client.get_pod(lease.namespace, lease.pod_name)
            except NotFoundError:
                self.drains.pop(uid, None)  # already gone
                continue
            except Exception as e:
                # transient read failure (brownout, timeout): KEEP the
                # lease and retry next cycle — dropping it here would
                # silently cancel the deadline on a replica that may be
                # wedged (same nothing-changed contract as _sweep_leases)
                log.warning("drain-lease probe of %s/%s failed: %s",
                            lease.namespace, lease.pod_name, e)
                continue
            if fresh.uid != uid:
                self.drains.pop(uid, None)  # name reused
                continue
            try:
                client.delete_pod(lease.namespace, lease.pod_name)
            except Exception as e:
                log.warning("drain-lease delete of %s/%s failed: %s",
                            lease.namespace, lease.pod_name, e)
                continue
            self.counters.drain_lease_expiries += 1
            self._ha_note("lease", uid=uid, action="expire")
            self._audit(
                uid, f"{lease.namespace}/{lease.pod_name}", lease.node,
                REASON_DRAIN_EXPIRED,
            )
            actions.append((
                "drain-expire", f"{lease.pod_name} @ {lease.node}",
            ))
            self.drains.pop(uid, None)

    def _sweep_leases(self, now: float, actions, evicted) -> None:
        for key in sorted(self.holes):
            hole = self.holes.get(key)
            if hole is None:
                continue
            for uid in sorted(hole.leases):
                lease = hole.leases[uid]
                if not self.dealer.tracks(uid):
                    hole.leases.pop(uid, None)  # departed on its own
                    continue
                if now < lease.expires_at:
                    continue
                # the gang's start is due and the pod overstayed its
                # declared runtime: evict (lease contract, docs/defrag.md)
                if self._evict(
                    lease.namespace, lease.pod_name, uid,
                    REASON_LEASE_EXPIRED,
                ):
                    self.counters.backfill_lease_expiries += 1
                    self._ha_note("lease", uid=uid, action="expire")
                    evicted.append(lease.pod_name)
                    actions.append((
                        "lease-expire",
                        f"{lease.pod_name} @ {lease.node} for {key}",
                    ))
                    hole.leases.pop(uid, None)
                elif not self.dealer.tracks(uid):
                    hole.leases.pop(uid, None)  # gone between checks
                # else: transient strip failure (brownout) — the lease
                # stays so the next cycle retries the eviction, matching
                # _evict's "nothing changed" contract

    def _sweep_holes(self, now: float, gangs, actions) -> None:
        for key in sorted(self.holes):
            hole = self.holes.get(key)
            if hole is None:
                continue
            if key in gangs:
                hole.last_parked_t = now
                continue
            if now - hole.last_parked_t >= self.config.hole_ttl_s:
                self._close_hole(key)
                actions.append(("hole-close", f"{key} ttl"))

    def _hole_for(self, gang_key: str, priority: int,
                  now: float, actions) -> Hole:
        hole = self.holes.get(gang_key)
        if hole is None:
            hole = self.holes[gang_key] = Hole(
                gang_key=gang_key, priority=priority, opened_t=now,
                expected_start=now + self.config.gang_start_horizon_s,
                last_parked_t=now,
            )
            self.counters.holes_opened += 1
            self._ha_note("hole", gang=gang_key, action="open")
            actions.append(("hole-open", gang_key))
        return hole

    def _clear_gang(self, gang_key: str, prio: int, members: list,
                    now: float, infos, by_node, budgets, actions,
                    evicted) -> None:
        """Assemble capacity for every member a parked gang still needs.

        Joint feasibility is the point: the members are placed VIRTUALLY
        against per-cycle scratch chip states (one copy per touched
        node), so sixteen members needing sixteen whole hosts reserve
        sixteen — a real-state check would let every member point at the
        same free host and clear one node per cycle. For each member
        that cannot place even virtually, the cheapest
        eviction/migration set (least displaced percent, fewest victims,
        name) clears one node: short-declared victims get leases (lazy
        preemption), movable ones migrate (budgeted), the rest evict
        (budgeted), and the node is earmarked into the gang's hole
        BEFORE the evictions land so churn cannot refill it mid-clear."""
        all_names = sorted(infos)
        scratch: dict[str, ChipSet] = {}

        def sc(name: str) -> ChipSet:
            if name not in scratch:
                s = scratch[name] = _scratch_chips(infos[name])
                # a my-hole node whose only blockers are MY leased
                # incumbents is promised capacity: their leases end
                # before the gang's expected start, so virtual planning
                # treats them as already gone (the REAL gate still waits
                # for their departure/expiry — timing stays honest)
                hole = self.holes.get(gang_key)
                if hole is not None and name in hole.nodes:
                    for p in by_node.get(name, []):
                        if p.uid in hole.leases:
                            lp = plan_from_pod(p)
                            if lp is not None:
                                try:
                                    s.release(lp)
                                except ValueError:
                                    pass  # stale bookkeeping: keep real
            return scratch[name]

        from nanotpu.dealer.dealer import plan_from_pod

        rater = self.dealer.rater
        other_hole_nodes: set[str] = set()
        for key in sorted(self.holes):
            hole = self.holes.get(key)
            if hole is not None and key != gang_key:
                other_hole_nodes.update(hole.nodes)
        leased = self._leased_uids()
        #: nodes carrying VIRTUAL member placements this cycle: migration
        #: targets must avoid them — the scratch and the real state would
        #: otherwise diverge about the same chips (a real migration
        #: landing where a virtual member sits would double-book the
        #: cycle's own planning)
        virtual_nodes: set[str] = set()
        # one gang's members share annotations, so one candidate filter
        # serves them all
        allowed = (
            self.filter_candidates(members[0], all_names, now=now)
            if members else []
        )
        # whole-host fast path (the training-gang shape): identical
        # whole-chip members on a uniform fleet fit exactly on
        # fully-free hosts, so virtual placement is a pop from one
        # precomputed pool — O(hosts) once — instead of O(members x
        # hosts) trial packings per cycle
        free_pool: list[str] | None = None
        if uniform_whole_host_total(
            [Demand.from_pod(p).total for p in members], infos, allowed,
        ) is not None:
            free_pool = [
                n for n in allowed
                if n not in scratch and all(
                    c.percent_free == c.percent_total
                    for c in infos[n].chips.chips
                )
            ]
            free_pool.reverse()  # .pop() consumes in name order
        for pod in members:
            demand = Demand.from_pod(pod)
            if not demand.is_valid():
                continue
            placed = None
            if free_pool is not None:
                while free_pool and placed is None:
                    name = free_pool.pop()
                    s = sc(name)
                    plan = rater.choose(s, demand)
                    if plan is not None:
                        s.allocate(plan)
                        virtual_nodes.add(name)
                        placed = name
            else:
                for name in allowed:
                    s = sc(name)
                    if not s.can_fit(demand):
                        continue
                    plan = rater.choose(s, demand)
                    if plan is not None:
                        s.allocate(plan)
                        virtual_nodes.add(name)
                        placed = name
                        break
            if placed is not None:
                # EVERY node the gang's assembly plan counts on is
                # earmarked — not just the ones evictions cleared. An
                # unearmarked free node would be eaten by the arrival
                # stream (or by the very pods preemption just requeued)
                # before the gang's next gate check, and the plane would
                # clear another node for the same member next cycle,
                # forever: eviction thrash with a budget-sized leak per
                # cycle. Reservation must cover the whole plan.
                self._hole_for(
                    gang_key, prio, now, actions
                ).nodes.add(placed)
                continue
            if budgets["evict"] <= 0 and budgets["migrate"] <= 0:
                self.counters.eviction_budget_hits += 1
                return
            best = None  # (displaced, n_victims, node) + victims
            # cheap pre-rank, full planning capped: the nearly-free
            # nodes are where cheap eviction sets live, so rank every
            # candidate by used percent (O(hosts) attribute sums) and
            # run the real packer-backed planning only on the cheapest
            # few — a 1024-host fleet must not pay 1024 trial packings
            # per unplaced member
            ranked = sorted(
                (
                    (
                        scratch[name].percent_used()
                        if name in scratch
                        else infos[name].chips.percent_used(),
                        name,
                    )
                    for name in all_names
                    if name not in other_hole_nodes
                ),
            )[:48]
            for _used, name in ranked:
                plan = self._eviction_plan(
                    sc(name), by_node.get(name, []), demand, prio,
                    leased,
                )
                if plan is None:
                    continue
                victims, displaced = plan
                # least displaced WORK first (a handful of fractional
                # pods costs the fleet far less than one evicted 4-chip
                # replica idling through a requeue), then fewest victims
                cost = (displaced, len(victims), name)
                if best is None or cost < best[0]:
                    best = (cost, victims)
            if best is None:
                self.counters.preempt_infeasible += 1
                continue
            (_, _, node), victims = best
            hole = self._hole_for(gang_key, prio, now, actions)
            hole.nodes.add(node)
            cleared = True
            for victim in victims:
                vplan = plan_from_pod(victim)
                gone = False
                declared = podutil.expected_runtime_s(victim)
                if (
                    self.config.backfill
                    and declared is not None
                    and now + declared + self.config.lease_grace_s
                    <= hole.expected_start
                ):
                    # LAZY preemption: a short incumbent whose declared
                    # runtime ends before the gang's expected start is
                    # left RUNNING under a lease instead of evicted —
                    # zero displaced work, and the hole's capacity is
                    # busy instead of idle while the gang assembles (the
                    # exact waste backfill exists to recoup). The lease
                    # sweep evicts it at expiry if it overstays.
                    hole.leases[victim.uid] = Lease(
                        uid=victim.uid, pod_name=victim.name,
                        namespace=victim.namespace, node=node,
                        expires_at=min(
                            now + declared + self.config.lease_grace_s,
                            hole.expected_start,
                        ),
                        gang_key=gang_key,
                    )
                    self.counters.backfill_leases += 1
                    self._ha_note("lease", uid=victim.uid, action="grant")
                    self._audit(
                        victim.uid, victim.key(), node, REASON_BACKFILLED,
                    )
                    actions.append((
                        "lease",
                        f"{victim.name} @ {node} for {gang_key}",
                    ))
                    gone = True
                if not gone and budgets["migrate"] > 0:
                    target = self._migration_target(
                        victim, node, infos,
                        other_hole_nodes | hole.nodes | virtual_nodes,
                        require_gain=False,
                    )
                    if target is not None:
                        moved_pod = self._migrate(victim, target, actions)
                        if moved_pod is not None:
                            budgets["migrate"] -= 1
                            gone = True
                            # keep cycle bookkeeping coherent with the
                            # REWRITTEN pod: the target's scratch and
                            # resident list must reflect the migrated-in
                            # placement, or a later member's eviction
                            # plan releases chips that were never there
                            by_node.setdefault(target, []).append(
                                moved_pod
                            )
                            if target in scratch:
                                tplan = plan_from_pod(moved_pod)
                                if tplan is not None:
                                    scratch[target].allocate(tplan)
                if not gone:
                    if budgets["evict"] <= 0:
                        self.counters.eviction_budget_hits += 1
                        cleared = False
                        break
                    if self._evict(
                        victim.namespace, victim.name, victim.uid,
                        REASON_PREEMPTED,
                    ):
                        budgets["evict"] -= 1
                        self.counters.preempted_pods += 1
                        evicted.append(victim.name)
                        actions.append((
                            "preempt",
                            f"{victim.name} @ {node} for {gang_key}",
                        ))
                        gone = True
                    else:
                        cleared = False
                if gone:
                    if vplan is not None:
                        sc(node).release(vplan)
                    by_node[node] = [
                        p for p in by_node.get(node, [])
                        if p.uid != victim.uid
                    ]
            if cleared:
                plan = rater.choose(sc(node), demand)
                if plan is not None:
                    sc(node).allocate(plan)
                    virtual_nodes.add(node)
            # budgets may be spent now; the NEXT member's top-of-loop
            # check accounts the hit (a spent budget with no member left
            # to serve is not a hit)

    def _eviction_plan(self, chips: ChipSet, residents, demand: Demand,
                       prio: int, leased: set[str]):
        """(victims, displaced percent) making ``demand`` fit on the
        node by removing strictly-lower-priority non-gang pods — or
        None. ``chips`` is the caller's scratch state (virtual member
        placements included); the trial runs on a private copy. Leased
        backfill pods are never planned victims — the lease sweep is
        their only evictor (the lease contract), and their hole node is
        already earmarked anyway. Feasibility is judged by the REAL
        rater, so a plan the packer would refuse never evicts anyone."""
        from nanotpu.dealer.dealer import plan_from_pod

        candidates = []
        for p in residents:
            if podutil.gang_of(p):
                continue  # never break another gang
            if p.uid in leased:
                continue
            if podutil.priority_of(p) >= prio:
                continue
            vplan = plan_from_pod(p)
            if vplan is None:
                continue
            candidates.append((
                podutil.priority_of(p), Demand.from_pod(p).total,
                p.name, p, vplan,
            ))
        trial = ChipSet(
            chips.torus,
            [
                ChipResource(
                    percent_free=c.percent_free,
                    percent_total=c.percent_total,
                    load=c.load,
                    hbm_free_mib=c.hbm_free_mib,
                    hbm_total_mib=c.hbm_total_mib,
                )
                for c in chips.chips
            ],
            key=chips.key,
        )
        if self.dealer.rater.choose(trial, demand) is not None:
            return [], 0  # already fits: nothing to clear
        if not candidates:
            return None
        # cheapest first: lowest priority, least displaced work, name
        candidates.sort(key=lambda c: (c[0], c[1], c[2]))
        victims, displaced = [], 0
        for _vprio, total, _name, p, vplan in candidates:
            trial.release(vplan)
            victims.append(p)
            displaced += total
            if self.dealer.rater.choose(trial, demand) is not None:
                return victims, displaced
        return None

    def _leased_uids(self) -> set[str]:
        out: set[str] = set()
        for key in sorted(self.holes):
            hole = self.holes.get(key)
            if hole is not None:
                out.update(hole.leases)
        # draining replicas are leaving the fleet: migrating one would
        # replay a placement that is about to be deleted
        out.update(self.drains)
        return out

    def _migration_target(self, pod, source: str, infos,
                          excluded: set[str],
                          require_gain: bool = True) -> str | None:
        """Best node to absorb ``pod`` off ``source``: ranked by the
        native scoring path (``top_candidates`` — the rater's own
        packing preference, the Q16 fixed-point engine under the
        throughput rater), then gated by the monotone whole-free rule.

        ``require_gain=True`` (the defrag sweep): accept only when the
        fleet's whole-free chip count strictly improves — source gain
        from losing the pod must exceed target loss from absorbing it.
        That strict inequality is what makes migration ping-pong
        impossible: every accepted move increases a bounded integer.
        ``require_gain=False`` (clearing a node for a gang, where the
        source WILL be fully freed regardless): any feasible non-hole
        target qualifies — keeping the victim BOUND through the clear is
        worth more than its placement quality (an eviction would idle
        its chips through a requeue) — but targets are still tried
        cheapest-loss first, so the blockage prefers existing
        fragmentation over fresh whole chips.

        The native batch engine answers WHICH nodes are feasible
        (``top_candidates`` — one memoized crossing, the Q16 fixed-point
        path under the throughput rater); the defrag COST model then
        orders those targets itself — most-used first, then name — so
        consolidation packs regardless of the placement policy's own
        preference (a spread fleet must still defrag toward packing).
        Scratch trials are capped so a huge fleet never pays more than a
        bounded number of hypothetical packings per move."""
        demand = Demand.from_pod(pod)
        src_info = infos.get(source)
        if src_info is None:
            return None
        from nanotpu.dealer.dealer import plan_from_pod

        vplan = plan_from_pod(pod)
        if vplan is None:
            return None
        gain = 0
        if require_gain:
            src_scratch = _scratch_chips(src_info)
            before = _whole_free(src_scratch)
            src_scratch.release(vplan)
            gain = _whole_free(src_scratch) - before
            if gain <= 0:
                # loss is never negative, so gain > loss cannot hold:
                # skip the full-fleet scoring pass and the scratch
                # trials outright (most shared-chip fractional pods land
                # here every sweep cycle)
                return None
        feasible = self.dealer.top_candidates(
            sorted(infos), pod, k=None
        )
        order = []
        for name, _score in feasible:
            if name == source or name in excluded:
                continue
            info = infos.get(name)
            if info is None:
                continue
            order.append((-info.chips.usage(), name, info))
        order.sort(key=lambda e: (e[0], e[1]))
        best = None  # (loss, rank, name) — clear path keeps the cheapest
        for rank, (_neg_usage, name, info) in enumerate(order[:32]):
            scratch = _scratch_chips(info)
            before_t = _whole_free(scratch)
            tplan = self.dealer.rater.choose(scratch, demand)
            if tplan is None:
                continue
            scratch.allocate(tplan)
            loss = before_t - _whole_free(scratch)
            if require_gain:
                if gain > loss:
                    return name
                continue
            if loss == 0:
                return name  # absorbs into existing fragmentation
            if best is None or (loss, rank) < best[:2]:
                best = (loss, rank, name)
        return best[2] if best is not None else None

    def _migrate(self, pod, target: str, actions):
        """Execute one migration; returns the REWRITTEN pod (new
        annotations + nodeName — callers must book-keep with it, never
        with the stale source-side object) or None on failure."""
        from nanotpu.dealer.dealer import BindError

        source = pod.node_name
        try:
            moved = self.dealer.migrate(pod, target)
        except BindError as e:
            self.counters.migration_failures += 1
            log.warning(
                "migration of %s to %s failed: %s", pod.key(), target, e,
            )
            return None
        self.counters.migrated_pods += 1
        self._audit(pod.uid, pod.key(), target, REASON_MIGRATED)
        actions.append((
            "migrate", f"{pod.name} {source}->{target}",
        ))
        return moved

    def _defrag_sweep(self, now: float, infos, by_node, budgets,
                      actions) -> None:
        """Spend the sweep budget consolidating fractional
        pods: sources ascending by the fractional load pinning them (the
        cheapest nodes to fully free first), each move gated by the same
        strict whole-free improvement rule."""
        hole_nodes: set[str] = set()
        for key in sorted(self.holes):
            hole = self.holes.get(key)
            if hole is not None:
                hole_nodes.update(hole.nodes)
        sources = []
        for name in sorted(infos):
            if name in hole_nodes:
                continue
            movable = [
                p for p in by_node.get(name, [])
                if not podutil.gang_of(p)
                and Demand.from_pod(p).total < 100
            ]
            if not movable:
                continue
            load = sum(Demand.from_pod(p).total for p in movable)
            sources.append((load, name, movable))
        sources.sort()
        for _load, name, movable in sources:
            for pod in movable:
                if budgets["migrate"] <= 0:
                    self.counters.migration_budget_hits += 1
                    return
                target = self._migration_target(
                    pod, name, infos, hole_nodes | {name},
                )
                if target is None:
                    continue
                moved_pod = self._migrate(pod, target, actions)
                if moved_pod is not None:
                    budgets["migrate"] -= 1
                    by_node.setdefault(target, []).append(moved_pod)
                    by_node[name] = [
                        p for p in by_node.get(name, [])
                        if p.uid != pod.uid
                    ]

    # -- execution helpers ---------------------------------------------------
    def _evict(self, namespace: str, name: str, uid: str,
               reason: str) -> bool:
        """Preempt-and-requeue one pod: strip placement (annotations +
        label + nodeName) through the resilient write path, roll chips
        back via ``Dealer.forget``, requeue the sync via the coalescing
        queue with force=True. A failed strip leaves everything exactly
        as it was (the next cycle retries)."""
        client = self.dealer.client
        try:
            fresh = client.get_pod(namespace, name)
        except Exception:
            return False  # already gone
        if fresh.uid != uid:
            return False  # name reused by a different incarnation
        stripped = podutil.strip_placement(fresh, clear_node=True)
        try:
            client.update_pod(stripped)
        except Exception as e:
            log.warning("preemption strip of %s/%s failed: %s",
                        namespace, name, e)
            return False
        self.dealer.forget(fresh)
        self.pod_gone(uid)
        if self.controller is not None:
            self.controller.requeue(fresh)
        self._audit(uid, fresh.key(), fresh.node_name or "", reason)
        return True

    def _audit(self, uid: str, pod_key: str, node: str,
               reason: str) -> None:
        """Close an audit cycle with the typed recovery reason — gated
        on the pod's sticky sampling verdict exactly like the TTL
        sweeper's expiry records (a mass preemption must not evict the
        sampled pods' complete cycles from the bounded ring)."""
        if self.obs is not None and self.obs.tracer.sampled(uid):
            self.obs.ledger.bind_outcome(
                uid, node, reason, False, pod=pod_key, final=True,
            )


class RecoveryLoop:
    """Production driver: a daemon thread running
    ``plane.run_once(clock(), dealer.parked_gang_pods())`` every
    ``period_s``. The sim never uses this — it steps the plane
    deterministically through its own ``recovery_cycle`` events."""

    def __init__(self, plane: RecoveryPlane, period_s: float = 2.0,
                 gate=None):
        self.plane = plane
        self.period_s = period_s
        #: optional write gate (docs/ha.md "Degraded mode"): a callable
        #: answering False pauses cycles — every recovery action is an
        #: apiserver write, and spending the cycle budget on a dead
        #: apiserver starves the heal. None == always run.
        self.gate = gate
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        """Idempotent AND restart-safe: a live loop is left alone, a
        stopped one restarts (an HA promotion stops the standby-side
        loops and restarts them against the promoted dealer — the old
        guard latched `_thread` forever, so the restart silently
        no-opped; pinned by the promote-under-load test)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="recovery",
        )
        self._thread.start()

    def stop(self) -> None:
        """Idempotent; joins so teardown ordering is safe (the caller
        may close the dealer right after — a cycle still in flight must
        not race the closed pools). Safe from the loop's own thread."""
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                if self.gate is not None and not self.gate():
                    continue  # degraded: skip the cycle, stay alive
                self.plane.run_once(
                    self.plane.clock(),
                    self.plane.dealer.parked_gang_pods(),
                )
            except Exception:  # the loop must outlive any one cycle
                log.exception("recovery cycle failed")
