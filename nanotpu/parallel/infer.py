"""Sharded inference: tp / fsdp-gathered decode placement.

The north-star serving workloads (BASELINE.json: Llama-3-8B, Mixtral 8x7B)
do not fit one chip — 8B bf16 weights are ~16 GB against a 16 GB v5e — so
decode must run over a mesh. This module is the placement layer the decode
paths (:mod:`nanotpu.models.generate`, :mod:`nanotpu.serving.engine`) share:

* **params** reuse the training PartitionSpecs (tp over heads/ffn/vocab,
  fsdp over the other matmul axis — :func:`nanotpu.parallel.mesh
  .llama_param_specs`); an fsdp>1 inference mesh is the ZeRO-style
  "fsdp-gathered" decode where XLA all-gathers each layer's weights on use.
  int8 ``QArray`` weights place their per-output-channel scales with the
  contraction axis of the spec dropped (the scale's size-1 axis cannot
  shard).
* **KV caches** shard the ``n_kv_heads`` axis over tp — the cache is the
  decode-time HBM bottleneck, and the head axis is the one attention never
  reduces over, so each tp shard attends its own heads with zero cache
  collectives. Batch/slot and position axes stay unsharded (slots admit and
  evict one row at a time; a sharded slot axis would turn every admission
  into a cross-device scatter).
* single-chip is the mesh=None special case everywhere — callers that never
  pass a mesh get exactly the round-2 behavior.

The reference has no model/serving code at all (SURVEY §2 "absent in
reference": it schedules pods, pkg/dealer/dealer.go); this layer exists for
the capability bar, not reference parity.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nanotpu.parallel.mesh import (
    check_divisibility,
    check_moe_divisibility,
    llama_param_specs,
    mixtral_param_specs,
    qarray_scale_spec,
)


def infer_param_specs(cfg):
    """PartitionSpec tree for an inference param tree: the training specs
    (tp x fsdp) apply unchanged — MoE configs (anything with ``n_experts``)
    get the expert-sharded variant."""
    if hasattr(cfg, "n_experts"):
        return mixtral_param_specs(cfg)
    return llama_param_specs(cfg)


def check_infer_divisibility(cfg, mesh: Mesh) -> None:
    if hasattr(cfg, "n_experts"):
        check_moe_divisibility(cfg, mesh)
    else:
        check_divisibility(cfg, mesh)


def place_params(params, cfg, mesh: Mesh):
    """device_put a (possibly int8-quantized) param tree onto the mesh.

    QArray leaves are placed member-wise: ``q`` under the weight's spec,
    ``s`` under the spec minus its contraction axis."""
    from nanotpu.models.quant import QArray

    check_infer_divisibility(cfg, mesh)
    specs = infer_param_specs(cfg)

    def place(leaf, spec):
        if isinstance(leaf, QArray):
            return QArray(
                q=jax.device_put(leaf.q, NamedSharding(mesh, spec)),
                s=jax.device_put(
                    leaf.s,
                    NamedSharding(mesh, qarray_scale_spec(spec, leaf.q.ndim)),
                ),
            )
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(
        place, params, specs,
        is_leaf=lambda x: isinstance(x, QArray),
    )


#: Per-layer cache entry [B|SLOTS, max_len, n_kv_heads, head_dim]: kv heads
#: over tp, everything else unsharded (see module docstring).
KV_ENTRY_SPEC = P(None, None, "tp", None)
#: int8 scale planes [B|SLOTS, max_len, n_kv_heads].
KV_SCALE_SPEC = P(None, None, "tp")


def kv_cache_specs(cfg) -> "object":
    """Spec tree matching :class:`nanotpu.models.generate.KVCache`."""
    from nanotpu.models.generate import KVCache

    n = cfg.n_layers
    return KVCache(
        k=tuple(KV_ENTRY_SPEC for _ in range(n)),
        v=tuple(KV_ENTRY_SPEC for _ in range(n)),
        length=P(),
    )


def slot_cache_specs(cfg, kv_int8: bool = False) -> "object":
    """Spec tree matching SlotCache / SlotCache8 (serving engine)."""
    from nanotpu.serving.engine import SlotCache, SlotCache8

    n = cfg.n_layers
    ent = tuple(KV_ENTRY_SPEC for _ in range(n))
    if kv_int8:
        sc = tuple(KV_SCALE_SPEC for _ in range(n))
        return SlotCache8(k=ent, v=ent, k_scale=sc, v_scale=sc, lengths=P())
    return SlotCache(k=ent, v=ent, lengths=P())


class _CfgView:
    def __init__(self, n_layers: int):
        self.n_layers = n_layers


def _cache_specs_of(cache):
    """Spec tree for any of the three cache flavors, by inspection."""
    from nanotpu.serving.engine import SlotCache8

    cfg_like = _CfgView(n_layers=len(cache.k))
    if hasattr(cache, "lengths"):
        return slot_cache_specs(cfg_like, kv_int8=isinstance(cache, SlotCache8))
    return kv_cache_specs(cfg_like)


def _apply_cache(cache, mesh: Mesh, op):
    return jax.tree_util.tree_map(
        lambda leaf, spec: op(leaf, NamedSharding(mesh, spec)),
        cache, _cache_specs_of(cache),
    )


def place_cache(cache, mesh: Mesh):
    """device_put any of the three cache flavors onto the mesh."""
    return _apply_cache(cache, mesh, jax.device_put)


def constrain_cache(cache, mesh: Mesh):
    """with_sharding_constraint for a cache built INSIDE a jitted function
    (prefill creates its cache from zeros; without the pin XLA's propagation
    chooses, usually correctly but not deterministically)."""
    return _apply_cache(cache, mesh, jax.lax.with_sharding_constraint)
