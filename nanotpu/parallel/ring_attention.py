"""Ring attention: exact causal attention with the sequence sharded over the
``sp`` mesh axis.

Each device holds a contiguous sequence shard of q/k/v. K/V blocks rotate
around the ring via ``lax.ppermute`` (one ICI hop per step) while every
device accumulates its queries' attention over each visiting block with
online-softmax (log-sum-exp) merging — the sequence-parallel analogue of
flash attention's k-loop. Memory per device stays O(S/sp · d); the full
[S, S] score matrix never exists anywhere.

Causality works on block indices: a k/v block that started on ring rank
``src`` covers global positions [src·Sblk, (src+1)·Sblk); my queries at rank
``r`` attend fully to blocks with src < r, causally within src == r, and not
at all to src > r.

The inner block attend is the Pallas flash kernel by default (r5): each
visiting block runs :func:`nanotpu.ops.attention.flash_attention_lse` —
no [Sblk, Sblk] logits transient per block — selected per step by
``lax.switch`` on the block's origin (full / causal-diagonal / skipped).
Measured on a v5e at B=1 H=16 hd=64: 1.31x over the dense einsum at
Sblk=2048 and 1.74x at Sblk=4096 (fwd+bwd), with XLA temp bytes for the
step dropping 1042 MiB -> 42 MiB at Sblk=4096 (BASELINE.md).

Designed for use inside ``shard_map`` (see :func:`ring_attention_sharded`).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, scale, mask):
    """Partial attention of q against one k/v block.

    q [B,Sq,H,D]; k/v [B,Sk,KV,D] with KV | H (GQA: k/v stay UNexpanded —
    the ring rotates the small KV blocks, H/KV× less ICI traffic per hop —
    and the grouped einsums below broadcast them across each kv head's
    query group); mask [Sq,Sk] bool or None.
    Returns (m [B,H,Sq,1], l, acc [B,Sq,H,D]) for LSE merging.
    """
    B, Sq, H, D = q.shape
    Sk, KV = k.shape[1], k.shape[2]
    G = H // KV  # query heads per kv head; 1 for MHA
    qg = q.reshape(B, Sq, KV, G, D)
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qg, k).astype(jnp.float32) * scale
    logits = logits.reshape(B, H, Sq, Sk)
    if mask is not None:
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)  # [B,H,Sq,1]
    m_safe = jnp.where(m == NEG_INF, 0.0, m)
    p = jnp.exp(jnp.where(logits == NEG_INF, NEG_INF, logits - m_safe))
    l = jnp.sum(p, axis=-1, keepdims=True)
    pg = p.astype(q.dtype).reshape(B, KV, G, Sq, Sk)
    acc = (
        jnp.einsum("bkgqs,bskd->bqkgd", pg, v)
        .reshape(B, Sq, H, D)
        .astype(jnp.float32)
    )
    return m, l, acc


def _dense_block_lse(q, k, v, scale, mask):
    """Dense single-block attend returning the (out, lse) merge state —
    the XLA reference the flash path is grad-matched against (and the
    fallback used when Pallas is unavailable and mask shapes are
    irregular). out [B,Sq,H,D] f32, lse [B,H,Sq] f32."""
    m, l, acc = _block_attend(q, k, v, scale, mask)
    m_safe = jnp.where(m == NEG_INF, 0.0, m)
    lse = jnp.where(
        l[..., 0] > 0.0,
        m_safe[..., 0] + jnp.log(jnp.maximum(l[..., 0], 1e-30)),
        NEG_INF,
    )
    l_t = jnp.transpose(l, (0, 2, 1, 3))  # [B,Sq,H,1]
    return acc / jnp.maximum(l_t, 1e-30), lse


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    axis_name: str = "sp", causal: bool = True,
    impl: str = "flash", interpret: bool | None = None,
) -> jax.Array:
    """Per-shard q [B, Sblk, H, D], k/v [B, Sblk, KV, D] (KV | H; GQA kv
    blocks ride the ring unexpanded) -> per-shard out [B, Sblk, H, D].
    Call inside shard_map with the sequence dim sharded over ``axis_name``.

    ``impl="flash"`` (default) runs each visiting block's attend as the
    Pallas flash kernel (VERDICT r4 missing #2: the dense inner attend
    materialized a [Sblk, Sblk] f32 logits transient per visiting block —
    ~1 GiB at Sblk=4096 — on the slowest attention in the repo, in
    exactly the long-context regime ring attention owns). Three cases
    selected per step by ``lax.switch`` on the block's origin rank:
    past blocks -> full (non-causal) flash, the self block -> causal
    flash, future blocks -> skipped outright (the dense path burned full
    attend FLOPs on them and masked the result). Each block returns the
    (out, lse) merge state via :func:`flash_attention_lse`; the LSE merge
    is unchanged math, so grads flow through the merge weights (the lse
    cotangent folds into the kernel backward's D vector).
    ``impl="dense"`` keeps the original einsum path (the grad-match
    reference). On non-TPU backends the flash path transparently uses
    the dense-XLA (out, lse) fallback inside flash_attention_lse unless
    ``interpret=True`` forces the kernels in interpreter mode."""
    if impl not in ("flash", "dense"):
        raise ValueError(f"unknown ring attention impl: {impl!r}")
    B, Sblk, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    n = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)

    perm = [(i, (i + 1) % n) for i in range(n)]  # send k/v to the next rank

    from nanotpu.ops.attention import flash_attention_lse

    def attend(k_cur, v_cur, src):
        """(out, lse) of q against one visiting block."""
        if impl == "dense":
            if causal:
                causal_mask = jnp.tril(jnp.ones((Sblk, Sblk), jnp.bool_))
                mask = (src < rank) | ((src == rank) & causal_mask)
            else:
                mask = None
            return _dense_block_lse(q, k_cur, v_cur, scale, mask)
        if not causal:
            out, lse = flash_attention_lse(
                q, k_cur, v_cur, False, interpret=interpret
            )
            return out.astype(jnp.float32), lse
        branches = [
            # src < rank: the whole past block is visible
            lambda k_, v_: flash_attention_lse(
                q, k_, v_, False, interpret=interpret
            ),
            # src == rank: causal within the self block
            lambda k_, v_: flash_attention_lse(
                q, k_, v_, True, interpret=interpret
            ),
            # src > rank: nothing visible — zero mass, and zero FLOPs.
            # Zeros derived from q so they carry the same varying manual
            # axes as the real branches' outputs (a plain jnp.zeros is
            # axis-invariant and lax.switch rejects the type mismatch).
            lambda k_, v_: (
                q * 0,
                jnp.transpose(q, (0, 2, 1, 3))[..., 0].astype(jnp.float32)
                * 0 + NEG_INF,
            ),
        ]
        case = jnp.where(src < rank, 0, jnp.where(src == rank, 1, 2))
        out, lse = jax.lax.switch(case, branches, k_cur, v_cur)
        return out.astype(jnp.float32), lse

    def step(carry, step_idx):
        k_cur, v_cur, o_run, lse_run = carry
        # the block on my device at step s originated at rank (rank - s) mod n
        src = (rank - step_idx) % n
        o_blk, lse_blk = attend(k_cur, v_cur, src)
        # LSE merge of two normalized partial attentions
        lse_new = jnp.logaddexp(lse_run, lse_blk)
        c_run = jnp.where(
            lse_run == NEG_INF, 0.0, jnp.exp(lse_run - lse_new)
        )
        c_blk = jnp.where(
            lse_blk == NEG_INF, 0.0, jnp.exp(lse_blk - lse_new)
        )
        # correction factors are [B,H,Sq]; out is [B,Sq,H,D]
        o_new = (
            o_run * jnp.transpose(c_run, (0, 2, 1))[..., None]
            + o_blk * jnp.transpose(c_blk, (0, 2, 1))[..., None]
        )
        # rotate k/v one hop around the ring (ICI neighbor exchange)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, o_new, lse_new), None

    # scan-carry inits must be device-varying over every manual axis the
    # outputs vary over (the ring axis via the causal masks, PLUS any
    # enclosing manual region, e.g. the pp pipeline's stage shard_map).
    # Deriving them arithmetically from q inherits the full varying set,
    # whatever it is — no axis list to keep in sync; XLA folds the *0 away.
    q32 = jnp.transpose(q, (0, 2, 1, 3)).astype(jnp.float32)  # [B,H,Sblk,D]
    lse0 = q32[..., 0] * 0 + NEG_INF  # [B,H,Sblk]
    o0 = q.astype(jnp.float32) * 0
    (k_f, v_f, out, lse), _ = jax.lax.scan(
        step, (k, v, o0, lse0), jnp.arange(n)
    )
    return out.astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh | None = None,
    causal: bool = True, axis_name: str = "sp",
    impl: str = "flash", interpret: bool | None = None,
    check_vma: bool = True,
) -> jax.Array:
    """Global q [B, S, H, D], k/v [B, S, KV, D] with S sharded over
    ``axis_name``.

    Manual only over ``axis_name``: batch/head shardings (dp/tp) stay
    visible to XLA inside the region, so ring attention composes with the
    other mesh axes. ``mesh=None`` uses the ambient mesh (e.g. the train
    step's ``with mesh:`` scope) — how the model's ``attn_impl="ring"``
    path reaches it from inside jit.
    """
    spec = P(None, axis_name, None, None)
    fn = jax.shard_map(
        partial(ring_attention, axis_name=axis_name, causal=causal,
                impl=impl, interpret=interpret),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={axis_name},
        # True (default) keeps the varying-axes type checker on, which the
        # compiled kernel path satisfies (pallas out_shape structs carry
        # the inputs' vma). interpret=True kernels evaluate through the
        # HLO interpreter, which chokes on vma-typed avals — kernel-path
        # tests on CPU pass check_vma=False with a fully-manual (sp-only)
        # mesh (partial-auto meshes require the checker on).
        check_vma=check_vma,
    )
    return fn(q, k, v)
