"""Ring attention: exact causal attention with the sequence sharded over the
``sp`` mesh axis.

Each device holds a contiguous sequence shard of q/k/v. K/V blocks rotate
around the ring via ``lax.ppermute`` (one ICI hop per step) while every
device accumulates its queries' attention over each visiting block with
online-softmax (log-sum-exp) merging — the sequence-parallel analogue of
flash attention's k-loop. Memory per device stays O(S/sp · d); the full
[S, S] score matrix never exists anywhere.

Causality works on block indices: a k/v block that started on ring rank
``src`` covers global positions [src·Sblk, (src+1)·Sblk); my queries at rank
``r`` attend fully to blocks with src < r, causally within src == r, and not
at all to src > r (those steps still run — SPMD needs uniform control flow —
but are fully masked).

Designed for use inside ``shard_map`` (see :func:`ring_attention_sharded`).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, scale, mask):
    """Partial attention of q against one k/v block.

    q [B,Sq,H,D]; k/v [B,Sk,KV,D] with KV | H (GQA: k/v stay UNexpanded —
    the ring rotates the small KV blocks, H/KV× less ICI traffic per hop —
    and the grouped einsums below broadcast them across each kv head's
    query group); mask [Sq,Sk] bool or None.
    Returns (m [B,H,Sq,1], l, acc [B,Sq,H,D]) for LSE merging.
    """
    B, Sq, H, D = q.shape
    Sk, KV = k.shape[1], k.shape[2]
    G = H // KV  # query heads per kv head; 1 for MHA
    qg = q.reshape(B, Sq, KV, G, D)
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qg, k).astype(jnp.float32) * scale
    logits = logits.reshape(B, H, Sq, Sk)
    if mask is not None:
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)  # [B,H,Sq,1]
    m_safe = jnp.where(m == NEG_INF, 0.0, m)
    p = jnp.exp(jnp.where(logits == NEG_INF, NEG_INF, logits - m_safe))
    l = jnp.sum(p, axis=-1, keepdims=True)
    pg = p.astype(q.dtype).reshape(B, KV, G, Sq, Sk)
    acc = (
        jnp.einsum("bkgqs,bskd->bqkgd", pg, v)
        .reshape(B, Sq, H, D)
        .astype(jnp.float32)
    )
    return m, l, acc


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    axis_name: str = "sp", causal: bool = True,
) -> jax.Array:
    """Per-shard q [B, Sblk, H, D], k/v [B, Sblk, KV, D] (KV | H; GQA kv
    blocks ride the ring unexpanded) -> per-shard out [B, Sblk, H, D].
    Call inside shard_map with the sequence dim sharded over ``axis_name``."""
    B, Sblk, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    n = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)

    causal_mask = jnp.tril(jnp.ones((Sblk, Sblk), jnp.bool_))
    perm = [(i, (i + 1) % n) for i in range(n)]  # send k/v to the next rank

    def step(carry, step_idx):
        k_cur, v_cur, m_run, l_run, acc_run = carry
        # the block on my device at step s originated at rank (rank - s) mod n
        src = (rank - step_idx) % n
        if causal:
            # one attend with a mask built from traced scalars: past blocks
            # all-visible, the self block lower-triangular, future blocks
            # fully masked (the step still runs — SPMD needs uniform control
            # flow). This halves the FLOPs vs attending twice and selecting.
            mask = (src < rank) | ((src == rank) & causal_mask)
            m_blk, l_blk, acc_blk = _block_attend(q, k_cur, v_cur, scale, mask)
        else:
            m_blk, l_blk, acc_blk = _block_attend(q, k_cur, v_cur, scale, None)
        # LSE merge
        m_new = jnp.maximum(m_run, m_blk)
        m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
        c_run = jnp.where(m_run == NEG_INF, 0.0, jnp.exp(m_run - m_safe))
        c_blk = jnp.where(m_blk == NEG_INF, 0.0, jnp.exp(m_blk - m_safe))
        l_new = l_run * c_run + l_blk * c_blk
        # correction factors are [B,H,Sq,1]; acc is [B,Sq,H,D]
        c_run_t = jnp.transpose(c_run, (0, 2, 1, 3))
        c_blk_t = jnp.transpose(c_blk, (0, 2, 1, 3))
        acc_new = acc_run * c_run_t + acc_blk * c_blk_t
        # rotate k/v one hop around the ring (ICI neighbor exchange)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m_new, l_new, acc_new), None

    # scan-carry inits must be device-varying over every manual axis the
    # outputs vary over (the ring axis via the causal masks, PLUS any
    # enclosing manual region, e.g. the pp pipeline's stage shard_map).
    # Deriving them arithmetically from q inherits the full varying set,
    # whatever it is — no axis list to keep in sync; XLA folds the *0 away.
    q32 = jnp.transpose(q, (0, 2, 1, 3)).astype(jnp.float32)  # [B,H,Sblk,D]
    m0 = q32[..., :1] * 0 + NEG_INF
    l0 = q32[..., :1] * 0
    acc0 = q.astype(jnp.float32) * 0
    (k_f, v_f, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n)
    )
    l_t = jnp.transpose(l, (0, 2, 1, 3))  # [B,Sq,H,1]
    out = acc / jnp.maximum(l_t, 1e-30)
    return out.astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh | None = None,
    causal: bool = True, axis_name: str = "sp",
) -> jax.Array:
    """Global q [B, S, H, D], k/v [B, S, KV, D] with S sharded over
    ``axis_name``.

    Manual only over ``axis_name``: batch/head shardings (dp/tp) stay
    visible to XLA inside the region, so ring attention composes with the
    other mesh axes. ``mesh=None`` uses the ambient mesh (e.g. the train
    step's ``with mesh:`` scope) — how the model's ``attn_impl="ring"``
    path reaches it from inside jit.
    """
    spec = P(None, axis_name, None, None)
    fn = jax.shard_map(
        partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={axis_name},
    )
    return fn(q, k, v)
